"""Volume controllers: PV binder, attach/detach, PVC/PV protection,
ephemeral volumes.

Reference: pkg/controller/volume/
  persistentvolume/pv_controller.go - bind unbound PVCs to matching PVs
    (capacity / accessModes / storageClass / selector), dynamically
    provision for provisionable classes (honoring the scheduler's
    volume.kubernetes.io/selected-node annotation for WaitForFirstConsumer),
    reclaim released PVs per persistentVolumeReclaimPolicy
  attachdetach/attach_detach_controller.go - desired-vs-actual attachment
    reconciliation; we materialize VolumeAttachment objects and the node
    status.volumesAttached list
  pvcprotection/pvc_protection_controller.go - kubernetes.io/pvc-protection
    finalizer: added to live PVCs, removed once no non-terminal pod uses a
    terminating PVC (store finalizer semantics: kv.py delete/update)
  pvprotection/pv_protection_controller.go - same for PVs vs bound claims
  ephemeral/controller.go - create the <pod>-<volume> PVC for generic
    ephemeral volumes, owned by the pod
"""

from __future__ import annotations

import logging

from ..api import meta
from ..api.labels import selector_from_dict
from ..api.meta import Obj
from ..api.quantity import parse_quantity
from ..client.clientset import (
    NODES, PODS, PVCS, PVS, STORAGECLASSES, VOLUMEATTACHMENTS,
)
from ..store import kv
from .base import Controller, owner_ref, split_key

logger = logging.getLogger(__name__)

PVC_PROTECTION_FINALIZER = "kubernetes.io/pvc-protection"
PV_PROTECTION_FINALIZER = "kubernetes.io/pv-protection"
SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"
NO_PROVISIONER = "kubernetes.io/no-provisioner"


def _pvc_names(pod: Obj) -> list[str]:
    out = []
    for v in (pod.get("spec") or {}).get("volumes") or ():
        claim = (v.get("persistentVolumeClaim") or {}).get("claimName")
        if claim:
            out.append(claim)
    return out


def _capacity(obj: Obj, field: str) -> int:
    spec = obj.get("spec") or {}
    if field == "pvc":
        q = ((spec.get("resources") or {}).get("requests") or {}).get(
            "storage", "0")
    else:
        q = (spec.get("capacity") or {}).get("storage", "0")
    return int(parse_quantity(q))


def pv_matches_claim(pv: Obj, pvc: Obj) -> bool:
    """find_matching_volume (pv_controller): class, size, accessModes,
    selector, and not already claimed by someone else."""
    pv_spec = pv.get("spec") or {}
    pvc_spec = pvc.get("spec") or {}
    ref = pv_spec.get("claimRef")
    if ref and (ref.get("namespace") != meta.namespace(pvc)
                or ref.get("name") != meta.name(pvc)):
        return False
    if (pv_spec.get("storageClassName") or "") != (
            pvc_spec.get("storageClassName") or ""):
        return False
    want_modes = set(pvc_spec.get("accessModes") or ())
    if not want_modes.issubset(set(pv_spec.get("accessModes") or ())):
        return False
    if _capacity(pv, "pv") < _capacity(pvc, "pvc"):
        return False
    sel = pvc_spec.get("selector")
    if sel and not selector_from_dict(sel).matches(
            meta.labels(pv)):
        return False
    return True


class PersistentVolumeController(Controller):
    """The binder (pv_controller.go syncClaim/syncVolume)."""

    name = "persistentvolume-binder"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pvc_informer = factory.informer(PVCS)
        self.pv_informer = factory.informer(PVS)
        self.sc_informer = factory.informer(STORAGECLASSES)
        self.pvc_informer.add_event_handler(self._on_claim)
        self.pv_informer.add_event_handler(self._on_volume)

    def _on_volume(self, type_, pv: Obj, old: Obj | None) -> None:
        self.enqueue_key("volume:" + meta.name(pv))
        # a PV appearing or becoming Available can satisfy waiting claims;
        # with no periodic resync, this event is their only wake-up
        if not (pv.get("spec") or {}).get("claimRef"):
            for pvc in self.pvc_informer.list(None):
                if not (pvc.get("spec") or {}).get("volumeName"):
                    self.enqueue_key("claim:" + meta.namespaced_name(pvc))

    def _on_claim(self, type_, pvc: Obj, old: Obj | None) -> None:
        self.enqueue_key("claim:" + meta.namespaced_name(pvc))
        # a (re)moved claim must re-sync its bound volume for reclaim
        for o in (pvc, old):
            vol = ((o or {}).get("spec") or {}).get("volumeName")
            if vol:
                self.enqueue_key("volume:" + vol)

    def sync(self, key: str) -> None:
        kind, _, rest = key.partition(":")
        if kind == "claim":
            self._sync_claim(rest)
        else:
            self._sync_volume(rest)

    # -- claims ----------------------------------------------------------

    def _sync_claim(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.pvc_informer.get(ns, name)
        if pvc is None or meta.deletion_timestamp(pvc):
            return
        spec = pvc.get("spec") or {}
        if spec.get("volumeName"):
            self._ensure_bound_status(pvc)
            return
        cls_name = spec.get("storageClassName")
        cls = self.sc_informer.get("", cls_name) if cls_name else None
        delayed = (cls or {}).get("volumeBindingMode") == "WaitForFirstConsumer"
        selected = (pvc["metadata"].get("annotations") or {}).get(
            SELECTED_NODE_ANNOTATION)
        if delayed and not selected:
            return  # scheduler decides first (volume binding plugin)
        # static match first
        for pv in self.pv_informer.list(None):
            if meta.deletion_timestamp(pv):
                continue
            if ((pv.get("status") or {}).get("phase") in (None, "Available",
                                                          "Pending")
                    and pv_matches_claim(pv, pvc)):
                self._bind(pvc, pv)
                return
        # dynamic provisioning
        provisioner = (cls or {}).get("provisioner")
        if provisioner and provisioner != NO_PROVISIONER:
            self._provision(pvc, cls, selected)

    def _bind(self, pvc: Obj, pv: Obj) -> None:
        # the claimRef write re-validates inside the CAS closure: the
        # informer view used for matching may lag a concurrent bind of the
        # same PV to another claim (two sync workers, one Available PV)
        won = {"bind": False}

        def set_claim_ref(o):
            won["bind"] = False  # re-evaluated on every CAS retry
            ref = (o.get("spec") or {}).get("claimRef")
            if ref and (ref.get("namespace") != meta.namespace(pvc)
                        or ref.get("name") != meta.name(pvc)):
                return o  # lost the race; claim resyncs to another PV
            o.setdefault("spec", {})["claimRef"] = {
                "namespace": meta.namespace(pvc), "name": meta.name(pvc),
                "uid": meta.uid(pvc)}
            o.setdefault("status", {})["phase"] = "Bound"
            won["bind"] = True
            return o

        def set_volume(o):
            o.setdefault("spec", {})["volumeName"] = meta.name(pv)
            o.setdefault("status", {})["phase"] = "Bound"
            return o
        try:
            self.client.guaranteed_update(PVS, "", meta.name(pv),
                                          set_claim_ref)
            if won["bind"]:
                self.client.guaranteed_update(PVCS, meta.namespace(pvc),
                                              meta.name(pvc), set_volume)
            else:
                # lost the PV to a racing claim: try again for another PV
                self.enqueue_key("claim:" + meta.namespaced_name(pvc))
        except kv.NotFoundError:
            pass

    def _provision(self, pvc: Obj, cls: Obj, selected_node: str | None) -> None:
        pv_name = f"pvc-{meta.uid(pvc)}"
        if self.pv_informer.get("", pv_name) is not None:
            return
        pv = meta.new_object("PersistentVolume", pv_name, None)
        pv["metadata"]["annotations"] = {
            "pv.kubernetes.io/provisioned-by": cls.get("provisioner")}
        pv["spec"] = {
            "capacity": {"storage": ((pvc.get("spec") or {}).get("resources")
                                     or {}).get("requests", {}).get("storage",
                                                                    "1Gi")},
            "accessModes": list((pvc.get("spec") or {}).get("accessModes")
                                or ["ReadWriteOnce"]),
            "storageClassName": (pvc.get("spec") or {}).get(
                "storageClassName", ""),
            "persistentVolumeReclaimPolicy": cls.get("reclaimPolicy",
                                                     "Delete"),
            "hostPath": {"path": f"/var/lib/k8s-tpu/{pv_name}"},
        }
        if selected_node:
            pv["spec"]["nodeAffinity"] = {"required": {"nodeSelectorTerms": [
                {"matchExpressions": [{"key": "kubernetes.io/hostname",
                                       "operator": "In",
                                       "values": [selected_node]}]}]}}
        try:
            self.client.create(PVS, pv)
        except kv.AlreadyExistsError:
            pass
        self._bind(pvc, pv)

    def _ensure_bound_status(self, pvc: Obj) -> None:
        if (pvc.get("status") or {}).get("phase") == "Bound":
            return
        pv = self.pv_informer.get("", (pvc.get("spec") or {}).get("volumeName"))
        if pv is None:
            return

        def patch(o):
            o.setdefault("status", {})["phase"] = "Bound"
            return o
        try:
            self.client.guaranteed_update(PVCS, meta.namespace(pvc),
                                          meta.name(pvc), patch)
        except kv.NotFoundError:
            pass

    # -- volumes (reclaim) ------------------------------------------------

    def _sync_volume(self, name: str) -> None:
        pv = self.pv_informer.get("", name)
        if pv is None or meta.deletion_timestamp(pv):
            return
        ref = (pv.get("spec") or {}).get("claimRef")
        if not ref:
            if (pv.get("status") or {}).get("phase") not in ("Available",):
                # the closure re-checks against the CURRENT object: the
                # informer view may lag a concurrent bind (claimRef write)
                self._set_phase(name, "Available", unless_claimed=True)
            return
        pvc = self.pvc_informer.get(ref.get("namespace", ""), ref["name"])
        if pvc is not None and (not meta.uid(pvc) or not ref.get("uid")
                                or meta.uid(pvc) == ref["uid"]):
            return  # claim alive: stays Bound
        # claim is gone: phase -> Released first (pv_controller.go
        # syncVolume), which also tells pv-protection the PV is reclaimable
        if (pv.get("status") or {}).get("phase") != "Released":
            self._set_phase(name, "Released")
            return  # the MODIFIED event re-enters with phase Released
        policy = (pv.get("spec") or {}).get("persistentVolumeReclaimPolicy",
                                            "Retain")
        if policy == "Delete":
            try:
                self.client.delete(PVS, "", name)
            except kv.NotFoundError:
                pass
        elif policy == "Recycle":
            def scrub(o):
                o["spec"].pop("claimRef", None)
                o.setdefault("status", {})["phase"] = "Available"
                return o
            try:
                self.client.guaranteed_update(PVS, "", name, scrub)
            except kv.NotFoundError:
                pass
        # Retain: stays Released until an admin intervenes

    def _set_phase(self, name: str, phase: str,
                   unless_claimed: bool = False) -> None:
        def patch(o):
            if unless_claimed and (o.get("spec") or {}).get("claimRef"):
                return o
            o.setdefault("status", {})["phase"] = phase
            return o
        try:
            self.client.guaranteed_update(PVS, "", name, patch)
        except kv.NotFoundError:
            pass


class PVCProtectionController(Controller):
    """pvcprotection: finalizer lifecycle (pvc_protection_controller.go)."""

    name = "pvc-protection"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pvc_informer = factory.informer(PVCS)
        self.pod_informer = factory.informer(PODS)
        self.pvc_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))
        self.pod_informer.add_event_handler(self._on_pod)

    def _on_pod(self, type_, pod, old) -> None:
        for claim in _pvc_names(pod):
            self.enqueue_key(f"{meta.namespace(pod)}/{claim}")

    def _in_use(self, ns: str, claim: str) -> bool:
        for p in self.pod_informer.list(ns):
            if meta.pod_is_terminal(p):
                continue
            if claim in _pvc_names(p):
                return True
        return False

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.pvc_informer.get(ns, name)
        if pvc is None:
            return
        fins = pvc["metadata"].get("finalizers") or []
        deleting = bool(meta.deletion_timestamp(pvc))
        if not deleting and PVC_PROTECTION_FINALIZER not in fins:
            def add(o):
                f = o["metadata"].setdefault("finalizers", [])
                if PVC_PROTECTION_FINALIZER not in f:
                    f.append(PVC_PROTECTION_FINALIZER)
                return o
            try:
                self.client.guaranteed_update(PVCS, ns, name, add)
            except kv.NotFoundError:
                pass
        elif deleting and PVC_PROTECTION_FINALIZER in fins \
                and not self._in_use(ns, name):
            def remove(o):
                f = o["metadata"].get("finalizers") or []
                o["metadata"]["finalizers"] = [
                    x for x in f if x != PVC_PROTECTION_FINALIZER]
                return o
            try:
                self.client.guaranteed_update(PVCS, ns, name, remove)
            except kv.NotFoundError:
                pass


class PVProtectionController(Controller):
    """pvprotection (pv_protection_controller.go)."""

    name = "pv-protection"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pv_informer = factory.informer(PVS)
        self.pv_informer.add_event_handler(
            lambda t, obj, old: self.enqueue_key(meta.name(obj)))

    def sync(self, key: str) -> None:
        _, name = split_key(key)
        pv = self.pv_informer.get("", name)
        if pv is None:
            return
        fins = pv["metadata"].get("finalizers") or []
        deleting = bool(meta.deletion_timestamp(pv))
        # "in use" == phase Bound (pv_protection_controller.go)
        bound = (pv.get("status") or {}).get("phase") == "Bound"
        if not deleting and PV_PROTECTION_FINALIZER not in fins:
            def add(o):
                f = o["metadata"].setdefault("finalizers", [])
                if PV_PROTECTION_FINALIZER not in f:
                    f.append(PV_PROTECTION_FINALIZER)
                return o
            try:
                self.client.guaranteed_update(PVS, "", name, add)
            except kv.NotFoundError:
                pass
        elif deleting and PV_PROTECTION_FINALIZER in fins and not bound:
            def remove(o):
                f = o["metadata"].get("finalizers") or []
                o["metadata"]["finalizers"] = [
                    x for x in f if x != PV_PROTECTION_FINALIZER]
                return o
            try:
                self.client.guaranteed_update(PVS, "", name, remove)
            except kv.NotFoundError:
                pass


class AttachDetachController(Controller):
    """attachdetach: reconcile VolumeAttachment objects + node status
    (attach_detach_controller.go reconciler, much simplified: desired =
    {(node, pv) for scheduled pods with bound PVCs})."""

    name = "attachdetach"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pod_informer = factory.informer(PODS)
        self.pvc_informer = factory.informer(PVCS)
        self.va_informer = factory.informer(VOLUMEATTACHMENTS)
        self.node_informer = factory.informer(NODES)
        self.pod_informer.add_event_handler(self._on_pod)
        self.va_informer.add_event_handler(
            lambda t, obj, old: self.enqueue_key(
                (obj.get("spec") or {}).get("nodeName", "")))

    def _on_pod(self, type_, pod, old) -> None:
        node = meta.pod_node_name(pod) or (
            meta.pod_node_name(old) if old else "")
        if node and _pvc_names(pod):
            self.enqueue_key(node)

    def _desired_for_node(self, node: str) -> set[str]:
        want: set[str] = set()
        for p in self.pod_informer.list(None):
            if meta.pod_node_name(p) != node or meta.pod_is_terminal(p):
                continue
            for claim in _pvc_names(p):
                pvc = self.pvc_informer.get(meta.namespace(p), claim)
                vol = (pvc or {}).get("spec", {}).get("volumeName")
                if vol:
                    want.add(vol)
        return want

    def sync(self, key: str) -> None:
        _, node = split_key(key)
        if not node:
            return
        want = self._desired_for_node(node)
        have: dict[str, Obj] = {}
        for va in self.va_informer.list(None):
            spec = va.get("spec") or {}
            if spec.get("nodeName") == node:
                have[(spec.get("source") or {}).get("persistentVolumeName",
                                                    "")] = va
        for vol in want - set(have):
            # reference names are csi-<sha256(attacher+vol+node)> BECAUSE
            # concatenation is ambiguous: (node "a", vol "b-c") and
            # (node "a-b", vol "c") both make "a-b-c".  Always digest.
            import hashlib
            va_name = "va-" + hashlib.sha256(
                f"{node}/{vol}".encode()).hexdigest()[:32]
            va = meta.new_object("VolumeAttachment", va_name, None)
            va["spec"] = {"attacher": "tpu.kubernetes.io/host-attacher",
                          "nodeName": node,
                          "source": {"persistentVolumeName": vol}}
            va["status"] = {"attached": True}
            try:
                self.client.create(VOLUMEATTACHMENTS, va)
            except kv.AlreadyExistsError:
                pass
        for vol, va in have.items():
            if vol not in want:
                try:
                    self.client.delete(VOLUMEATTACHMENTS, "", meta.name(va))
                except kv.NotFoundError:
                    pass
        self._update_node_status(node, sorted(want))

    def _update_node_status(self, node: str, vols: list[str]) -> None:
        n = self.node_informer.get("", node)
        if n is None:
            return
        attached = [{"name": v, "devicePath": ""} for v in vols]
        if (n.get("status") or {}).get("volumesAttached") == attached:
            return

        def patch(o):
            o.setdefault("status", {})["volumesAttached"] = attached
            return o
        try:
            self.client.guaranteed_update(NODES, "", node, patch)
        except kv.NotFoundError:
            pass


class EphemeralVolumeController(Controller):
    """ephemeral: create PVCs for generic ephemeral volumes
    (pkg/controller/volume/ephemeral/controller.go)."""

    name = "ephemeral-volume"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pod_informer = factory.informer(PODS)
        self.pvc_informer = factory.informer(PVCS)
        self.pod_informer.add_event_handler(
            lambda t, obj, old: self.enqueue(obj))

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pod = self.pod_informer.get(ns, name)
        if pod is None or meta.pod_is_terminal(pod):
            return
        for v in (pod.get("spec") or {}).get("volumes") or ():
            eph = v.get("ephemeral")
            if not eph:
                continue
            pvc_name = f"{name}-{v.get('name', 'vol')}"
            if self.pvc_informer.get(ns, pvc_name) is not None:
                continue
            tmpl = eph.get("volumeClaimTemplate") or {}
            pvc = meta.new_object("PersistentVolumeClaim", pvc_name, ns)
            tmpl_meta = tmpl.get("metadata") or {}
            if tmpl_meta.get("labels"):
                pvc["metadata"]["labels"] = dict(tmpl_meta["labels"])
            pvc["metadata"]["ownerReferences"] = [owner_ref(pod, "Pod")]
            pvc["spec"] = meta.deep_copy(tmpl.get("spec") or {
                "accessModes": ["ReadWriteOnce"],
                "resources": {"requests": {"storage": "1Gi"}}})
            try:
                self.client.create(PVCS, pvc)
            except kv.AlreadyExistsError:
                pass


class VolumeExpandController(Controller):
    """PVC expansion (pkg/controller/volume/expand/expand_controller.go):
    a bound claim whose requested storage grew past its status capacity
    gets its PV resized — gated on the StorageClass declaring
    `allowVolumeExpansion: true`, like the reference.  The simulated
    volume plane "resizes" instantly: PV capacity and PVC
    status.capacity follow the new request and the
    FileSystemResizePending dance collapses to one status write."""

    name = "persistentvolume-expander"

    def __init__(self, client, factory):
        super().__init__(client, factory)
        self.pvc_informer = factory.informer(PVCS)
        self.sc_informer = factory.informer(STORAGECLASSES)
        self.pvc_informer.add_event_handler(
            lambda t, pvc, old: self.enqueue(pvc))
        # allowVolumeExpansion flipping true must wake claims that were
        # rejected at the gate: there is no periodic resync backstop
        self.sc_informer.add_event_handler(self._on_class)

    def _on_class(self, type_, sc: Obj, old: Obj | None) -> None:
        name = meta.name(sc)
        for pvc in self.pvc_informer.list(None):
            if (pvc.get("spec") or {}).get("storageClassName") == name:
                self.enqueue(pvc)

    def _expandable(self, pvc: Obj) -> bool:
        sc_name = (pvc.get("spec") or {}).get("storageClassName")
        if not sc_name:
            return False
        sc = self.sc_informer.get("", sc_name)
        return bool(sc and sc.get("allowVolumeExpansion"))

    def sync(self, key: str) -> None:
        ns, name = split_key(key)
        pvc = self.pvc_informer.get(ns, name)
        if pvc is None or meta.deletion_timestamp(pvc):
            return
        spec = pvc.get("spec") or {}
        vol_name = spec.get("volumeName")
        status = pvc.get("status") or {}
        if not vol_name or status.get("phase") != "Bound":
            return
        pv = self.factory.informer(PVS).get("", vol_name)
        if pv is None:
            try:
                pv = self.client.get(PVS, "", vol_name)
            except kv.NotFoundError:
                return
        # compare against the VOLUME's capacity, never pvc.status (the
        # binder doesn't maintain status.capacity; a status-derived
        # `have` of 0 would shrink every statically-bound oversized PV
        # down to its claim's request on first sync)
        want = _capacity(pvc, "pvc")
        have = _capacity(pv, "pv")
        if want <= have:
            # catch up a stale status.capacity: the PV write and the
            # claim-status write are two transactions, and a crash or
            # transient failure between them must converge on retry
            pv_size = ((pv.get("spec") or {}).get("capacity")
                       or {}).get("storage")
            tracked = (status.get("capacity") or {}).get("storage")
            if tracked is not None and pv_size is not None \
                    and tracked != pv_size:
                def catch_up(c: Obj) -> Obj:
                    c.setdefault("status", {}).setdefault(
                        "capacity", {})["storage"] = pv_size
                    return c
                try:
                    self.client.guaranteed_update(PVCS, ns, name,
                                                  catch_up)
                except kv.NotFoundError:
                    pass
            return
        if not self._expandable(pvc):
            return  # reference: rejected unless the class allows it
        new_size = (spec.get("resources") or {})["requests"]["storage"]

        def grow_pv(pv: Obj) -> Obj:
            pv.setdefault("spec", {}).setdefault(
                "capacity", {})["storage"] = new_size
            return pv

        def grow_claim_status(c: Obj) -> Obj:
            c.setdefault("status", {}).setdefault(
                "capacity", {})["storage"] = new_size
            return c
        try:
            self.client.guaranteed_update(PVS, "", vol_name, grow_pv)
            self.client.guaranteed_update(PVCS, ns, name,
                                          grow_claim_status)
        except kv.NotFoundError:
            return
        self.client.create_event(pvc, "VolumeResizeSuccessful",
                                 f"expanded to {new_size}")
