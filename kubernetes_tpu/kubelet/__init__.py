"""Node agent: hollow kubelet + fake CRI (reference: pkg/kubelet, kubemark)."""

from .cri import FakeRuntimeService  # noqa: F401
from .hollow import HollowKubelet, start_hollow_nodes  # noqa: F401
from .server import KubeletServer  # noqa: F401
