"""Checkpoint manager: atomic, checksummed, file-backed state.

Reference: pkg/kubelet/checkpointmanager — device-manager/cpu-manager
allocation state survives kubelet restarts via checkpoints written
atomically (tmp file + rename) with a checksum guarding torn writes;
corrupt checkpoints surface as errors, not silent garbage.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Any, List, Optional


class CorruptCheckpointError(Exception):
    pass


def _checksum(payload: str) -> str:
    return hashlib.sha256(payload.encode()).hexdigest()


class CheckpointManager:
    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, name: str) -> str:
        if "/" in name or name.startswith("."):
            raise ValueError("invalid checkpoint name %r" % name)
        return os.path.join(self.directory, name)

    def create_checkpoint(self, name: str, data: Any) -> None:
        payload = json.dumps(data, sort_keys=True)
        doc = json.dumps({"data": payload, "checksum": _checksum(payload)})
        path = self._path(name)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "w") as f:
                f.write(doc)
                f.flush()
                os.fsync(f.fileno())
            os.rename(tmp, path)  # atomic on POSIX

    def get_checkpoint(self, name: str) -> Any:
        path = self._path(name)
        with self._lock:
            try:
                with open(path) as f:
                    doc = json.load(f)
            except FileNotFoundError:
                raise KeyError(name)
            except (json.JSONDecodeError, ValueError):
                raise CorruptCheckpointError(name)
        payload = doc.get("data")
        if payload is None or doc.get("checksum") != _checksum(payload):
            raise CorruptCheckpointError(name)
        return json.loads(payload)

    def remove_checkpoint(self, name: str) -> None:
        with self._lock:
            try:
                os.remove(self._path(name))
            except FileNotFoundError:
                pass

    def list_checkpoints(self) -> List[str]:
        with self._lock:
            return sorted(n for n in os.listdir(self.directory)
                          if not n.endswith(".tmp"))
