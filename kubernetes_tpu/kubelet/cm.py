"""Container manager: CPU, memory, device, and topology managers.

Reference: pkg/kubelet/cm/
  cpumanager/policy_static.go   - static policy: Guaranteed pods with
      integer CPU requests get exclusive cores carved from the shared pool;
      state checkpointed (cpumanager/state/state_checkpoint.go)
  memorymanager/policy_static.go - static policy: Guaranteed pods reserve
      memory from per-NUMA banks
  devicemanager/manager.go      - device plugin registry: plugins advertise
      lists of device IDs per resource name; allocations are checkpointed
      (devicemanager/checkpoint/checkpoint.go)
  topologymanager/manager.go    - merges TopologyHints (NUMA affinity
      bitmasks) from the providers under a policy (none/best-effort/
      restricted/single-numa-node); admission fails a pod whose merged hint
      is infeasible under `restricted`/`single-numa-node`

TPU note: the device manager is the seam where TPU chips surface as a
scalar resource (google.com/tpu) with NUMA-aware topology hints, exactly
like the reference's GPU plugins; the schedulable resource flows through
NodeResourcesFit's scalar slots (ops/flatten.py scalar_vocab).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field

from ..api.quantity import parse_quantity
from .checkpoint import CheckpointManager
from .qos import GUARANTEED, pod_qos

logger = logging.getLogger(__name__)

POLICY_NONE = "none"
POLICY_STATIC = "static"

TOPOLOGY_NONE = "none"
TOPOLOGY_BEST_EFFORT = "best-effort"
TOPOLOGY_RESTRICTED = "restricted"
TOPOLOGY_SINGLE_NUMA = "single-numa-node"


class AdmissionError(Exception):
    """Pod rejected by a resource manager (kubelet admission failure)."""


def _pod_cpu_request_milli(pod: dict) -> int:
    total = 0
    for c in (pod.get("spec") or {}).get("containers") or ():
        req = ((c.get("resources") or {}).get("requests") or {})
        total += int(parse_quantity(req.get("cpu", "0")) * 1000)
    return total


def _pod_memory_request(pod: dict) -> int:
    total = 0
    for c in (pod.get("spec") or {}).get("containers") or ():
        req = ((c.get("resources") or {}).get("requests") or {})
        total += int(parse_quantity(req.get("memory", "0")))
    return total


# --- topology hints (topologymanager/bitmask) ------------------------------

@dataclass(frozen=True)
class TopologyHint:
    """NUMA affinity bitmask + whether it's the provider's preferred one."""

    numa_mask: int     # bit i set = NUMA node i acceptable
    preferred: bool


def merge_hints(provider_hints: list[list[TopologyHint]],
                num_numa: int) -> TopologyHint:
    """topologymanager policy.go mergeProvidersHints: cross-product AND of
    masks, narrowest winning mask preferred."""
    full = (1 << num_numa) - 1
    best: TopologyHint | None = None
    stack = [(full, True, 0)]
    while stack:
        mask, preferred, i = stack.pop()
        if i == len(provider_hints):
            if mask != 0:
                cand = TopologyHint(mask, preferred)
                if best is None or _hint_better(cand, best):
                    best = cand
            continue
        hints = provider_hints[i] or [TopologyHint(full, True)]
        for h in hints:
            stack.append((mask & h.numa_mask, preferred and h.preferred,
                          i + 1))
    return best or TopologyHint(0, False)


def _hint_better(a: TopologyHint, b: TopologyHint) -> bool:
    if a.preferred != b.preferred:
        return a.preferred
    return bin(a.numa_mask).count("1") < bin(b.numa_mask).count("1")


class TopologyManager:
    """topologymanager/manager.go — admit pods by merged NUMA hint."""

    def __init__(self, policy: str = TOPOLOGY_NONE, num_numa: int = 1):
        self.policy = policy
        self.num_numa = num_numa
        self.pod_hints: dict[str, TopologyHint] = {}

    def admit(self, pod_uid: str,
              provider_hints: list[list[TopologyHint]]) -> TopologyHint:
        merged = merge_hints(provider_hints, self.num_numa)
        if self.policy == TOPOLOGY_NONE:
            self.pod_hints[pod_uid] = merged
            return merged
        if merged.numa_mask == 0:
            raise AdmissionError("TopologyAffinityError: no feasible NUMA "
                                 "assignment")
        if self.policy == TOPOLOGY_RESTRICTED and not merged.preferred:
            raise AdmissionError("TopologyAffinityError: merged hint not "
                                 "preferred under restricted policy")
        if (self.policy == TOPOLOGY_SINGLE_NUMA
                and bin(merged.numa_mask).count("1") != 1):
            raise AdmissionError("TopologyAffinityError: spans multiple NUMA "
                                 "nodes under single-numa-node policy")
        self.pod_hints[pod_uid] = merged
        return merged

    def remove(self, pod_uid: str) -> None:
        self.pod_hints.pop(pod_uid, None)


# --- CPU manager -----------------------------------------------------------

class CPUManager:
    """cpumanager static policy over a flat core list (NUMA-striped)."""

    CHECKPOINT = "cpu_manager_state"

    def __init__(self, num_cpus: int = 8, policy: str = POLICY_STATIC,
                 reserved: int = 1, num_numa: int = 1,
                 checkpoints: CheckpointManager | None = None):
        self.policy = policy
        self.num_cpus = num_cpus
        self.num_numa = max(1, num_numa)
        self.reserved = reserved
        self.checkpoints = checkpoints
        self._lock = threading.Lock()
        # pod uid -> sorted list of exclusive cores
        self.assignments: dict[str, list[int]] = {}
        self._restore()

    def _numa_of(self, cpu: int) -> int:
        return cpu * self.num_numa // self.num_cpus

    def shared_pool(self) -> list[int]:
        taken = {c for cores in self.assignments.values() for c in cores}
        return [c for c in range(self.num_cpus)
                if c not in taken and c >= self.reserved]

    def hints(self, pod: dict) -> list[TopologyHint]:
        """Topology hints: one per NUMA node that could host the request."""
        if not self._wants_exclusive(pod):
            return []
        need = _pod_cpu_request_milli(pod) // 1000
        pool = self.shared_pool()
        out = []
        for numa in range(self.num_numa):
            avail = sum(1 for c in pool if self._numa_of(c) == numa)
            if avail >= need:
                out.append(TopologyHint(1 << numa, True))
        if not out and len(pool) >= need:
            out.append(TopologyHint((1 << self.num_numa) - 1, False))
        return out

    def _wants_exclusive(self, pod: dict) -> bool:
        if self.policy != POLICY_STATIC or pod_qos(pod) != GUARANTEED:
            return False
        milli = _pod_cpu_request_milli(pod)
        return milli >= 1000 and milli % 1000 == 0

    def allocate(self, pod: dict, hint: TopologyHint | None = None) -> list[int]:
        """Admission-time allocation (policy_static.go Allocate)."""
        uid = (pod.get("metadata") or {}).get("uid", "")
        with self._lock:
            if uid in self.assignments:
                return self.assignments[uid]
            if not self._wants_exclusive(pod):
                return []
            need = _pod_cpu_request_milli(pod) // 1000
            pool = self.shared_pool()
            if hint is not None and hint.numa_mask:
                preferred = [c for c in pool
                             if (1 << self._numa_of(c)) & hint.numa_mask]
                pool = preferred + [c for c in pool if c not in preferred]
            if len(pool) < need:
                raise AdmissionError(
                    f"not enough exclusive CPUs: want {need}, "
                    f"free {len(pool)}")
            cores = sorted(pool[:need])
            self.assignments[uid] = cores
            self._persist()
            return cores

    def release(self, pod_uid: str) -> None:
        with self._lock:
            if self.assignments.pop(pod_uid, None) is not None:
                self._persist()

    def _persist(self) -> None:
        if self.checkpoints:
            self.checkpoints.create_checkpoint(
                self.CHECKPOINT, {"policy": self.policy,
                                  "assignments": self.assignments})

    def _restore(self) -> None:
        if not self.checkpoints:
            return
        try:
            data = self.checkpoints.get_checkpoint(self.CHECKPOINT)
        except Exception:
            return
        if data.get("policy") == self.policy:
            self.assignments = {k: list(v)
                                for k, v in data.get("assignments", {}).items()}


# --- memory manager --------------------------------------------------------

class MemoryManager:
    """memorymanager static policy over per-NUMA banks."""

    CHECKPOINT = "memory_manager_state"

    def __init__(self, numa_banks: list[int] | None = None,
                 policy: str = POLICY_STATIC,
                 checkpoints: CheckpointManager | None = None):
        self.policy = policy
        self.banks = list(numa_banks or [16 << 30])
        self.checkpoints = checkpoints
        self._lock = threading.Lock()
        # pod uid -> {numa_index: bytes}
        self.assignments: dict[str, dict[int, int]] = {}
        self._restore()

    def free_in(self, numa: int) -> int:
        used = sum(a.get(numa, 0) for a in self.assignments.values())
        return self.banks[numa] - used

    def hints(self, pod: dict) -> list[TopologyHint]:
        if self.policy != POLICY_STATIC or pod_qos(pod) != GUARANTEED:
            return []
        need = _pod_memory_request(pod)
        if need == 0:
            return []
        out = [TopologyHint(1 << i, True)
               for i in range(len(self.banks)) if self.free_in(i) >= need]
        if not out and sum(self.free_in(i)
                           for i in range(len(self.banks))) >= need:
            out.append(TopologyHint((1 << len(self.banks)) - 1, False))
        return out

    def allocate(self, pod: dict, hint: TopologyHint | None = None
                 ) -> dict[int, int]:
        uid = (pod.get("metadata") or {}).get("uid", "")
        with self._lock:
            if uid in self.assignments:
                return self.assignments[uid]
            if self.policy != POLICY_STATIC or pod_qos(pod) != GUARANTEED:
                return {}
            need = _pod_memory_request(pod)
            if need == 0:
                return {}
            order = range(len(self.banks))
            if hint is not None and hint.numa_mask:
                order = sorted(order,
                               key=lambda i: not ((1 << i) & hint.numa_mask))
            alloc: dict[int, int] = {}
            remaining = need
            for i in order:
                take = min(self.free_in(i), remaining)
                if take > 0:
                    alloc[i] = take
                    remaining -= take
                if remaining == 0:
                    break
            if remaining > 0:
                raise AdmissionError(
                    f"not enough memory: want {need}, short {remaining}")
            self.assignments[uid] = alloc
            self._persist()
            return alloc

    def release(self, pod_uid: str) -> None:
        with self._lock:
            if self.assignments.pop(pod_uid, None) is not None:
                self._persist()

    def _persist(self) -> None:
        if self.checkpoints:
            self.checkpoints.create_checkpoint(
                self.CHECKPOINT,
                {"assignments": {u: {str(k): v for k, v in a.items()}
                                 for u, a in self.assignments.items()}})

    def _restore(self) -> None:
        if not self.checkpoints:
            return
        try:
            data = self.checkpoints.get_checkpoint(self.CHECKPOINT)
        except Exception:
            return
        self.assignments = {
            u: {int(k): v for k, v in a.items()}
            for u, a in data.get("assignments", {}).items()}


# --- device manager --------------------------------------------------------

@dataclass
class DevicePlugin:
    """An in-process device plugin (devicemanager plugin registration).
    devices maps device-id -> NUMA node index."""

    resource_name: str
    devices: dict[str, int] = field(default_factory=dict)


class DeviceManager:
    """devicemanager/manager.go — registry + checkpointed allocations."""

    CHECKPOINT = "device_manager_state"

    def __init__(self, checkpoints: CheckpointManager | None = None):
        self.checkpoints = checkpoints
        self._lock = threading.Lock()
        self.plugins: dict[str, DevicePlugin] = {}
        # pod uid -> {resource: [device ids]}
        self.allocations: dict[str, dict[str, list[str]]] = {}
        self._restore()

    def register(self, plugin: DevicePlugin) -> None:
        with self._lock:
            self.plugins[plugin.resource_name] = plugin

    def allocatable(self) -> dict[str, int]:
        """resource -> device count (feeds node.status.allocatable)."""
        with self._lock:
            return {name: len(p.devices) for name, p in self.plugins.items()}

    def _requested(self, pod: dict) -> dict[str, int]:
        want: dict[str, int] = {}
        for c in (pod.get("spec") or {}).get("containers") or ():
            for name, q in ((c.get("resources") or {}).get("requests")
                            or {}).items():
                if name in self.plugins:
                    want[name] = want.get(name, 0) + int(parse_quantity(q))
        return want

    def _free(self, resource: str) -> list[str]:
        taken = {d for alloc in self.allocations.values()
                 for d in alloc.get(resource, ())}
        return [d for d in self.plugins[resource].devices if d not in taken]

    def hints(self, pod: dict) -> list[TopologyHint]:
        want = self._requested(pod)
        if not want:
            return []
        numa_sets: list[set[int]] = []
        for resource, n in want.items():
            free = self._free(resource)
            if len(free) < n:
                return [TopologyHint(0, False)]  # infeasible
            by_numa: dict[int, int] = {}
            for d in free:
                numa = self.plugins[resource].devices[d]
                by_numa[numa] = by_numa.get(numa, 0) + 1
            numa_sets.append({numa for numa, cnt in by_numa.items()
                              if cnt >= n})
        common = set.intersection(*numa_sets) if numa_sets else set()
        hints = [TopologyHint(1 << numa, True) for numa in sorted(common)]
        all_numa = {n for r in want for n in
                    self.plugins[r].devices.values()}
        if not hints and all_numa:
            mask = 0
            for n in all_numa:
                mask |= 1 << n
            hints.append(TopologyHint(mask, False))
        return hints

    def allocate(self, pod: dict, hint: TopologyHint | None = None
                 ) -> dict[str, list[str]]:
        uid = (pod.get("metadata") or {}).get("uid", "")
        with self._lock:
            if uid in self.allocations:
                return self.allocations[uid]
            want = self._requested(pod)
            if not want:
                return {}
            alloc: dict[str, list[str]] = {}
            for resource, n in want.items():
                free = self._free(resource)
                if hint is not None and hint.numa_mask:
                    devs = self.plugins[resource].devices
                    free.sort(key=lambda d: not ((1 << devs[d])
                                                 & hint.numa_mask))
                if len(free) < n:
                    raise AdmissionError(
                        f"insufficient {resource}: want {n}, free {len(free)}")
                alloc[resource] = free[:n]
            self.allocations[uid] = alloc
            self._persist()
            return alloc

    def release(self, pod_uid: str) -> None:
        with self._lock:
            if self.allocations.pop(pod_uid, None) is not None:
                self._persist()

    def _persist(self) -> None:
        if self.checkpoints:
            self.checkpoints.create_checkpoint(self.CHECKPOINT,
                                               {"allocations": self.allocations})

    def _restore(self) -> None:
        if not self.checkpoints:
            return
        try:
            data = self.checkpoints.get_checkpoint(self.CHECKPOINT)
        except Exception:
            return
        self.allocations = {u: {r: list(ds) for r, ds in a.items()}
                            for u, a in data.get("allocations", {}).items()}


# --- the container manager facade -----------------------------------------

class ContainerManager:
    """cm/container_manager_linux.go — owns the resource managers and runs
    the kubelet's resource-admission step (AdmitPod)."""

    def __init__(self, num_cpus: int = 8, memory_bytes: int = 16 << 30,
                 num_numa: int = 1, topology_policy: str = TOPOLOGY_NONE,
                 cpu_policy: str = POLICY_STATIC,
                 checkpoint_dir: str | None = None):
        ckpt = CheckpointManager(checkpoint_dir) if checkpoint_dir else None
        self.topology = TopologyManager(topology_policy, num_numa)
        self.cpu = CPUManager(num_cpus, cpu_policy, num_numa=num_numa,
                              checkpoints=ckpt)
        per_bank = memory_bytes // max(1, num_numa)
        self.memory = MemoryManager([per_bank] * max(1, num_numa),
                                    checkpoints=ckpt)
        self.devices = DeviceManager(checkpoints=ckpt)

    def admit_pod(self, pod: dict) -> None:
        """Admission: merge hints, then allocate under the merged hint.
        Raises AdmissionError (kubelet rejects the pod) on failure; partial
        allocations are rolled back."""
        uid = (pod.get("metadata") or {}).get("uid", "")
        hints = [self.cpu.hints(pod), self.memory.hints(pod),
                 self.devices.hints(pod)]
        merged = self.topology.admit(uid, hints)
        done = []
        try:
            for mgr in (self.cpu, self.memory, self.devices):
                mgr.allocate(pod, merged)
                done.append(mgr)
        except AdmissionError:
            for mgr in done:
                mgr.release(uid)
            self.topology.remove(uid)
            raise

    def release_pod(self, pod_uid: str) -> None:
        for mgr in (self.cpu, self.memory, self.devices):
            mgr.release(pod_uid)
        self.topology.remove(pod_uid)

    def reconcile(self, live_pod_uids: set[str]) -> None:
        """Release checkpoint-restored allocations whose pod no longer
        exists (cpumanager removeStaleState / devicemanager
        UpdateAllocatedDevices semantics on kubelet restart)."""
        known = (set(self.cpu.assignments) | set(self.memory.assignments)
                 | set(self.devices.allocations))
        for uid in known - live_pod_uids:
            logger.info("cm: releasing stale allocation for vanished pod %s",
                        uid)
            self.release_pod(uid)
