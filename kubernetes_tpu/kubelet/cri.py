"""Fake CRI runtime.

Reference: the CRI contract (staging/src/k8s.io/cri-api/pkg/apis/runtime/v1/
api.proto — RunPodSandbox/StopPodSandbox/RemovePodSandbox, CreateContainer/
StartContainer/StopContainer/RemoveContainer, ListContainers, PullImage...)
and the kubemark fake (pkg/kubelet/cri/remote/fake/): an in-process
implementation that tracks sandbox/container state machines without running
anything.  Method names follow the proto rpcs; this is the seam where a
real gRPC runtime (containerd) would plug in.
"""

from __future__ import annotations

import threading
import time
import uuid

SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"

CREATED = "CONTAINER_CREATED"
RUNNING = "CONTAINER_RUNNING"
EXITED = "CONTAINER_EXITED"


class FakeRuntimeService:
    def __init__(self, start_latency: float = 0.0):
        self._lock = threading.Lock()
        self._sandboxes: dict[str, dict] = {}
        self._containers: dict[str, dict] = {}
        self._images: set[str] = set()
        self.start_latency = start_latency

    # -- RuntimeService --------------------------------------------------

    def run_pod_sandbox(self, config: dict) -> str:
        if self.start_latency:
            time.sleep(self.start_latency)
        sid = uuid.uuid4().hex[:12]
        with self._lock:
            self._sandboxes[sid] = {"id": sid, "state": SANDBOX_READY,
                                    "config": config,
                                    "createdAt": time.time()}
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb:
                sb["state"] = SANDBOX_NOTREADY
            for c in self._containers.values():
                if c["sandboxId"] == sandbox_id and c["state"] == RUNNING:
                    c["state"] = EXITED
                    c["exitCode"] = 137

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            self._sandboxes.pop(sandbox_id, None)
            self._containers = {cid: c for cid, c in self._containers.items()
                                if c["sandboxId"] != sandbox_id}

    def create_container(self, sandbox_id: str, config: dict) -> str:
        cid = uuid.uuid4().hex[:12]
        with self._lock:
            self._containers[cid] = {
                "id": cid, "sandboxId": sandbox_id, "state": CREATED,
                "name": config.get("name", ""), "image": config.get("image", ""),
                "config": config, "createdAt": time.time(), "exitCode": None,
            }
        return cid

    def start_container(self, container_id: str) -> None:
        if self.start_latency:
            time.sleep(self.start_latency)
        with self._lock:
            c = self._containers[container_id]
            c["state"] = RUNNING
            c["startedAt"] = time.time()
            # hollow semantics: a container may declare it exits by itself
            run_for = (c["config"].get("annotations") or {}).get("hollow/run-seconds")
            if run_for is not None:
                c["exitAt"] = c["startedAt"] + float(run_for)
                c["plannedExitCode"] = int(
                    (c["config"].get("annotations") or {}).get("hollow/exit-code", 0))

    def stop_container(self, container_id: str, timeout: float = 0) -> None:
        with self._lock:
            c = self._containers.get(container_id)
            if c and c["state"] == RUNNING:
                c["state"] = EXITED
                c["exitCode"] = 137

    def remove_container(self, container_id: str) -> None:
        with self._lock:
            self._containers.pop(container_id, None)

    def list_containers(self, sandbox_id: str | None = None) -> list[dict]:
        with self._lock:
            self._advance_clock()
            return [dict(c) for c in self._containers.values()
                    if sandbox_id is None or c["sandboxId"] == sandbox_id]

    def pod_sandbox_status(self, sandbox_id: str) -> dict | None:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            return dict(sb) if sb else None

    def _advance_clock(self) -> None:
        now = time.time()
        for c in self._containers.values():
            if c["state"] == RUNNING and c.get("exitAt") and now >= c["exitAt"]:
                c["state"] = EXITED
                c["exitCode"] = c.get("plannedExitCode", 0)

    # -- ImageService ----------------------------------------------------

    def pull_image(self, image: str) -> str:
        with self._lock:
            self._images.add(image)
        return image

    def list_images(self) -> list[str]:
        with self._lock:
            return sorted(self._images)
