"""Fake CRI runtime.

Reference: the CRI contract (staging/src/k8s.io/cri-api/pkg/apis/runtime/v1/
api.proto — RunPodSandbox/StopPodSandbox/RemovePodSandbox, CreateContainer/
StartContainer/StopContainer/RemoveContainer, ListContainers, PullImage...)
and the kubemark fake (pkg/kubelet/cri/remote/fake/): an in-process
implementation that tracks sandbox/container state machines without running
anything.  Method names follow the proto rpcs; this is the seam where a
real gRPC runtime (containerd) would plug in.
"""

from __future__ import annotations

import threading
import time
import uuid

SANDBOX_READY = "SANDBOX_READY"
SANDBOX_NOTREADY = "SANDBOX_NOTREADY"

CREATED = "CONTAINER_CREATED"
RUNNING = "CONTAINER_RUNNING"
EXITED = "CONTAINER_EXITED"


class FakeRuntimeService:
    def __init__(self, start_latency: float = 0.0):
        self._lock = threading.Lock()
        self._sandboxes: dict[str, dict] = {}
        self._containers: dict[str, dict] = {}
        self._images: set[str] = set()
        self.start_latency = start_latency
        # streaming seam (api.proto Exec/Attach/PortForward rpcs): log
        # lines + a condvar for `follow`, checkpoint archives
        self._log_cond = threading.Condition(self._lock)
        self._checkpoints: dict[str, dict] = {}

    # -- RuntimeService --------------------------------------------------

    def run_pod_sandbox(self, config: dict) -> str:
        if self.start_latency:
            time.sleep(self.start_latency)
        sid = uuid.uuid4().hex[:12]
        with self._lock:
            self._sandboxes[sid] = {"id": sid, "state": SANDBOX_READY,
                                    "config": config,
                                    "createdAt": time.time()}
        return sid

    def stop_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            if sb:
                sb["state"] = SANDBOX_NOTREADY
            for c in self._containers.values():
                if c["sandboxId"] == sandbox_id and c["state"] == RUNNING:
                    c["state"] = EXITED
                    c["exitCode"] = 137

    def remove_pod_sandbox(self, sandbox_id: str) -> None:
        with self._lock:
            self._sandboxes.pop(sandbox_id, None)
            self._containers = {cid: c for cid, c in self._containers.items()
                                if c["sandboxId"] != sandbox_id}

    def create_container(self, sandbox_id: str, config: dict) -> str:
        cid = uuid.uuid4().hex[:12]
        with self._lock:
            self._containers[cid] = {
                "id": cid, "sandboxId": sandbox_id, "state": CREATED,
                "name": config.get("name", ""), "image": config.get("image", ""),
                "config": config, "createdAt": time.time(), "exitCode": None,
            }
        return cid

    def start_container(self, container_id: str) -> None:
        if self.start_latency:
            time.sleep(self.start_latency)
        with self._lock:
            c = self._containers[container_id]
            c["state"] = RUNNING
            c["startedAt"] = time.time()
            name = c["name"] or container_id
            c["logs"] = [f"{name} starting\n", f"{name} ready\n"]
            interval = (c["config"].get("annotations") or {}).get(
                "hollow/log-interval-seconds")
            if interval is not None:
                try:
                    every = float(interval)
                except (TypeError, ValueError):
                    every = 0.0
                if every > 0:  # <=0 would spin _advance_clock forever
                    c["logEvery"] = every
                    c["nextLogAt"] = c["startedAt"] + every
                    c["logSeq"] = 0
            self._log_cond.notify_all()
            # hollow semantics: a container may declare it exits by itself
            run_for = (c["config"].get("annotations") or {}).get("hollow/run-seconds")
            if run_for is not None:
                c["exitAt"] = c["startedAt"] + float(run_for)
                c["plannedExitCode"] = int(
                    (c["config"].get("annotations") or {}).get("hollow/exit-code", 0))

    def stop_container(self, container_id: str, timeout: float = 0) -> None:
        with self._lock:
            c = self._containers.get(container_id)
            if c and c["state"] == RUNNING:
                c["state"] = EXITED
                c["exitCode"] = 137

    def remove_container(self, container_id: str) -> None:
        with self._lock:
            self._containers.pop(container_id, None)

    def list_containers(self, sandbox_id: str | None = None) -> list[dict]:
        with self._lock:
            self._advance_clock()
            return [dict(c) for c in self._containers.values()
                    if sandbox_id is None or c["sandboxId"] == sandbox_id]

    def pod_sandbox_status(self, sandbox_id: str) -> dict | None:
        with self._lock:
            sb = self._sandboxes.get(sandbox_id)
            return dict(sb) if sb else None

    def _advance_clock(self) -> None:
        now = time.time()
        logged = False
        for c in self._containers.values():
            if c["state"] == RUNNING and c.get("exitAt") and now >= c["exitAt"]:
                c["state"] = EXITED
                c["exitCode"] = c.get("plannedExitCode", 0)
            while (c["state"] == RUNNING and c.get("logEvery")
                   and now >= c["nextLogAt"]):
                c["logs"].append(f"tick {c['logSeq']}\n")
                c["logSeq"] += 1
                c["nextLogAt"] += c["logEvery"]
                logged = True
        if logged:
            self._log_cond.notify_all()

    # -- streaming (api.proto Exec/Attach/PortForward/ReattachContainer;
    # the reference runtime returns a streaming-server URL from these
    # rpcs — in-process, the seam is a direct call taking an IO adapter
    # with read_stdin()/write_stdout()/write_stderr()) ------------------

    def read_logs(self, container_id: str, follow: bool = False,
                  tail: int | None = None, stop=None, poll: float = 0.1,
                  since_index: int | None = None):
        """Yield log lines; with follow, block for appends until the
        container exits or `stop` (an Event) is set.  `since_index`
        pins the start position eagerly captured by the caller (attach
        must snapshot the tail BEFORE it starts pumping stdin, or an
        immediate write lands in the skipped prefix)."""
        sent = 0
        with self._log_cond:
            c = self._containers.get(container_id)
            if c is None:
                raise KeyError(container_id)
            logs = c.setdefault("logs", [])
            if since_index is not None:
                sent = min(since_index, len(logs))
            elif tail is not None:
                sent = max(0, len(logs) - tail)
        while True:
            with self._log_cond:
                self._advance_clock()
                c = self._containers.get(container_id)
                if c is None:
                    return
                batch = c["logs"][sent:]
                sent += len(batch)
                done = not follow or c["state"] != RUNNING
                if not batch and not done:
                    # timed wait doubles as the tick/exit poll
                    self._log_cond.wait(poll)
            yield from batch
            if batch:
                continue
            if done or (stop is not None and stop.is_set()):
                return

    def exec_stream(self, container_id: str, command: list[str], io,
                    tty: bool = False) -> int:
        """Scripted in-container shell; returns the exit code.

        The hollow runtime executes nothing, so exec semantics are a
        deterministic script over the container's config — enough to
        exercise the full kubectl<->apiserver<->kubelet plumbing the
        reference drives through a real shell."""
        with self._lock:
            c = self._containers.get(container_id)
            if c is None or c["state"] != RUNNING:
                io.write_stderr(b"container not running\n")
                return 126
            cfg = dict(c["config"])
            # the container's in-memory filesystem persists across execs
            # (lives on the container entry, not the config copy)
            files = c.setdefault("files", {})
            sandbox = self._sandboxes.get(c["sandboxId"]) or {}
            hostname = (sandbox.get("config") or {}).get("name", "")
        return self._run_scripted(command, io, cfg, hostname, files)

    def _run_scripted(self, argv: list[str], io, cfg: dict,
                      hostname: str, files: dict | None = None) -> int:
        if not argv:
            io.write_stderr(b"no command\n")
            return 126
        cmd, args = argv[0], argv[1:]
        if cmd in ("sh", "/bin/sh", "bash") and len(args) >= 2 \
                and args[0] == "-c":
            inner = args[1].split()
            if inner[:1] == ["exit"]:
                try:
                    return int(inner[1]) if len(inner) > 1 else 0
                except ValueError:
                    return 2
            return self._run_scripted(inner, io, cfg, hostname, files)
        if cmd == "echo":
            io.write_stdout((" ".join(args) + "\n").encode())
            return 0
        if cmd == "cat" and not args:
            while True:
                data = io.read_stdin()
                if data is None:
                    return 0
                io.write_stdout(data)
        if cmd == "env":
            env = cfg.get("env") or [{"name": "PATH", "value": "/usr/bin"}]
            for e in env:
                io.write_stdout(f"{e['name']}={e.get('value', '')}\n".encode())
            io.write_stdout(f"HOSTNAME={hostname}\n".encode())
            return 0
        if cmd == "hostname":
            io.write_stdout((hostname + "\n").encode())
            return 0
        if cmd == "true":
            return 0
        if cmd == "false":
            return 1
        if cmd == "exit":
            try:
                return int(args[0]) if args else 0
            except ValueError:
                return 2
        if cmd == "sleep":
            try:
                time.sleep(min(float(args[0]), 10.0) if args else 0.0)
            except ValueError:
                return 2
            return 0
        if cmd == "tar":
            return self._run_tar(args, io, files if files is not None else {})
        if cmd == "cat":
            files = files if files is not None else {}
            code = 0
            for path in args:
                data = files.get(self._normpath(path))
                if data is None:
                    io.write_stderr(
                        f"cat: {path}: No such file or directory\n".encode())
                    code = 1
                else:
                    io.write_stdout(data)
            return code
        if cmd == "ls":
            files = files if files is not None else {}
            prefix = self._normpath(args[0]) if args else "/"
            names = sorted(p for p in files
                           if p == prefix or p.startswith(
                               prefix.rstrip("/") + "/"))
            if args and not names:
                io.write_stderr(
                    f"ls: {args[0]}: No such file or directory\n".encode())
                return 1
            io.write_stdout(("\n".join(names) + "\n").encode())
            return 0
        io.write_stderr(f"sh: {cmd}: command not found\n".encode())
        return 127

    @staticmethod
    def _normpath(path: str) -> str:
        import posixpath
        return posixpath.normpath("/" + path.lstrip("/"))

    def _run_tar(self, args: list[str], io, files: dict) -> int:
        """Scripted `tar` over the container's in-memory files — the
        transport kubectl cp rides (reference: kubectl/pkg/cmd/cp/cp.go
        execs `tar cf -` / `tar xmf -` in the container)."""
        import io as pyio
        import tarfile

        flags = args[0].lstrip("-") if args else ""
        rest = args[1:]
        chdir = "/"
        members: list[str] = []
        i = 0
        while i < len(rest):
            if rest[i] == "-C" and i + 1 < len(rest):
                chdir = rest[i + 1]
                i += 2
            elif rest[i] == "-":
                i += 1  # archive == stdin/stdout, implied
            else:
                members.append(rest[i])
                i += 1
        if "c" in flags:
            buf = pyio.BytesIO()
            with tarfile.open(fileobj=buf, mode="w") as tf:
                for m in members:
                    full = self._normpath(
                        m if m.startswith("/") else chdir + "/" + m)
                    hits = {p: d for p, d in files.items()
                            if p == full or p.startswith(
                                full.rstrip("/") + "/")}
                    if not hits:
                        io.write_stderr(
                            f"tar: {m}: No such file or directory\n".encode())
                        return 2
                    for p, d in sorted(hits.items()):
                        ti = tarfile.TarInfo(p.lstrip("/"))
                        ti.size = len(d)
                        tf.addfile(ti, pyio.BytesIO(d))
            data = buf.getvalue()
            step = 1 << 20  # stream frame cap (streams.MAX_FRAME)
            for at in range(0, len(data), step):
                io.write_stdout(data[at:at + step])
            return 0
        if "x" in flags:
            chunks = []
            while True:
                data = io.read_stdin()
                if data is None:
                    break
                chunks.append(data)
            try:
                with tarfile.open(fileobj=pyio.BytesIO(b"".join(chunks)),
                                  mode="r") as tf:
                    for ti in tf.getmembers():
                        if not ti.isfile():
                            continue
                        dest = self._normpath(chdir + "/" + ti.name)
                        files[dest] = tf.extractfile(ti).read()
            except tarfile.TarError as e:
                io.write_stderr(f"tar: {e}\n".encode())
                return 2
            return 0
        io.write_stderr(b"tar: need c or x\n")
        return 2

    def attach_stream(self, container_id: str, io, stop=None,
                      tty: bool = False) -> int:
        """Attach to the scripted console: stream log appends to stdout;
        stdin lines are appended to the log (as if the entrypoint read
        them) and echoed back when tty."""
        import threading
        done = threading.Event()

        def pump_stdin():
            while not done.is_set():
                data = io.read_stdin()
                if data is None:
                    return
                with self._log_cond:
                    c = self._containers.get(container_id)
                    if c is None:
                        return
                    c.setdefault("logs", []).append(
                        data.decode(errors="replace"))
                    self._log_cond.notify_all()

        with self._log_cond:
            c = self._containers.get(container_id)
            start = len(c.get("logs") or ()) if c else 0
        t = threading.Thread(target=pump_stdin, daemon=True)
        t.start()
        try:
            for line in self.read_logs(container_id, follow=True,
                                       since_index=start, stop=stop):
                io.write_stdout(line.encode())
        finally:
            done.set()
        return 0

    def portforward_stream(self, sandbox_id: str, port: int, io) -> None:
        """Scripted pod network: a declared containerPort answers with a
        banner then echoes; anything else refuses (the contract a real
        CRI implements by dialing the pod's netns)."""
        with self._lock:
            declared = {
                p.get("containerPort")
                for c in self._containers.values()
                if c["sandboxId"] == sandbox_id and c["state"] == RUNNING
                for p in (c["config"].get("ports") or ())}
        if port not in declared:
            io.error(f"connection refused: port {port} not declared")
            return
        io.write_data(f"hollow-port {port}\n".encode())
        while True:
            data = io.read_data()
            if data is None:
                return
            io.write_data(data)

    def checkpoint_container(self, container_id: str) -> str:
        """CRI CheckpointContainer (api.proto): snapshot the container's
        fake state; returns the archive name the kubelet reports."""
        with self._lock:
            c = self._containers.get(container_id)
            if c is None:
                raise KeyError(container_id)
            archive = (f"checkpoint-{c['name'] or container_id}-"
                       f"{int(time.time())}.tar")
            self._checkpoints[archive] = {
                "container": dict(c, config=dict(c["config"])),
                "at": time.time()}
        return archive

    # -- ImageService ----------------------------------------------------

    def pull_image(self, image: str) -> str:
        with self._lock:
            self._images.add(image)
        return image

    def list_images(self) -> list[str]:
        with self._lock:
            return sorted(self._images)
