"""Eviction manager.

Reference: pkg/kubelet/eviction — observes node resource pressure against
signal thresholds (memory.available, nodefs.available, pid.available);
under pressure it sets the node condition (MemoryPressure/DiskPressure),
ranks pods (BestEffort first, then Burstable exceeding requests, by
priority) and evicts until the signal clears, stamping the pod Failed with
reason Evicted.

Stats come from a pluggable provider; the default derives memory usage
from pod requests (kubemark-style synthetic stats).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional

from ..api import meta, quantity
from ..api.meta import Obj
from ..client.clientset import NODES, PODS, Client
from ..store import kv
from .qos import eviction_rank

logger = logging.getLogger(__name__)


def requests_stats_provider(pods: List[Obj]) -> int:
    """-> memory working set in bytes, synthesized from requests."""
    total = 0
    for p in pods:
        for c in (p.get("spec") or {}).get("containers") or ():
            req = (c.get("resources") or {}).get("requests") or {}
            total += quantity.parse_mem_bytes(req.get("memory", "0"))
    return total


class EvictionManager:
    def __init__(self, client: Client, node_name: str,
                 memory_capacity: int,
                 memory_available_threshold: float = 0.05,
                 stats_provider: Callable = requests_stats_provider,
                 list_pods: Optional[Callable] = None):
        self.client = client
        self.node_name = node_name
        self.memory_capacity = memory_capacity
        # threshold as a fraction of capacity (eviction-hard
        # memory.available<5% equivalent)
        self.memory_available_threshold = memory_available_threshold
        self.stats_provider = stats_provider
        self.list_pods = list_pods or (lambda: [])
        self.under_pressure = False

    def synchronize(self) -> List[str]:
        """One reconcile (eviction manager main loop body).  Returns the
        names of pods evicted this round."""
        pods = [p for p in self.list_pods()
                if not meta.pod_is_terminal(p)
                and meta.deletion_timestamp(p) is None]
        evicted: List[str] = []
        while True:
            used = self.stats_provider(pods)
            available = self.memory_capacity - used
            pressure = available < (self.memory_capacity
                                    * self.memory_available_threshold)
            if pressure != self.under_pressure:
                self.under_pressure = pressure
                self._set_node_condition(pressure)
            if not pressure or not pods:
                break
            victim = min(pods, key=eviction_rank)
            self._evict(victim)
            evicted.append(meta.name(victim))
            pods.remove(victim)
        return evicted

    def _evict(self, pod: Obj) -> None:
        logger.info("evicting pod %s: node %s under memory pressure",
                    meta.namespaced_name(pod), self.node_name)
        try:
            def patch(p):
                p.setdefault("status", {}).update({
                    "phase": "Failed", "reason": "Evicted",
                    "message": "The node was low on resource: memory."})
                return p
            self.client.guaranteed_update(PODS, meta.namespace(pod),
                                          meta.name(pod), patch)
        except kv.StoreError:
            pass

    def _set_node_condition(self, pressure: bool) -> None:
        cond = {"type": "MemoryPressure",
                "status": "True" if pressure else "False",
                "lastTransitionTime": time.time()}
        try:
            def patch(n):
                conds = [c for c in (n.get("status") or {})
                         .get("conditions", [])
                         if c.get("type") != "MemoryPressure"]
                conds.append(cond)
                n.setdefault("status", {})["conditions"] = conds
                return n
            self.client.guaranteed_update(NODES, "", self.node_name, patch)
        except kv.StoreError:
            pass
