"""Hollow kubelet — the node agent with a fake runtime.

Reference: pkg/kubelet (syncLoop, kubelet.go:2019) driven through the
kubemark hollow-node shape (pkg/kubemark/hollow_kubelet.go:87): real
kubelet logic, fake CRI, fake cadvisor.  The loop here is event-driven off
the pod informer (ADD/UPDATE/DELETE -> per-pod sync, kubelet
syncLoopIteration) plus a PLEG-like relist that surfaces container exits
(pkg/kubelet/pleg/generic.go), and a heartbeat loop renewing the node
Lease + status (kubelet nodestatus + nodelease).
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..api.resources import make_resource_list
from ..client.clientset import LEASES, NODES, PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv
from .cri import EXITED, RUNNING, FakeRuntimeService

logger = logging.getLogger(__name__)

LEASE_NS = "kube-node-lease"


class HollowKubelet:
    def __init__(self, client: Client, factory: SharedInformerFactory,
                 node_name: str, cpu: str = "32", memory: str = "256Gi",
                 pods: int = 110, labels: dict[str, str] | None = None,
                 heartbeat_interval: float = 10.0,
                 runtime: FakeRuntimeService | None = None,
                 container_manager=None, kubelet_server=None):
        self.client = client
        self.node_name = node_name
        self.cpu, self.memory, self.max_pods = cpu, memory, pods
        self.labels = labels or {}
        self.heartbeat_interval = heartbeat_interval
        self.runtime = runtime or FakeRuntimeService()
        # optional cm.ContainerManager: runs resource admission (cpu/memory/
        # device/topology managers) before containers start
        self.container_manager = container_manager
        # optional server.KubeletServer: serves logs/exec/attach/
        # portForward for this node; its port lands in node status
        self.kubelet_server = kubelet_server
        self.pod_informer = factory.informer(PODS)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # pod uid -> {"sandbox": id, "containers": {name: id},
        #             "key": (ns, podname)}
        self._pod_state: dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "HollowKubelet":
        if self.kubelet_server is not None:
            self.kubelet_server.register(self)
        self._register_node()
        if self.container_manager is not None:
            # reconcile checkpointed allocations against live pods: anything
            # restored for a pod that vanished while we were down leaks
            # forever otherwise (callers start the informer factory before
            # kubelets, so the view is synced here)
            live = {meta.uid(p) for p in self.pod_informer.list()
                    if meta.pod_node_name(p) == self.node_name
                    and not meta.pod_is_terminal(p)}
            self.container_manager.reconcile(live)
        self.pod_informer.add_event_handler(self._on_pod_event)
        for target, name in ((self._heartbeat_loop, "heartbeat"),
                             (self._pleg_loop, "pleg")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"kubelet-{self.node_name}-{name}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()
        if self.kubelet_server is not None:
            self.kubelet_server.unregister(self)

    # -- node registration + heartbeats ----------------------------------

    def _register_node(self) -> None:
        rl = make_resource_list(
            cpu_milli=int(float(self.cpu) * 1000),
            mem=self._mem_bytes(), pods=self.max_pods)
        if self.container_manager is not None:
            # device plugins surface as scalar allocatable (devicemanager
            # feeding nodestatus, e.g. google.com/tpu)
            for res, n in self.container_manager.devices.allocatable().items():
                rl[res] = str(n)
        node = meta.new_object("Node", self.node_name, None)
        node["metadata"]["labels"] = {
            "kubernetes.io/hostname": self.node_name, **self.labels}
        node["spec"] = {}
        node["status"] = {
            "capacity": rl, "allocatable": rl,
            "conditions": [{"type": "Ready", "status": "True"}],
            "nodeInfo": {"kubeletVersion": "hollow", "architecture": "tpu"},
            "lastHeartbeatTime": time.time(),
        }
        if self.kubelet_server is not None:
            # nodestatus daemonEndpoints: how the apiserver's node tunnel
            # finds this kubelet (pkg/kubelet/nodestatus/setters.go)
            node["status"]["addresses"] = [
                {"type": "InternalIP",
                 "address": self.kubelet_server.host}]
            node["status"]["daemonEndpoints"] = {
                "kubeletEndpoint": {"Port": self.kubelet_server.port}}
        try:
            self.client.create(NODES, node)
        except kv.AlreadyExistsError:
            pass
        lease = meta.new_object("Lease", self.node_name, LEASE_NS)
        lease["spec"] = {"holderIdentity": self.node_name,
                         "renewTime": time.time(),
                         "leaseDurationSeconds": 40}
        try:
            self.client.create(LEASES, lease)
        except kv.AlreadyExistsError:
            pass

    def _mem_bytes(self) -> int:
        from ..api.quantity import parse_mem_bytes
        return parse_mem_bytes(self.memory)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            now = time.time()
            try:
                self.client.guaranteed_update(
                    LEASES, LEASE_NS, self.node_name,
                    lambda l: {**l, "spec": {**l.get("spec", {}),
                                             "renewTime": now}})
                self.client.guaranteed_update(
                    NODES, "", self.node_name,
                    lambda n: {**n, "status": {**n.get("status", {}),
                                               "lastHeartbeatTime": now}})
            except kv.StoreError:
                pass

    # -- pod sync (syncLoopIteration -> SyncPod) -------------------------

    def _on_pod_event(self, type_: str, pod: Obj, old: Obj | None) -> None:
        mine = meta.pod_node_name(pod) == self.node_name
        was_mine = old is not None and meta.pod_node_name(old) == self.node_name
        if not mine and not was_mine:
            return
        if type_ == kv.DELETED or not mine:
            self._kill_pod(pod)
        elif meta.pod_is_terminal(pod):
            # terminal pods keep their API object but give back their
            # sandbox and resource-manager allocations (devicemanager
            # reclaims terminated pods' devices via activePods)
            self._kill_pod(pod)
        else:
            self._sync_pod(pod)

    def _sync_pod(self, pod: Obj) -> None:
        """kuberuntime SyncPod (kuberuntime_manager.go:672): ensure sandbox,
        start missing containers, then report status."""
        uid = meta.uid(pod)
        if self.container_manager is not None:
            with self._lock:
                new_pod = uid not in self._pod_state
            if new_pod and not self._admit(pod):
                return
        with self._lock:
            st = self._pod_state.get(uid)
            if st is None:
                sandbox = self.runtime.run_pod_sandbox(
                    {"name": meta.name(pod), "uid": uid})
                st = self._pod_state[uid] = {
                    "sandbox": sandbox, "containers": {},
                    "key": (meta.namespace(pod), meta.name(pod))}
            for c in (pod.get("spec") or {}).get("containers") or ():
                if c["name"] in st["containers"]:
                    continue
                self.runtime.pull_image(c.get("image", ""))
                cid = self.runtime.create_container(st["sandbox"], {
                    "name": c["name"], "image": c.get("image", ""),
                    "annotations": meta.annotations(pod),
                    "env": c.get("env"), "ports": c.get("ports")})
                self.runtime.start_container(cid)
                st["containers"][c["name"]] = cid
        self._report_status(pod)

    def _admit(self, pod: Obj) -> bool:
        """kubelet admission (HandlePodAdditions -> canAdmitPod): resource
        managers allocate or the pod is failed with the admission reason."""
        from .cm import AdmissionError
        try:
            self.container_manager.admit_pod(pod)
            return True
        except AdmissionError as e:
            def patch(p):
                p.setdefault("status", {}).update({
                    "phase": "Failed", "reason": "UnexpectedAdmissionError",
                    "message": str(e)})
                return p
            try:
                self.client.guaranteed_update(PODS, meta.namespace(pod),
                                              meta.name(pod), patch)
            except kv.StoreError:
                pass
            return False

    def _kill_pod(self, pod: Obj) -> None:
        uid = meta.uid(pod)
        if self.container_manager is not None:
            self.container_manager.release_pod(uid)
        with self._lock:
            st = self._pod_state.pop(uid, None)
        if st:
            self.runtime.stop_pod_sandbox(st["sandbox"])
            self.runtime.remove_pod_sandbox(st["sandbox"])

    def _report_status(self, pod: Obj) -> None:
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
        if st is None:
            return
        containers = self.runtime.list_containers(st["sandbox"])
        running = [c for c in containers if c["state"] == RUNNING]
        exited = [c for c in containers if c["state"] == EXITED]
        if containers and not running and exited:
            failed = any(c.get("exitCode") not in (0, None) for c in exited)
            phase = "Failed" if failed else "Succeeded"
            ready = False
        else:
            phase = "Running"
            ready = bool(running)
        status = {
            "phase": phase,
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Ready", "status": "True" if ready else "False"},
            ],
            "containerStatuses": [
                {"name": c["name"], "state": c["state"],
                 "exitCode": c.get("exitCode")} for c in containers],
            "hostIP": f"10.0.0.{abs(hash(self.node_name)) % 250 + 1}",
            "podIP": f"10.{abs(hash(uid)) % 250}.{abs(hash(uid) >> 8) % 250}."
                     f"{abs(hash(uid) >> 16) % 250 + 1}",
        }
        try:
            def patch(p):
                # terminal phases never regress (status_manager versioned
                # updates): a stale Running report must not resurrect a
                # pod that went Succeeded/Failed meanwhile
                if (p.get("status") or {}).get("phase") in ("Succeeded",
                                                            "Failed"):
                    return p
                p.setdefault("status", {}).update(status)
                return p
            self.client.guaranteed_update(PODS, meta.namespace(pod),
                                          meta.name(pod), patch)
        except kv.StoreError:
            pass

    # -- PLEG: relist container states, surface exits --------------------

    def _pleg_loop(self) -> None:
        while not self._stop.wait(1.0):
            with self._lock:
                uids = list(self._pod_state)
            for uid in uids:
                pod = self._find_pod(uid)
                if pod is not None and not meta.pod_is_terminal(pod):
                    self._report_status(pod)

    def _find_pod(self, uid: str) -> Obj | None:
        for p in self.pod_informer.list():
            if meta.uid(p) == uid:
                return p
        return None

    # -- streaming-server lookups ---------------------------------------

    def lookup_pod(self, ns: str, name: str) -> dict | None:
        """(sandbox id, container name->id) for a pod this node runs."""
        with self._lock:
            for st in self._pod_state.values():
                if st.get("key") == (ns, name):
                    return {"sandbox": st["sandbox"],
                            "containers": dict(st["containers"])}
        return None

    def list_pod_keys(self) -> list[tuple[str, str]]:
        with self._lock:
            return [st["key"] for st in self._pod_state.values()
                    if "key" in st]


def start_hollow_nodes(client: Client, factory: SharedInformerFactory,
                       count: int, prefix: str = "hollow-",
                       **kwargs) -> list[HollowKubelet]:
    """kubemark: register `count` hollow nodes against the control plane."""
    return [HollowKubelet(client, factory, f"{prefix}{i}", **kwargs).start()
            for i in range(count)]
