"""Hollow kubelet — the node agent with a fake runtime.

Reference: pkg/kubelet (syncLoop, kubelet.go:2019) driven through the
kubemark hollow-node shape (pkg/kubemark/hollow_kubelet.go:87): real
kubelet logic, fake CRI, fake cadvisor.  The loop here is event-driven off
the pod informer (ADD/UPDATE/DELETE -> per-pod sync, kubelet
syncLoopIteration) plus a PLEG-like relist that surfaces container exits
(pkg/kubelet/pleg/generic.go), and a heartbeat loop renewing the node
Lease + status (kubelet nodestatus + nodelease).
"""

from __future__ import annotations

import logging
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..api.resources import make_resource_list
from ..client.clientset import LEASES, NODES, PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv
from .cri import EXITED, RUNNING, FakeRuntimeService

logger = logging.getLogger(__name__)

LEASE_NS = "kube-node-lease"


class HollowKubelet:
    def __init__(self, client: Client, factory: SharedInformerFactory,
                 node_name: str, cpu: str = "32", memory: str = "256Gi",
                 pods: int = 110, labels: dict[str, str] | None = None,
                 heartbeat_interval: float = 10.0,
                 runtime: FakeRuntimeService | None = None):
        self.client = client
        self.node_name = node_name
        self.cpu, self.memory, self.max_pods = cpu, memory, pods
        self.labels = labels or {}
        self.heartbeat_interval = heartbeat_interval
        self.runtime = runtime or FakeRuntimeService()
        self.pod_informer = factory.informer(PODS)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        # pod uid -> {"sandbox": id, "containers": {name: id}}
        self._pod_state: dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "HollowKubelet":
        self._register_node()
        self.pod_informer.add_event_handler(self._on_pod_event)
        for target, name in ((self._heartbeat_loop, "heartbeat"),
                             (self._pleg_loop, "pleg")):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"kubelet-{self.node_name}-{name}")
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stop.set()

    # -- node registration + heartbeats ----------------------------------

    def _register_node(self) -> None:
        rl = make_resource_list(
            cpu_milli=int(float(self.cpu) * 1000),
            mem=self._mem_bytes(), pods=self.max_pods)
        node = meta.new_object("Node", self.node_name, None)
        node["metadata"]["labels"] = {
            "kubernetes.io/hostname": self.node_name, **self.labels}
        node["spec"] = {}
        node["status"] = {
            "capacity": rl, "allocatable": rl,
            "conditions": [{"type": "Ready", "status": "True"}],
            "nodeInfo": {"kubeletVersion": "hollow", "architecture": "tpu"},
            "lastHeartbeatTime": time.time(),
        }
        try:
            self.client.create(NODES, node)
        except kv.AlreadyExistsError:
            pass
        lease = meta.new_object("Lease", self.node_name, LEASE_NS)
        lease["spec"] = {"holderIdentity": self.node_name,
                         "renewTime": time.time(),
                         "leaseDurationSeconds": 40}
        try:
            self.client.create(LEASES, lease)
        except kv.AlreadyExistsError:
            pass

    def _mem_bytes(self) -> int:
        from ..api.quantity import parse_mem_bytes
        return parse_mem_bytes(self.memory)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            now = time.time()
            try:
                self.client.guaranteed_update(
                    LEASES, LEASE_NS, self.node_name,
                    lambda l: {**l, "spec": {**l.get("spec", {}),
                                             "renewTime": now}})
                self.client.guaranteed_update(
                    NODES, "", self.node_name,
                    lambda n: {**n, "status": {**n.get("status", {}),
                                               "lastHeartbeatTime": now}})
            except kv.StoreError:
                pass

    # -- pod sync (syncLoopIteration -> SyncPod) -------------------------

    def _on_pod_event(self, type_: str, pod: Obj, old: Obj | None) -> None:
        mine = meta.pod_node_name(pod) == self.node_name
        was_mine = old is not None and meta.pod_node_name(old) == self.node_name
        if not mine and not was_mine:
            return
        if type_ == kv.DELETED or not mine:
            self._kill_pod(pod)
        elif not meta.pod_is_terminal(pod):
            self._sync_pod(pod)

    def _sync_pod(self, pod: Obj) -> None:
        """kuberuntime SyncPod (kuberuntime_manager.go:672): ensure sandbox,
        start missing containers, then report status."""
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
            if st is None:
                sandbox = self.runtime.run_pod_sandbox(
                    {"name": meta.name(pod), "uid": uid})
                st = self._pod_state[uid] = {"sandbox": sandbox, "containers": {}}
            for c in (pod.get("spec") or {}).get("containers") or ():
                if c["name"] in st["containers"]:
                    continue
                self.runtime.pull_image(c.get("image", ""))
                cid = self.runtime.create_container(st["sandbox"], {
                    "name": c["name"], "image": c.get("image", ""),
                    "annotations": meta.annotations(pod)})
                self.runtime.start_container(cid)
                st["containers"][c["name"]] = cid
        self._report_status(pod)

    def _kill_pod(self, pod: Obj) -> None:
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.pop(uid, None)
        if st:
            self.runtime.stop_pod_sandbox(st["sandbox"])
            self.runtime.remove_pod_sandbox(st["sandbox"])

    def _report_status(self, pod: Obj) -> None:
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
        if st is None:
            return
        containers = self.runtime.list_containers(st["sandbox"])
        running = [c for c in containers if c["state"] == RUNNING]
        exited = [c for c in containers if c["state"] == EXITED]
        if containers and not running and exited:
            failed = any(c.get("exitCode") not in (0, None) for c in exited)
            phase = "Failed" if failed else "Succeeded"
            ready = False
        else:
            phase = "Running"
            ready = bool(running)
        status = {
            "phase": phase,
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Ready", "status": "True" if ready else "False"},
            ],
            "containerStatuses": [
                {"name": c["name"], "state": c["state"],
                 "exitCode": c.get("exitCode")} for c in containers],
            "hostIP": f"10.0.0.{abs(hash(self.node_name)) % 250 + 1}",
            "podIP": f"10.{abs(hash(uid)) % 250}.{abs(hash(uid) >> 8) % 250}."
                     f"{abs(hash(uid) >> 16) % 250 + 1}",
        }
        try:
            def patch(p):
                p.setdefault("status", {}).update(status)
                return p
            self.client.guaranteed_update(PODS, meta.namespace(pod),
                                          meta.name(pod), patch)
        except kv.StoreError:
            pass

    # -- PLEG: relist container states, surface exits --------------------

    def _pleg_loop(self) -> None:
        while not self._stop.wait(1.0):
            with self._lock:
                uids = list(self._pod_state)
            for uid in uids:
                pod = self._find_pod(uid)
                if pod is not None and not meta.pod_is_terminal(pod):
                    self._report_status(pod)

    def _find_pod(self, uid: str) -> Obj | None:
        for p in self.pod_informer.list():
            if meta.uid(p) == uid:
                return p
        return None


def start_hollow_nodes(client: Client, factory: SharedInformerFactory,
                       count: int, prefix: str = "hollow-",
                       **kwargs) -> list[HollowKubelet]:
    """kubemark: register `count` hollow nodes against the control plane."""
    return [HollowKubelet(client, factory, f"{prefix}{i}", **kwargs).start()
            for i in range(count)]
