"""Image manager: GC by disk thresholds.

Reference: pkg/kubelet/images/image_gc_manager.go — when image disk usage
exceeds highThresholdPercent, delete unused images oldest-last-used first
until usage drops below lowThresholdPercent.  Disk usage is synthetic
here: every image costs `image_size` bytes against `disk_capacity`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Set

logger = logging.getLogger(__name__)


class ImageGCManager:
    def __init__(self, runtime, disk_capacity: int = 100 << 30,
                 image_size: int = 1 << 30,
                 high_threshold_percent: int = 85,
                 low_threshold_percent: int = 80):
        self.runtime = runtime  # FakeRuntimeService (list_images/remove)
        self.disk_capacity = disk_capacity
        self.image_size = image_size
        self.high = high_threshold_percent
        self.low = low_threshold_percent
        self._lock = threading.Lock()
        self._last_used: Dict[str, float] = {}

    def image_used(self, image: str) -> None:
        with self._lock:
            self._last_used[image] = time.monotonic()

    def usage_percent(self) -> float:
        n = len(self.runtime.list_images())
        return 100.0 * n * self.image_size / self.disk_capacity

    def garbage_collect(self, in_use: Set[str]) -> list[str]:
        """-> images deleted.  in_use images are never deleted."""
        deleted = []
        if self.usage_percent() < self.high:
            return deleted
        with self._lock:
            candidates = sorted(
                (img for img in self.runtime.list_images()
                 if img not in in_use),
                key=lambda img: self._last_used.get(img, 0.0))
        for img in candidates:
            if self.usage_percent() <= self.low:
                break
            self._remove(img)
            deleted.append(img)
        if self.usage_percent() > self.high:
            logger.warning("image GC: still above high threshold "
                           "(%.0f%%) after deleting %d images",
                           self.usage_percent(), len(deleted))
        return deleted

    def _remove(self, image: str) -> None:
        with self.runtime._lock:
            self.runtime._images.discard(image)
        with self._lock:
            self._last_used.pop(image, None)
