"""Full kubelet: syncLoop + pod workers + managers over the fake CRI.

Reference: pkg/kubelet/kubelet.go — Run (:1432) starts the managers and
syncLoop (:2019); syncLoopIteration (:2093) dispatches pod updates to per-
pod workers; kuberuntime SyncPod computes sandbox/container actions.  This
class composes the subsystem managers built alongside:

  pod_workers      per-pod serialized update pipelines (pod_workers.go)
  probes           liveness/readiness workers (pkg/kubelet/prober)
  status_manager   deduped status writer (pkg/kubelet/status)
  eviction         memory-pressure eviction (pkg/kubelet/eviction)
  images           image GC by disk thresholds (pkg/kubelet/images)
  checkpoint       atomic checksummed state files (checkpointmanager)
  qos              QoS classes driving eviction order

plus restart-policy enforcement with CrashLoopBackOff-style exponential
backoff (kuberuntime's computePodActions + backoff tracking).

HollowKubelet (hollow.py) stays the high-density kubemark node; Kubelet is
the full node agent.  Both speak the same CRI seam.
"""

from __future__ import annotations

import logging
import tempfile
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..api.quantity import parse_mem_bytes
from ..client.clientset import PODS, Client
from ..client.informer import SharedInformerFactory
from ..store import kv
from .cri import EXITED, RUNNING, FakeRuntimeService
from .eviction import EvictionManager
from .hollow import HollowKubelet
from .images import ImageGCManager
from .checkpoint import CheckpointManager
from .pod_workers import PodWorkers
from .probes import ProbeManager
from .qos import pod_qos
from .status_manager import StatusManager

logger = logging.getLogger(__name__)

CRASH_BACKOFF_INITIAL = 0.25
CRASH_BACKOFF_MAX = 10.0  # upstream: 10s..5m; compressed for tests


class Kubelet(HollowKubelet):
    def __init__(self, client: Client, factory: SharedInformerFactory,
                 node_name: str, root_dir: str | None = None, **kwargs):
        super().__init__(client, factory, node_name, **kwargs)
        root = root_dir or tempfile.mkdtemp(prefix=f"kubelet-{node_name}-")
        self.checkpoints = CheckpointManager(root)
        self.status_manager = StatusManager(client)
        self.workers = PodWorkers(self._sync_worker)
        self.probes = ProbeManager(
            container_running=self._container_running,
            on_liveness_failure=self._restart_container,
            on_readiness_change=lambda pod, c, ok: self._report_status(pod))
        self.images = ImageGCManager(self.runtime)
        self.eviction = EvictionManager(
            client, node_name,
            memory_capacity=parse_mem_bytes(self.memory),
            list_pods=self._my_pods)
        # container crash backoff: (uid, container) -> (delay, not_before)
        self._backoff: dict[tuple, tuple] = {}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Kubelet":
        super().start()
        t = threading.Thread(target=self._housekeeping_loop, daemon=True,
                             name=f"kubelet-{self.node_name}-housekeeping")
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        super().stop()
        self.probes.stop()
        self.workers.stop()

    # -- syncLoopIteration -> pod workers --------------------------------

    def _on_pod_event(self, type_: str, pod: Obj, old: Obj | None) -> None:
        mine = meta.pod_node_name(pod) == self.node_name
        was_mine = old is not None and meta.pod_node_name(old) == self.node_name
        if not mine and not was_mine:
            return
        if type_ == kv.DELETED or not mine:
            self.workers.update_pod("KILL", pod)
        else:
            self.workers.update_pod("SYNC", pod)

    def _sync_worker(self, update_type: str, pod: Obj) -> None:
        if update_type == "KILL":
            self.probes.remove_pod(pod)
            self._kill_pod(pod)
            self.status_manager.remove_pod(meta.uid(pod))
            self.workers.forget_pod(meta.uid(pod))
            return
        if meta.deletion_timestamp(pod) is not None:
            # graceful termination: honor terminationGracePeriodSeconds=0
            # shape by killing immediately (store deletes are final here)
            self.workers.update_pod("KILL", pod)
            return
        if not meta.pod_is_terminal(pod):
            self._sync_pod(pod)
            self._restart_exited_containers(pod)
            self.probes.add_pod(pod)
            for c in (pod.get("spec") or {}).get("containers") or ():
                self.images.image_used(c.get("image", ""))

    # -- restart policy + crash backoff ----------------------------------

    def _restart_exited_containers(self, pod: Obj) -> None:
        """computePodActions: exited containers restart per restartPolicy
        (Always; OnFailure only when exitCode != 0) behind a per-container
        exponential backoff (CrashLoopBackOff)."""
        policy = (pod.get("spec") or {}).get("restartPolicy", "Always")
        if policy == "Never":
            return
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
        if st is None:
            return
        now = time.monotonic()
        for c in self.runtime.list_containers(st["sandbox"]):
            if c["state"] != EXITED:
                continue
            if policy == "OnFailure" and c.get("exitCode") in (0, None):
                continue
            key = (uid, c["name"])
            delay, not_before = self._backoff.get(key,
                                                  (CRASH_BACKOFF_INITIAL, 0.0))
            if now < not_before:
                continue  # CrashLoopBackOff: wait it out
            self._backoff[key] = (min(delay * 2, CRASH_BACKOFF_MAX),
                                  now + delay)
            self.runtime.remove_container(c["id"])
            spec_c = next((x for x in (pod.get("spec") or {})
                           .get("containers", [])
                           if x["name"] == c["name"]), None)
            if spec_c is None:
                continue
            cid = self.runtime.create_container(st["sandbox"], {
                "name": spec_c["name"], "image": spec_c.get("image", ""),
                "annotations": meta.annotations(pod)})
            self.runtime.start_container(cid)
            with self._lock:
                st["containers"][c["name"]] = cid
            logger.info("restarted container %s/%s (backoff %.2fs)",
                        meta.name(pod), c["name"], delay)

    def _restart_container(self, pod: Obj, container_name: str) -> None:
        """Liveness failure: kill the container; restart policy picks it
        back up on the next sync."""
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
            cid = st["containers"].get(container_name) if st else None
        if cid:
            self.runtime.stop_container(cid)
            self.workers.update_pod("SYNC", pod)

    def _container_running(self, pod: Obj, container_name: str) -> bool:
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
            cid = st["containers"].get(container_name) if st else None
        if cid is None:
            return False
        return any(c["id"] == cid and c["state"] == RUNNING
                   for c in self.runtime.list_containers())

    # -- status: route through the status manager + probe readiness ------

    def _report_status(self, pod: Obj) -> None:
        uid = meta.uid(pod)
        with self._lock:
            st = self._pod_state.get(uid)
        if st is None:
            return
        containers = self.runtime.list_containers(st["sandbox"])
        running = [c for c in containers if c["state"] == RUNNING]
        exited = [c for c in containers if c["state"] == EXITED]
        if containers and not running and exited:
            failed = any(c.get("exitCode") not in (0, None) for c in exited)
            phase = "Failed" if failed else "Succeeded"
            ready = False
        else:
            phase = "Running"
            ready = bool(running) and self.probes.pod_ready(pod)
        status = {
            "phase": phase,
            "qosClass": pod_qos(pod),
            "conditions": [
                {"type": "PodScheduled", "status": "True"},
                {"type": "Ready", "status": "True" if ready else "False"},
            ],
            "containerStatuses": [
                {"name": c["name"], "state": c["state"],
                 "exitCode": c.get("exitCode"),
                 "restartCount": 0} for c in containers],
            "hostIP": f"10.0.0.{abs(hash(self.node_name)) % 250 + 1}",
            "podIP": f"10.{abs(hash(uid)) % 250}.{abs(hash(uid) >> 8) % 250}."
                     f"{abs(hash(uid) >> 16) % 250 + 1}",
        }
        self.status_manager.set_pod_status(pod, status)

    # -- housekeeping: eviction + image GC + checkpoints ------------------

    def _my_pods(self) -> list[Obj]:
        return [p for p in self.pod_informer.list()
                if meta.pod_node_name(p) == self.node_name]

    def _housekeeping_loop(self) -> None:
        while not self._stop.wait(2.0):
            try:
                self.eviction.synchronize()
                in_use = {c.get("image", "")
                          for p in self._my_pods()
                          for c in (p.get("spec") or {}).get("containers", ())}
                self.images.garbage_collect(in_use)
                self._checkpoint_state()
            except Exception:  # noqa: BLE001
                logger.exception("kubelet housekeeping failed")

    def _checkpoint_state(self) -> None:
        """Persist pod->container allocation (the device/cpu-manager state
        analogue) so a restarted kubelet can reconcile without re-creating
        sandboxes for pods it already runs."""
        with self._lock:
            state = {uid: {"sandbox": st["sandbox"],
                           "containers": dict(st["containers"])}
                     for uid, st in self._pod_state.items()}
        self.checkpoints.create_checkpoint("pod_state", state)

    def restore_state(self) -> bool:
        """Crash-only restart: reload the allocation checkpoint."""
        try:
            state = self.checkpoints.get_checkpoint("pod_state")
        except KeyError:
            return False
        with self._lock:
            self._pod_state.update(state)
        return True
