"""Pod workers: per-pod serialized update pipelines.

Reference: pkg/kubelet/pod_workers.go — syncLoopIteration never blocks on a
pod; each pod gets its own goroutine+channel processing updates in order,
with "work coalescing": if updates arrive while a sync runs, only the
latest is kept.  Reproduced with a small shared thread pool and per-pod
FIFO-of-one pending slots.
"""

from __future__ import annotations

import logging
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

logger = logging.getLogger(__name__)


class PodWorkers:
    def __init__(self, sync_fn: Callable[[str, dict], None],
                 max_workers: int = 8):
        self.sync_fn = sync_fn  # sync_fn(update_type, pod)
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="pod-worker")
        self._lock = threading.Lock()
        # uid -> {"running": bool, "pending": (type, pod) | None}
        self._state: Dict[str, dict] = {}
        self._closed = False

    def update_pod(self, update_type: str, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid", "")
        with self._lock:
            if self._closed:
                return
            st = self._state.setdefault(uid,
                                        {"running": False, "pending": None})
            if st["running"]:
                st["pending"] = (update_type, pod)  # coalesce: latest wins
                return
            st["running"] = True
        self._pool.submit(self._drain, uid, update_type, pod)

    def _drain(self, uid: str, update_type: str, pod: dict) -> None:
        while True:
            try:
                self.sync_fn(update_type, pod)
            except Exception:  # noqa: BLE001 — a pod sync must not kill the pool
                logger.exception("pod worker sync failed for %s", uid)
            with self._lock:
                st = self._state.get(uid)
                if st is None:
                    return
                if st["pending"] is None:
                    st["running"] = False
                    return
                update_type, pod = st["pending"]
                st["pending"] = None

    def forget_pod(self, uid: str) -> None:
        with self._lock:
            self._state.pop(uid, None)

    def stop(self) -> None:
        with self._lock:
            self._closed = True
        self._pool.shutdown(wait=False)
