"""Probe manager: liveness + readiness workers.

Reference: pkg/kubelet/prober — one worker per (pod, container, probe
type); respects initialDelaySeconds / periodSeconds / failureThreshold /
successThreshold; readiness results flip the pod Ready condition, liveness
failures trigger a container restart through the kubelet callback.

The probe *handler* is pluggable (upstream: exec/httpGet/tcpSocket
runners).  The default handler understands the hollow runtime: a container
annotation ``hollow/fail-liveness`` / ``hollow/fail-readiness`` forces
failure; otherwise a RUNNING container passes.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

LIVENESS = "liveness"
READINESS = "readiness"


def default_handler(pod: dict, container: dict, probe_type: str,
                    container_running: bool) -> bool:
    ann = (pod.get("metadata") or {}).get("annotations") or {}
    if probe_type == LIVENESS and ann.get("hollow/fail-liveness") == "true":
        return False
    if probe_type == READINESS and ann.get("hollow/fail-readiness") == "true":
        return False
    return container_running


class _Worker:
    def __init__(self, mgr: "ProbeManager", pod: dict, container: dict,
                 probe_type: str, spec: dict):
        self.mgr = mgr
        self.pod = pod
        self.container = container
        self.probe_type = probe_type
        self.initial_delay = float(spec.get("initialDelaySeconds", 0))
        self.period = max(0.05, float(spec.get("periodSeconds", 10)))
        self.failure_threshold = int(spec.get("failureThreshold", 3))
        self.success_threshold = int(spec.get("successThreshold", 1))
        self._failures = 0
        self._successes = 0
        self.result: Optional[bool] = None  # None until first sample
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        if self._stop.wait(self.initial_delay):
            return
        while not self._stop.is_set():
            self._probe_once()
            if self._stop.wait(self.period):
                return

    def _probe_once(self) -> None:
        ok = self.mgr._run_handler(self.pod, self.container, self.probe_type)
        if ok:
            self._successes += 1
            self._failures = 0
            if self._successes >= self.success_threshold:
                self._set_result(True)
        else:
            self._failures += 1
            self._successes = 0
            if self._failures >= self.failure_threshold:
                self._set_result(False)

    def _set_result(self, ok: bool) -> None:
        if self.result == ok:
            return
        self.result = ok
        self.mgr._on_result(self.pod, self.container, self.probe_type, ok)


class ProbeManager:
    def __init__(self, handler: Callable = default_handler,
                 container_running: Optional[Callable] = None,
                 on_liveness_failure: Optional[Callable] = None,
                 on_readiness_change: Optional[Callable] = None):
        self.handler = handler
        # container_running(pod, container_name) -> bool; injected by kubelet
        self.container_running = container_running or (lambda p, c: True)
        self.on_liveness_failure = on_liveness_failure or (lambda p, c: None)
        self.on_readiness_change = on_readiness_change or (
            lambda p, c, ok: None)
        self._lock = threading.Lock()
        self._workers: Dict[Tuple[str, str, str], _Worker] = {}
        # (pod_uid, container) -> readiness (True until a probe says no,
        # mirroring upstream: containers without readiness probes are ready)
        self.readiness: Dict[Tuple[str, str], bool] = {}

    def add_pod(self, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid", "")
        for c in (pod.get("spec") or {}).get("containers") or ():
            for probe_type, field in ((LIVENESS, "livenessProbe"),
                                      (READINESS, "readinessProbe")):
                spec = c.get(field)
                if not spec:
                    continue
                key = (uid, c["name"], probe_type)
                with self._lock:
                    if key in self._workers:
                        continue
                    w = _Worker(self, pod, c, probe_type, spec)
                    self._workers[key] = w
                if probe_type == READINESS:
                    # not ready until the probe succeeds (upstream default)
                    self.readiness[(uid, c["name"])] = False
                w.start()

    def remove_pod(self, pod: dict) -> None:
        uid = (pod.get("metadata") or {}).get("uid", "")
        with self._lock:
            for key in [k for k in self._workers if k[0] == uid]:
                self._workers.pop(key).stop()
        for key in [k for k in self.readiness if k[0] == uid]:
            del self.readiness[key]

    def stop(self) -> None:
        with self._lock:
            for w in self._workers.values():
                w.stop()
            self._workers.clear()

    def pod_ready(self, pod: dict) -> bool:
        """All containers with readiness probes report ready."""
        uid = (pod.get("metadata") or {}).get("uid", "")
        return all(ok for (u, _), ok in self.readiness.items() if u == uid)

    # -- worker callbacks -------------------------------------------------

    def _run_handler(self, pod, container, probe_type) -> bool:
        running = self.container_running(pod, container["name"])
        try:
            return self.handler(pod, container, probe_type, running)
        except Exception:  # noqa: BLE001 — probe errors count as failures
            logger.exception("probe handler failed")
            return False

    def _on_result(self, pod, container, probe_type, ok: bool) -> None:
        uid = (pod.get("metadata") or {}).get("uid", "")
        if probe_type == READINESS:
            self.readiness[(uid, container["name"])] = ok
            self.on_readiness_change(pod, container["name"], ok)
        elif not ok:
            logger.info("liveness probe failed for %s/%s; restarting",
                        uid, container["name"])
            self.on_liveness_failure(pod, container["name"])
