"""Pod QoS classification.

Reference: pkg/apis/core/v1/helper/qos/qos.go GetPodQOS — Guaranteed when
every container has equal, non-empty requests and limits for cpu+memory;
BestEffort when no container has any request/limit; Burstable otherwise.
Eviction ranks BestEffort < Burstable < Guaranteed.
"""

from __future__ import annotations

GUARANTEED = "Guaranteed"
BURSTABLE = "Burstable"
BEST_EFFORT = "BestEffort"

_QOS_RESOURCES = ("cpu", "memory")


def pod_qos(pod: dict) -> str:
    requests: dict = {}
    limits: dict = {}
    guaranteed = True
    containers = (pod.get("spec") or {}).get("containers") or []
    for c in containers:
        res = c.get("resources") or {}
        req = res.get("requests") or {}
        lim = res.get("limits") or {}
        for k in _QOS_RESOURCES:
            if k in req:
                requests[k] = True
            if k in lim:
                limits[k] = True
        # guaranteed requires limits for both resources on every container
        # and requests (if set) equal to limits
        for k in _QOS_RESOURCES:
            if k not in lim:
                guaranteed = False
            elif k in req and req[k] != lim[k]:
                guaranteed = False
    if not requests and not limits:
        return BEST_EFFORT
    if guaranteed and containers:
        return GUARANTEED
    return BURSTABLE


def eviction_rank(pod: dict) -> tuple:
    """Lower sorts first (evicted earlier): BestEffort, then Burstable,
    then Guaranteed; ties by priority then creation recency."""
    order = {BEST_EFFORT: 0, BURSTABLE: 1, GUARANTEED: 2}
    prio = (pod.get("spec") or {}).get("priority", 0)
    return (order[pod_qos(pod)], prio)
