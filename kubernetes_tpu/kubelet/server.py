"""Kubelet HTTP server: logs/exec/attach/portForward/checkpoint/stats.

Reference: pkg/kubelet/server/server.go:949-967 — the kubelet serves
  /pods /healthz /stats/summary /configz
  /containerLogs/{ns}/{pod}/{container}
  /exec/{ns}/{pod}/{container}   /attach/...   /portForward/{ns}/{pod}
  /checkpoint/{ns}/{pod}/{container}
with the interactive endpoints upgrading to a multiplexed stream that a
CRI streaming server backs (cri-api api.proto Exec/Attach/PortForward).

Redesign for the hollow fleet: ONE process-wide server fronts every
hollow kubelet (kubemark runs hundreds of nodes per process; a listener
per node would be pure socket overhead).  Each request resolves
{ns, pod} across registered kubelets — node identity stays intact
because every node advertises this server in its own
status.daemonEndpoints.  The stream protocol is `streams.py`'s framed
upgrade, the plain-HTTP stand-in for the reference's SPDY.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from . import streams

logger = logging.getLogger(__name__)


class _ExecIO:
    """FrameSock -> CRI exec adapter: stdin demux + stdout/stderr mux.

    A dedicated reader thread drains the socket so stdin reads can't
    miss interleaved resize/close frames; exec scripts block on the
    queue, matching a real shell blocking on read(0)."""

    def __init__(self, fs: streams.FrameSock):
        import queue
        self.fs = fs
        self._stdin: queue.Queue[bytes | None] = queue.Queue()
        self.resizes: list[dict] = []
        t = threading.Thread(target=self._pump, daemon=True)
        t.start()

    def _pump(self) -> None:
        while True:
            frame = self.fs.recv()
            if frame is None:
                self._stdin.put(None)
                return
            ch, payload = frame
            if ch == streams.STDIN:
                self._stdin.put(payload)
            elif ch == streams.RESIZE:
                try:
                    self.resizes.append(json.loads(payload.decode()))
                except json.JSONDecodeError:
                    pass
            elif ch == streams.CLOSE and payload == bytes([streams.STDIN]):
                self._stdin.put(None)

    def read_stdin(self) -> bytes | None:
        return self._stdin.get()

    def write_stdout(self, data: bytes) -> None:
        self.fs.send(streams.STDOUT, data)

    def write_stderr(self, data: bytes) -> None:
        self.fs.send(streams.STDERR, data)


class _ConnClosedProbe:
    """Event-shaped view of "has the HTTP client hung up?".

    A GET log stream is half-duplex: the client sends nothing after the
    request, so EOF (readable socket + empty peek) is the only
    disconnect signal.  read_logs polls is_set() between waits."""

    def __init__(self, conn):
        self.conn = conn

    def is_set(self) -> bool:
        import select
        import socket as socketlib
        try:
            readable, _, _ = select.select([self.conn], [], [], 0)
            if not readable:
                return False
            return self.conn.recv(1, socketlib.MSG_PEEK) == b""
        except OSError:
            return True


class _PortIO:
    """FrameSock -> CRI port-forward adapter (data/error channels)."""

    def __init__(self, fs: streams.FrameSock):
        self.fs = fs

    def read_data(self) -> bytes | None:
        while True:
            frame = self.fs.recv()
            if frame is None:
                return None
            ch, payload = frame
            if ch == streams.PF_DATA:
                return payload
            if ch == streams.CLOSE:
                return None

    def write_data(self, data: bytes) -> None:
        self.fs.send(streams.PF_DATA, data)

    def error(self, message: str) -> None:
        self.fs.send(streams.PF_ERROR, message.encode())


class KubeletServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._kubelets: dict[str, object] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # see apiserver/server.py: Nagle + delayed ACK costs 40ms
            # per request on two-write responses
            disable_nagle_algorithm = True

            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj: dict) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _err(self, code: int, message: str) -> None:
                self._json(code, {"kind": "Status", "status": "Failure",
                                  "code": code, "message": message})

            def _resolve(self, ns: str, pod: str, container: str | None):
                """-> (runtime, sandbox, container id) or None+response."""
                hit = outer.lookup(ns, pod)
                if hit is None:
                    self._err(404, f"pod {ns}/{pod} not found on node")
                    return None
                kubelet, state = hit
                if container is None:
                    if len(state["containers"]) != 1:
                        self._err(400, "container name required")
                        return None
                    cid = next(iter(state["containers"].values()))
                else:
                    cid = state["containers"].get(container)
                    if cid is None:
                        self._err(404, f"container {container!r} not found")
                        return None
                return kubelet.runtime, state["sandbox"], cid

            # ---- routes ----

            def do_GET(self):
                self._handle()

            def do_POST(self):
                self._handle()

            def _handle(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                parts = [p for p in u.path.split("/") if p]
                try:
                    if not parts:
                        self._err(404, "not found")
                    elif parts[0] == "healthz":
                        body = b"ok"
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    elif parts[0] == "pods":
                        self._serve_pods(q)
                    elif parts[0] == "stats":
                        self._serve_stats()
                    elif parts[0] == "containerLogs" and len(parts) == 4:
                        self._serve_logs(parts[1], parts[2], parts[3], q)
                    elif parts[0] in ("exec", "attach") and len(parts) == 4:
                        self._serve_exec(parts[0], parts[1], parts[2],
                                         parts[3], q)
                    elif parts[0] == "portForward" and len(parts) == 3:
                        self._serve_portforward(parts[1], parts[2], q)
                    elif parts[0] == "checkpoint" and len(parts) == 4:
                        self._serve_checkpoint(parts[1], parts[2], parts[3])
                    else:
                        self._err(404, f"no handler for {u.path}")
                except (BrokenPipeError, ConnectionResetError):
                    self.close_connection = True

            def _serve_pods(self, q) -> None:
                node = (q.get("node") or [None])[0]
                items = []
                with outer._lock:
                    kubelets = list(outer._kubelets.items())
                for name, k in kubelets:
                    if node is not None and name != node:
                        continue
                    items += [{"namespace": ns, "name": pod, "node": name}
                              for ns, pod in k.list_pod_keys()]
                self._json(200, {"kind": "PodList", "items": items})

            def _serve_stats(self) -> None:
                with outer._lock:
                    kubelets = list(outer._kubelets.items())
                nodes = []
                for name, k in kubelets:
                    pods = k.list_pod_keys()
                    nodes.append({"nodeName": name, "numPods": len(pods),
                                  "pods": [{"podRef": {"namespace": ns,
                                                       "name": pod}}
                                           for ns, pod in pods]})
                self._json(200, {"node": nodes[0] if len(nodes) == 1
                                 else None, "nodes": nodes})

            def _serve_logs(self, ns, pod, container, q) -> None:
                got = self._resolve(ns, pod, container)
                if got is None:
                    return
                runtime, _, cid = got
                follow = (q.get("follow") or ["false"])[0] == "true"
                tail = q.get("tailLines")
                try:
                    tail_n = int(tail[0]) if tail else None
                except ValueError:
                    self._err(400, f"invalid tailLines {tail[0]!r}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Connection", "close")
                self.end_headers()
                self.close_connection = True
                # a quiet follow writes nothing, so a vanished client is
                # only visible on the socket itself — probe it each idle
                # poll or the handler thread leaks until container exit
                stop = _ConnClosedProbe(self.connection) if follow \
                    else None
                try:
                    for line in runtime.read_logs(cid, follow=follow,
                                                  tail=tail_n, stop=stop):
                        self.wfile.write(line.encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass

            def _serve_exec(self, kind, ns, pod, container, q) -> None:
                got = self._resolve(ns, pod, container)
                if got is None:
                    return
                runtime, _, cid = got
                fs = streams.accept_upgrade(self)
                if fs is None:
                    return
                io = _ExecIO(fs)
                tty = (q.get("tty") or ["false"])[0] == "true"
                try:
                    if kind == "exec":
                        code = runtime.exec_stream(
                            cid, q.get("command") or [], io, tty=tty)
                    else:
                        code = runtime.attach_stream(cid, io, tty=tty)
                    fs.send_status(code)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    fs.close()

            def _serve_portforward(self, ns, pod, q) -> None:
                got_pod = outer.lookup(ns, pod)
                if got_pod is None:
                    self._err(404, f"pod {ns}/{pod} not found on node")
                    return
                kubelet, state = got_pod
                try:
                    port = int((q.get("port") or ["0"])[0])
                except ValueError:
                    self._err(400, "bad port")
                    return
                fs = streams.accept_upgrade(self)
                if fs is None:
                    return
                try:
                    kubelet.runtime.portforward_stream(
                        state["sandbox"], port, _PortIO(fs))
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    fs.close()

            def _serve_checkpoint(self, ns, pod, container) -> None:
                if self.command != "POST":
                    self._err(405, "POST required")
                    return
                got = self._resolve(ns, pod, container)
                if got is None:
                    return
                runtime, _, cid = got
                archive = runtime.checkpoint_container(cid)
                self._json(200, {"items": [archive]})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address
        self._thread: threading.Thread | None = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "KubeletServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="kubelet-server", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    # -- registry --------------------------------------------------------

    def register(self, kubelet) -> None:
        with self._lock:
            self._kubelets[kubelet.node_name] = kubelet

    def unregister(self, kubelet) -> None:
        with self._lock:
            if self._kubelets.get(kubelet.node_name) is kubelet:
                del self._kubelets[kubelet.node_name]

    def lookup(self, ns: str, pod: str):
        with self._lock:
            kubelets = list(self._kubelets.values())
        for k in kubelets:
            state = k.lookup_pod(ns, pod)
            if state is not None:
                return k, state
        return None
