"""Pod status manager.

Reference: pkg/kubelet/status — the kubelet's single writer to pod status:
callers set the local view; the manager syncs to the apiserver only when
the status actually changed (versioned cache), absorbing the N probe/PLEG
updates per change into one PATCH.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional, Tuple

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import PODS, Client
from ..store import kv

logger = logging.getLogger(__name__)


class StatusManager:
    def __init__(self, client: Client):
        self.client = client
        self._lock = threading.Lock()
        # uid -> (version, status); version bumps on every local set
        self._statuses: Dict[str, Tuple[int, dict]] = {}
        self._synced_version: Dict[str, int] = {}
        self.api_writes = 0  # observability: how many PATCHes actually went

    def set_pod_status(self, pod: Obj, status: dict) -> None:
        uid = meta.uid(pod)
        with self._lock:
            version, old = self._statuses.get(uid, (0, None))
            if old == status:
                return
            self._statuses[uid] = (version + 1, status)
        self._sync(pod)

    def get_pod_status(self, uid: str) -> Optional[dict]:
        with self._lock:
            entry = self._statuses.get(uid)
            return entry[1] if entry else None

    def remove_pod(self, uid: str) -> None:
        with self._lock:
            self._statuses.pop(uid, None)
            self._synced_version.pop(uid, None)

    def _sync(self, pod: Obj) -> None:
        uid = meta.uid(pod)
        with self._lock:
            entry = self._statuses.get(uid)
            if entry is None:
                return
            version, status = entry
            if self._synced_version.get(uid, -1) >= version:
                return
        try:
            def patch(p):
                p.setdefault("status", {}).update(status)
                return p
            self.client.guaranteed_update(PODS, meta.namespace(pod),
                                          meta.name(pod), patch)
            with self._lock:
                self._synced_version[uid] = version
                self.api_writes += 1
        except kv.NotFoundError:
            self.remove_pod(uid)
        except kv.StoreError as e:
            logger.warning("status sync failed for %s: %s",
                           meta.namespaced_name(pod), e)
