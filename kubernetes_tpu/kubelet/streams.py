"""Channel-framed bidirectional streaming over an HTTP/1.1 Upgrade.

Reference contract: the kubelet's interactive endpoints speak a
multiplexed stream protocol negotiated by HTTP upgrade — SPDY in
`staging/src/k8s.io/apimachinery/pkg/util/httpstream/spdy/` with the
channel semantics of `staging/src/k8s.io/apiserver/pkg/util/wsstream/`
(remotecommand v4: stdin/stdout/stderr/error/resize channels, JSON exit
status on the error channel).

This framework serves everything over plain HTTP/1.1, so the transport
is redesigned rather than translated: a `ktpu-stream` upgrade followed
by length-prefixed frames, one byte of channel + uint32 big-endian
payload length.  Every party that only moves bytes (the apiserver's
node tunnel, kubectl port-forward's socket pump) never parses frames —
the protocol is endpoint-to-endpoint, which is what lets the apiserver
relay stay a blind byte pump exactly like the reference's
UpgradeAwareProxy (`pkg/registry/core/pod/rest/subresources.go` ->
`proxy.NewUpgradeAwareHandler`).

Channels (remotecommand v4 numbering for the first five):
  0 stdin   client -> server
  1 stdout  server -> client
  2 stderr  server -> client
  3 error   server -> client, one JSON status object, ends the stream
  4 resize  client -> server, JSON {"Width": w, "Height": h}
  5 data    port-forward payload (both directions)
  6 perror  port-forward error (server -> client)
  255 close half-close notification; payload is the closed channel byte
"""

from __future__ import annotations

import json
import socket
import struct

PROTOCOL = "ktpu-stream"

STDIN, STDOUT, STDERR, ERROR, RESIZE = 0, 1, 2, 3, 4
PF_DATA, PF_ERROR = 5, 6
CLOSE = 255

_HEADER = struct.Struct("!BI")
MAX_FRAME = 4 << 20


class StreamError(Exception):
    """Transport-level failure (bad handshake, oversized frame)."""


class FrameSock:
    """Frame reader/writer over a connected socket.

    Writes are locked per-frame so concurrent producers (stdout pump +
    error status) interleave at frame granularity; reads are expected
    from a single consumer thread.
    """

    def __init__(self, sock: socket.socket):
        import threading
        self.sock = sock
        self._wlock = threading.Lock()
        self._rbuf = b""

    # -- write ----------------------------------------------------------

    def send(self, channel: int, payload: bytes = b"") -> None:
        with self._wlock:
            self.sock.sendall(_HEADER.pack(channel, len(payload)) + payload)

    def send_close(self, channel: int) -> None:
        self.send(CLOSE, bytes([channel]))

    def send_status(self, exit_code: int, message: str = "") -> None:
        """Terminal status on the error channel (remotecommand v4 shape)."""
        if exit_code == 0:
            body = {"status": "Success"}
        else:
            body = {"status": "Failure", "reason": "NonZeroExitCode",
                    "message": message or f"command terminated with "
                                          f"exit code {exit_code}",
                    "details": {"causes": [{"reason": "ExitCode",
                                            "message": str(exit_code)}]}}
        self.send(ERROR, json.dumps(body).encode())

    # -- read -----------------------------------------------------------

    def _read_exact(self, n: int) -> bytes | None:
        while len(self._rbuf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError:
                return None
            if not chunk:
                return None
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def recv(self) -> tuple[int, bytes] | None:
        """Next (channel, payload), or None at EOF/reset."""
        head = self._read_exact(_HEADER.size)
        if head is None:
            return None
        channel, length = _HEADER.unpack(head)
        if length > MAX_FRAME:
            raise StreamError(f"frame of {length} bytes exceeds cap")
        payload = self._read_exact(length) if length else b""
        if payload is None:
            return None
        return channel, payload

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def parse_exit_status(payload: bytes) -> tuple[int, str]:
    """Exit code + message from an ERROR-channel status frame."""
    try:
        st = json.loads(payload.decode() or "{}")
    except json.JSONDecodeError:
        return 1, payload.decode(errors="replace")
    if st.get("status") == "Success":
        return 0, ""
    for cause in ((st.get("details") or {}).get("causes") or ()):
        if cause.get("reason") == "ExitCode":
            try:
                return int(cause.get("message", 1)), st.get("message", "")
            except ValueError:
                pass
    return 1, st.get("message", "")


# -- server side (inside a BaseHTTPRequestHandler) ----------------------

def accept_upgrade(handler) -> FrameSock | None:
    """Complete the 101 handshake on `handler` and hand back the raw
    connection as a FrameSock.  Returns None (after writing a 400) when
    the client didn't ask for our protocol."""
    conn_hdr = (handler.headers.get("Connection") or "").lower()
    if (handler.headers.get("Upgrade") != PROTOCOL
            or "upgrade" not in conn_hdr):
        body = json.dumps({"kind": "Status", "status": "Failure",
                           "code": 400, "reason": "BadRequest",
                           "message": f"upgrade to {PROTOCOL} required"})
        handler.send_response(400)
        handler.send_header("Content-Type", "application/json")
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body.encode())
        return None
    handler.send_response_only(101, "Switching Protocols")
    handler.send_header("Upgrade", PROTOCOL)
    handler.send_header("Connection", "Upgrade")
    handler.end_headers()
    handler.wfile.flush()
    handler.close_connection = True
    return FrameSock(handler.connection)


# -- client side --------------------------------------------------------

def open_upgrade(host: str, port: int, path: str,
                 headers: dict[str, str] | None = None,
                 timeout: float = 30.0, ssl_context=None) -> FrameSock:
    """POST `path` with an upgrade request; raise StreamError carrying
    the server's error body on anything but 101.  `ssl_context` wraps
    the connection for a TLS apiserver."""
    sock = socket.create_connection((host, port), timeout=timeout)
    if ssl_context is not None:
        sock = ssl_context.wrap_socket(sock, server_hostname=host)
    try:
        req = [f"POST {path} HTTP/1.1", f"Host: {host}:{port}",
               "Connection: Upgrade", f"Upgrade: {PROTOCOL}"]
        for k, v in (headers or {}).items():
            req.append(f"{k}: {v}")
        sock.sendall(("\r\n".join(req) + "\r\n\r\n").encode())
        # read the response head
        head = b""
        while b"\r\n\r\n" not in head:
            chunk = sock.recv(4096)
            if not chunk:
                raise StreamError("connection closed during handshake")
            head += chunk
            if len(head) > 65536:
                raise StreamError("oversized handshake response")
        head_text, _, rest = head.partition(b"\r\n\r\n")
        lines = head_text.decode(errors="replace").split("\r\n")
        try:
            status = int(lines[0].split()[1])
        except (IndexError, ValueError):
            raise StreamError(f"bad status line {lines[0]!r}") from None
        if status != 101:
            # non-upgrade response: collect what body we can for the error
            hdrs = {}
            for ln in lines[1:]:
                k, _, v = ln.partition(":")
                hdrs[k.strip().lower()] = v.strip()
            want = int(hdrs.get("content-length") or 0)
            body = rest
            sock.settimeout(5.0)
            while len(body) < want:
                chunk = sock.recv(4096)
                if not chunk:
                    break
                body += chunk
            message = body.decode(errors="replace")
            try:
                message = json.loads(message).get("message", message)
            except (json.JSONDecodeError, AttributeError):
                pass
            raise StreamError(f"upgrade refused: {status} {message}")
        sock.settimeout(None)
        fs = FrameSock(sock)
        fs._rbuf = rest  # frames may ride the handshake packet
        return fs
    except Exception:
        sock.close()
        raise
