"""Batched assignment solvers (greedy scan; auction/sinkhorn to follow)."""

from .assign import build_assign_fn  # noqa: F401
