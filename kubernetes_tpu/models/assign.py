"""Batched pod->node assignment on device.

This is the TPU replacement for the reference's HOT LOOPS (SURVEY.md §3.1):
  findNodesThatPassFilters (schedule_one.go:512)  -> feasibility masks
  RunScorePlugins          (runtime/framework.go:903) -> score matrix
  selectHost               (schedule_one.go:777)  -> masked argmax (the
      reference breaks score ties by reservoir sampling; we break them with
      fixed pseudo-random noise, which also de-correlates claims)
  + the implicit cache.assume() between per-pod cycles -> running aggregate
    state (resources, pod counts, host ports, topology/affinity domain
    counts) updated as placements commit (SURVEY.md §7 hard part #1).

Two solvers share the static phase:

  WAVE (default, the TPU-native design): every pending pod claims its
    argmax node simultaneously; conflicts are resolved in pod (queue) order
    with [P,P] prefix matrices — earlier claimants' requests are
    prefix-summed per node, and constraint-carrying claimants into the same
    topology domain are serialized one-per-wave; losers retry next wave
    against updated aggregates.  A batch converges in O(contention) waves
    (typically 2-6), each wave a handful of [P,N] vectorized ops + small
    [P,P] matmuls — no sequential scan, so device time is independent of
    batch size for uncontended workloads.  Placements are feasible at
    commit time exactly like the sequential path; *which* feasible node a
    pod gets can differ from strict one-at-a-time order (the reference
    itself is nondeterministic here: random tie-break + node sampling).

  SCAN (mode="scan"): strict one-pod-at-a-time lax.scan, bit-faithful to
    sequential semantics; used as the parity oracle and for tiny batches.

Conservative wave-conflict rules (reject -> retry, never accept wrongly):
  - resources/pod-count: prefix-sum of ALL earlier same-node claimants
  - host ports: any earlier same-node claimant with overlapping ports
  - spread/anti-affinity: any earlier claimant that increments the same
    selector-group into the same topology domain
  - affinity bootstrap (first pod of a self-affine group): any earlier
    claimant incrementing the group anywhere
  - existing-pod anti-affinity groups (asg): any earlier claimant carrying
    a matching anti-term into the claimed domain

Multi-chip: the node axis shards across a jax Mesh (parallel/mesh.py wraps
this in shard_map); cross-node reductions go through _Comm (pmax/pmin/psum
over ICI), per-pod argmax is per-shard top-1 + all_gather + pick, and
gathers by global node index are psum-of-owner.  The [P,P] conflict
matrices are slab-partitioned: gather_cols_rs reduce-scatters so each
shard resolves a contiguous P/n_shards pod slab (the same addends and
per-row reduction order as the replicated all-reduce form, so results are
bit-identical), and the [P]-bool verdicts merge with a small tiled
all-gather.  All collectives are XLA ICI collectives — no NCCL on TPU
(SURVEY.md §2.6).

All shapes are static (derived from flatten.Caps); one compile serves every
batch.
"""

from __future__ import annotations

import os
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.flatten import (
    C_AFFINITY, C_ANTI_AFFINITY, C_NONE, C_PREF_AFFINITY, C_SPREAD_HARD,
    C_SPREAD_SCORE, CORE_R, Caps,
)

NEG = -1e9
# process-local: per-process debug scratch; never read cross-process
_WAVE_DEBUG: list = []  # populated only under KTPU_WAVE_DEBUG + eager mode
TIE_NOISE = 0.05  # breaks exact score ties only (real score deltas >> this).
# Must stay ABOVE f32 resolution at score scale (~200 * 1.2e-7 * n_cap per
# whole-axis gradient): at 1e-3 the per-node deltas rounded away at
# n_cap >= ~1k and every same-preference claimant argmaxed onto the same
# first node (node-capacity serialization).
COHORT_ITERS = 3  # spread water-filling fixpoint rounds per wave (round 1
# fills to the legal level in one shot; extra rounds catch stragglers
# whose level rose with round-1 commits)

# Kernel feature flags.  The device endpoint has high per-op overhead, so
# the backend compiles specialized variants: a batch with no selectors /
# constraints / host ports (the common case) runs a kernel with those code
# paths elided entirely.
ALL_FEATURES = frozenset({"selectors", "ports", "constraints", "asg", "pin",
                          "prefer"})
PLAIN_FEATURES = frozenset()


class _Comm:
    """Reduction layer: local ops when axis_name is None, ICI collectives
    inside shard_map otherwise.  `n_shards` (the mesh size) lets the wave
    solver slab-partition its [P,P] conflict matrices: gather_cols_rs
    returns only this shard's contiguous row slab via reduce-scatter
    instead of materializing the replicated all-reduce result."""

    def __init__(self, axis_name: str | None, n_shards: int = 1):
        self.axis = axis_name
        self.n_shards = n_shards if axis_name else 1

    def psum(self, x):
        return lax.psum(x, self.axis) if self.axis else x

    def any_rows(self, m):
        """any over the node axis of [P,N] bool -> [P]."""
        a = jnp.any(m, axis=-1)
        return self.psum(a.astype(jnp.int32)) > 0 if self.axis else a

    def rowmax(self, x, mask, fill):
        m = jnp.max(jnp.where(mask, x, fill), axis=-1, keepdims=True)
        return lax.pmax(m, self.axis) if self.axis else m

    def rowmin(self, x, mask, fill):
        m = jnp.min(jnp.where(mask, x, fill), axis=-1, keepdims=True)
        return lax.pmin(m, self.axis) if self.axis else m

    def row_argmax(self, score, n_loc: int):
        """Per-row global argmax of [P,N(sharded)] -> global indices [P]."""
        best = jnp.max(score, axis=-1)                       # [P]
        idx = jnp.argmax(score, axis=-1)                     # [P]
        if not self.axis:
            return idx, best
        best_all = lax.all_gather(best, self.axis)           # [S,P]
        idx_all = lax.all_gather(idx, self.axis)             # [S,P]
        shard = jnp.argmax(best_all, axis=0)                 # [P]
        p_iota = jnp.arange(idx.shape[0])
        j = shard * n_loc + idx_all[shard, p_iota]
        return j, best_all[shard, p_iota]

    def my_offset(self, n_loc: int):
        if not self.axis:
            return 0
        return lax.axis_index(self.axis) * n_loc

    def gather_cols(self, arr, gidx, offset, n_loc: int, fill=0.0):
        """arr[..., gidx] where gidx are GLOBAL node indices and arr holds
        the local shard of the node axis (last dim).  Out-of-range gidx
        (e.g. -1) yield `fill`."""
        local = gidx - offset
        inrange = (local >= 0) & (local < n_loc) & (gidx >= 0)
        vals = jnp.take(arr, jnp.clip(local, 0, n_loc - 1), axis=-1)
        vals = jnp.where(inrange, vals, 0)
        if self.axis:
            vals = lax.psum(vals, self.axis)
        if fill != 0.0:
            seen = inrange if not self.axis else (
                lax.psum(inrange.astype(jnp.int32), self.axis) > 0)
            vals = jnp.where(seen, vals, fill)
        return vals

    def gather_cols_rs(self, arr, gidx, offset, n_loc: int, fill=0.0):
        """gather_cols, slab form: same psum-of-owner addends, but each
        shard keeps only its contiguous rows/n_shards slab of the leading
        axis via reduce-scatter — for a [P,P] conflict matrix this ships
        1/S of the all-reduce bytes and every (row, col) cell is still
        exact (exactly one shard owns col's claimed node; the reduction
        sums one non-zero contribution).  Leading axis must divide by
        n_shards; callers fall back to gather_cols when it doesn't."""
        local = gidx - offset
        inrange = (local >= 0) & (local < n_loc) & (gidx >= 0)
        vals = jnp.take(arr, jnp.clip(local, 0, n_loc - 1), axis=-1)
        vals = jnp.where(inrange, vals, 0)
        vals = lax.psum_scatter(vals, self.axis, scatter_dimension=0,
                                tiled=True)
        if fill != 0.0:
            # seen is per-COLUMN (the gathered q axis), so the full [P]
            # mask broadcasts over the slab rows unchanged
            seen = lax.psum(inrange.astype(jnp.int32), self.axis) > 0
            vals = jnp.where(seen, vals, fill)
        return vals


def _static_mask_and_score(node: dict, pod: dict, comm: _Comm, offset,
                           features: frozenset = ALL_FEATURES):
    """Vectorized P x N feasibility independent of in-batch placements.

    Returns (sel_mask, static_mask, static_score):
      sel_mask    - node-affinity/selector-only eligibility (used for the
                    spread min-match domain set, which the reference computes
                    over affinity-eligible nodes only, filtering.go:261)
      static_mask - sel_mask AND taints AND nodeName pin AND validity
      static_score- PreferNoSchedule taint score contribution (0..100)
    """
    valid = node["valid"][None, :]                        # [1,N]
    N = node["valid"].shape[0]
    P = pod["req"].shape[0]

    if "selectors" in features:
        # label/key masks exist in the node dict ONLY for the selector-
        # carrying (full) variant — the plain variant's static pytree
        # omits them so ~140 MB of masks never ship to device at 100k
        # nodes (ops/backend.py _upload_static split)
        label = node["label_mask"]                        # [N,L]
        keym = node["key_mask"]                           # [N,KL]
        hits = jnp.einsum("pgl,nl->pgn", pod["sel_any"], label)
        group_ok = (hits > 0) | (pod["sel_any_active"][:, :, None] == 0)
        sel_ok = jnp.all(group_ok, axis=1)                # [P,N]
        khits = jnp.einsum("pgk,nk->pgn", pod["key_any"], keym)
        kgroup_ok = (khits > 0) | (pod["key_any_active"][:, :, None] == 0)
        sel_ok &= jnp.all(kgroup_ok, axis=1)
        sel_ok &= (pod["sel_forb"] @ label.T) == 0        # NotIn
        sel_ok &= (pod["key_forb"] @ keym.T) == 0         # DoesNotExist
        sel_mask = sel_ok & valid
    else:
        sel_mask = jnp.broadcast_to(valid, (P, N))

    hard = (pod["untol_hard"] @ node["taint_mask"].T) == 0
    static_mask = sel_mask & hard
    if "pin" in features:
        n_idx = offset + jnp.arange(N)[None, :]
        pin = ((pod["node_row"][:, None] < 0)
               | (n_idx == pod["node_row"][:, None]))
        static_mask = static_mask & pin

    if "prefer" in features:
        prefer_cnt = pod["untol_prefer"] @ node["taint_mask"].T   # [P,N]
        mx = comm.rowmax(prefer_cnt, static_mask, 0.0)
        static_score = jnp.where(
            mx > 0, (mx - prefer_cnt) * 100.0 / jnp.maximum(mx, 1.0), 100.0)
    else:
        static_score = jnp.zeros((P, 1), jnp.float32)
    return sel_mask, static_mask, static_score


def _fold_ns_masks(node: dict, pod: dict) -> dict:
    """Namespace gate for namespaceSelector terms: AND each pod's group
    MEMBERSHIP vectors (inc_sg, match_asg) with the per-slot namespace
    masks, selecting the column of the pod's namespace vocab id (last
    column = outside-vocab).  The host encoder already folds namespace
    membership into these bits from the SAME resolved sets, so on a
    correct host this multiply is idempotent — it exists as structural
    enforcement: a stale host fold can only over-block, never admit a
    placement the resolution forbids.  inc_asg is deliberately NOT
    gated: it marks the pod as a term CARRIER (its count must enter
    cd_asg regardless of the pod's own namespace).  Plain-namespace
    slots carry all-ones mask rows, so batches without namespaceSelector
    terms pay two [P, cap]-scale multiplies and nothing else."""
    sgm = node.get("sg_ns_mask")
    col = pod.get("pod_ns")
    if sgm is None or col is None:
        return pod
    pod = dict(pod)
    pod["inc_sg"] = pod["inc_sg"] * sgm[:, col].T
    asgm = node.get("asg_ns_mask")
    if asgm is not None:
        pod["match_asg"] = pod["match_asg"] * asgm[:, col].T
    return pod


def _fit_scores_vec(req_nz, alloc, used_nz):
    """LeastAllocated + BalancedAllocation over cpu/mem: [P,N] each.
    Written as 2-D ops (never materializes [P,N,R]) because the device
    endpoint prices ops by count/bytes, not FLOPs.  For exactly two
    resources, std == |u_cpu - u_mem| / 2."""
    utils = []
    for r in range(2):
        a = alloc[None, :, r]
        u = used_nz[None, :, r] + req_nz[:, None, r]      # [P,N]
        utils.append(jnp.where(a > 0, jnp.minimum(u / jnp.maximum(a, 1.0), 1.0), 1.0))
    ucpu, umem = utils
    least = (2.0 - ucpu - umem) * 50.0
    balanced = (1.0 - jnp.abs(ucpu - umem) * 0.5) * 100.0
    return least, balanced


HARD_KINDS_SERIAL = (C_SPREAD_HARD, C_ANTI_AFFINITY)


def make_assign_core(caps: Caps, weights: dict[str, float] | None = None,
                     axis_name: str | None = None, mode: str = "wave",
                     max_waves: int = 128,
                     features: frozenset = ALL_FEATURES,
                     n_shards: int = 1):
    w = {"fit": 1.0, "balanced": 1.0, "spread": 2.0, "affinity": 1.0,
         "taint": 1.0, **(weights or {})}
    comm = _Comm(axis_name, n_shards)
    if mode == "scan":
        return _make_scan_core(caps, w, comm)
    return _make_wave_core(caps, w, comm, max_waves, features)


# ---------------------------------------------------------------------------
# WAVE solver
# ---------------------------------------------------------------------------

TAIL_P = 512  # compacted straggler sub-batch size (tail compaction)


def _make_wave_core(caps: Caps, w: dict, comm: _Comm, max_waves: int,
                    features: frozenset = ALL_FEATURES):
    f_ports = "ports" in features
    f_cons = "constraints" in features
    f_asg = "asg" in features
    # PLAIN single-device waves run the fused Pallas tile kernel for the
    # [P,N] mask+score+argmax (ops/pallas_kernels.py); everything else
    # (conflict resolution, commits) is unchanged XLA.  The kernel bakes in
    # the default fit/balanced weights, so custom weights take the XLA path
    from ..ops import pallas_kernels as pk
    use_pallas = (not features and comm.axis is None and pk.pallas_enabled()
                  and w["fit"] == 1.0 and w["balanced"] == 1.0)

    def assign(node: dict, pod: dict) -> dict[str, jnp.ndarray]:
        n_loc = node["alloc"].shape[0]
        pod = _fold_ns_masks(node, pod)
        P = pod["req"].shape[0]
        offset = comm.my_offset(n_loc)
        sel_mask, static_mask, static_score = _static_mask_and_score(
            node, pod, comm, offset, features)
        # deterministic tie-break noise keyed on (pod, GLOBAL node) so the
        # result is identical regardless of how the node axis is sharded
        # (reference: selectHost reservoir sample breaks ties randomly)
        gn = (offset + jnp.arange(n_loc)).astype(jnp.uint32)
        pp = jnp.arange(P, dtype=jnp.uint32)
        # pseudo-random tie-break keyed on (pod, GLOBAL node): uniform per
        # cell, so claims stay spread under ANY occupancy pattern (a
        # structured cyclic gradient was tried — 1 wave on an empty
        # cluster — but under fragmentation every claimant's
        # first-feasible collapsed to the same few nodes and
        # anti-affinity serialized to ~1 pod/wave).  Deterministic and
        # shard-invariant, same contract as the reference's selectHost
        # random tie-break (schedule_one.go:777).  Integer mix (murmur3
        # finalizer), NOT a sin() hash: f32 sin of large arguments is not
        # correctly rounded, so XLA's constant folder (offset=0 path) and
        # the runtime vectorized libm disagree in the low bits — which
        # breaks bit-identical single-vs-sharded parity.  Modular uint32
        # arithmetic and the exact 2^-24 scale are reproducible under any
        # fusion/folding.
        hx = (pp[:, None] * jnp.uint32(0x9E3779B1)
              ^ gn[None, :] * jnp.uint32(0x85EBCA77))
        hx ^= hx >> 16
        hx *= jnp.uint32(0x85EBCA6B)
        hx ^= hx >> 13
        hx *= jnp.uint32(0xC2B2AE35)
        hx ^= hx >> 16
        noise = (hx >> 8).astype(jnp.float32) * (TIE_NOISE / (1 << 24))
        alloc = node["alloc"]
        # absent in the plain variant's static pytree (only f_cons/f_asg
        # blocks read them; those elide when the features are off)
        dom_sg, dom_asg = node.get("dom_sg"), node.get("dom_asg")
        pk_static = (pk.prepare_static(pod["req"], pod["req_nz"], alloc,
                                       node["maxpods"], static_mask)
                     if use_pallas else None)

        # TAIL COMPACTION (constraint variants): the first wave of a hard-
        # constraint batch admits ~98-99% (water-filling cohort); the
        # straggler waves each re-ran the FULL [P,P] conflict matrices +
        # [P,N] planes to admit a handful of pods (measured 26.5 ms/wave
        # at P=4096/N=1280, 5 tail waves for the last ~50 pods).  When
        # the active set fits TAIL_P, the remaining waves run on a
        # COMPACTED sub-batch gathered to the front — [P,P] terms shrink
        # 64x at P=4096 -> 512 — inside the SAME device call, so the
        # host-side retry kernel's extra round trips (KTPU_FULL_MAIN_WAVES,
        # a tunnel loss) are not needed.  Semantics are identical: the
        # sub-batch reruns the same wave body against the same resident
        # state, and queue-order fairness within a wave is preserved by
        # the gather (top_k indices are ascending among equal activity).
        # Applies to the PLAIN path too (round-5 measurement: a 16k-pod
        # plain batch at 100k nodes averages ~1.6 waves, and wave 2 re-
        # ran the whole [P,N] tile for a handful of stragglers at
        # ~300-500ms); the compacted tail always runs the XLA wave body
        # (pk_staticv=None below), so a Pallas main phase hands its
        # stragglers to a cheap [TAIL_P,N] XLA loop.
        tail_p = TAIL_P if P > TAIL_P else 0

        def mk_wave(podv, sel_maskv, static_maskv, static_scorev, noisev,
                    pk_staticv):
            Pv = podv["req"].shape[0]
            req, req_nz = podv["req"], podv["req_nz"]
            earlier = jnp.tril(jnp.ones((Pv, Pv), jnp.float32), k=-1)  # q<p
            p_iota = jnp.arange(Pv)
            # REDUCE-SCATTER slab mode (multi-chip): instead of every
            # shard materializing the replicated [P,P] conflict matrices
            # through all-reduce (the SCALING.md multi-chip cost center:
            # s32[P,P] per constraint slot per wave), each shard resolves
            # a contiguous P/S pod slab — gather_cols_rs keeps only the
            # slab rows, the per-row reductions over the full q axis run
            # unchanged (same addends, same order -> bit-identical), and
            # the [P]-bool verdicts merge with a small tiled all-gather.
            # Applies to the compacted tail sub-batch too (TAIL_P divides
            # by any power-of-two mesh), so the round-5 tail-compaction
            # trick runs per shard.  Falls back to the all-reduce path
            # when the pod axis doesn't divide by the mesh.
            # KTPU_RS_DISABLE forces that fallback (read at trace time) —
            # the LATENCY.md/SCALING.md A/B baseline, not a runtime knob.
            rs = bool(comm.axis) and comm.n_shards > 1 \
                and Pv % comm.n_shards == 0 \
                and not os.environ.get("KTPU_RS_DISABLE")
            P_S = Pv // comm.n_shards if rs else Pv
            s_iota = jnp.arange(P_S)
            pod, sel_mask, static_mask, static_score, noise = (
                podv, sel_maskv, static_maskv, static_scorev, noisev)
            P = Pv

            def wave(state):
                (used, used_nz, npods, ports, cd_sg, cd_asg,
                 assigned, active, _progress, wcount) = state

                avail = alloc - used                              # [N,R]
                if pk_staticv is not None:  # Pallas main phase only; the
                    # compacted tail runs the XLA body below
                    # fused Pallas [P,N] pass straight to per-pod claims
                    claims, _best = pk.claims(pk_staticv, active, used, used_nz,
                                              npods)
                    has = claims >= 0
                    return _resolve_and_commit(state, claims, has, [], [],
                                               avail)

                # per-resource 2-D compares instead of one [P,N,R] broadcast
                fit = (npods + 1.0 <= node["maxpods"])[None, :]
                for r in range(caps.r):
                    fit &= req[:, None, r] <= avail[None, :, r]
                mask = static_mask & fit
                if f_ports:
                    mask &= (pod["ports"] @ ports.T) == 0         # [P,N]

                if f_asg:
                    # existing anti-affinity groups block
                    adom = jnp.clip(dom_asg, 0)
                    acnt = jnp.take_along_axis(cd_asg, adom, axis=1)  # [ASG,N]
                    acnt = jnp.where(dom_asg >= 0, acnt, 0.0)
                    blocked = (pod["match_asg"] @ (acnt > 0).astype(jnp.float32)) > 0
                    mask &= ~blocked

                least, balanced = _fit_scores_vec(req_nz, alloc, used_nz)
                score = w["fit"] * least + w["balanced"] * balanced
                score = score + w["taint"] * static_score

                # constraints.  Domain counts are gathered ONCE per wave at
                # the GROUP level ([SG,N] — 16 x n_loc elements), and each
                # constraint slot row-selects by its sg index; the previous
                # per-slot [P,N] element gather (take_along_axis with per-pod
                # index planes) dominated wave time on TPU, where scattered
                # gathers bypass the vector units (~375ms/wave at 1024x5632
                # measured; row selects are plain copies).
                if f_cons:
                    gath_sg_all = jnp.where(
                        dom_sg >= 0,
                        jnp.take_along_axis(cd_sg, jnp.clip(dom_sg, 0), axis=1),
                        0.0)                                      # [SG,N]
                boot_flags = []     # [P] per c: relies on bootstrap this wave
                minmatches = []     # [P,1] per c: min domain count (spread)
                for c in range(caps.c_cap if f_cons else 0):
                    kind = pod["c_kind"][:, c]                    # [P]
                    sg = jnp.clip(pod["c_sg"][:, c], 0)
                    dom_rows = dom_sg[sg]                         # [P,N] row sel
                    cnt_rows = cd_sg[sg]                          # [P,D] row sel
                    gathered = gath_sg_all[sg]                    # [P,N] row sel
                    has_dom = dom_rows >= 0
                    active_c = (kind != C_NONE)[:, None]

                    elig = sel_mask & has_dom
                    minmatch = comm.rowmin(gathered, elig, jnp.inf)
                    minmatch = jnp.where(jnp.isfinite(minmatch), minmatch, 0.0)
                    total = jnp.sum(cnt_rows, axis=-1, keepdims=True)  # cd replicated

                    selfm = pod["c_selfmatch"][:, c:c + 1]
                    maxskew = pod["c_maxskew"][:, c:c + 1]
                    spread_ok = ((gathered + selfm - minmatch) <= maxskew) & has_dom
                    boot = (total[:, 0] == 0) & (selfm[:, 0] > 0)
                    aff_ok = ((gathered > 0) | boot[:, None]) & has_dom
                    anti_ok = jnp.where(has_dom, gathered == 0, True)

                    kindb = kind[:, None]
                    ok = jnp.where(kindb == C_SPREAD_HARD, spread_ok,
                                   jnp.where(kindb == C_AFFINITY, aff_ok,
                                             jnp.where(kindb == C_ANTI_AFFINITY,
                                                       anti_ok, True)))
                    mask &= ok | ~active_c

                    smx = comm.rowmax(gathered, mask, 0.0)
                    smn = comm.rowmin(gathered, mask, jnp.inf)
                    smn = jnp.where(jnp.isfinite(smn), smn, 0.0)
                    rng = jnp.maximum(smx - smn, 1.0)
                    spread_score = (smx - gathered) * 100.0 / rng
                    score += jnp.where(kindb == C_SPREAD_SCORE,
                                       w["spread"] * spread_score, 0.0)
                    score += jnp.where(kindb == C_PREF_AFFINITY,
                                       w["affinity"] * pod["c_weight"][:, c:c + 1]
                                       * gathered, 0.0)
                    boot_flags.append((kind == C_AFFINITY) & boot)
                    minmatches.append(minmatch)

                feasible = mask & active[:, None]
                has = comm.any_rows(feasible)                     # [P]
                claims, _ = comm.row_argmax(
                    jnp.where(feasible, score + noise, NEG), n_loc)
                claims = jnp.where(has, claims, -1)               # global idx
                return _resolve_and_commit(state, claims, has, boot_flags,
                                           minmatches, avail)

            def _resolve_and_commit(state, claims, has, boot_flags, minmatches,
                                    avail):
                """Wave tail shared by the Pallas and XLA paths: conflict
                resolution in pod/queue order + aggregate commit."""
                (used, used_nz, npods, ports, cd_sg, cd_asg,
                 assigned, active, _progress, wcount) = state

                # ---- conflict resolution (pod/queue order) ----
                # claims are GLOBAL indices: same-node is a [P,P] outer equality,
                # no N-sized contraction needed
                loc_claims = claims - offset
                in_shard = (loc_claims >= 0) & (loc_claims < n_loc) & has
                onehot = ((loc_claims[:, None] == jnp.arange(n_loc)[None, :])
                          & in_shard[:, None]).astype(jnp.float32)  # [P,N] local
                SN = ((claims[:, None] == claims[None, :])
                      & has[:, None] & has[None, :]).astype(jnp.float32)
                E = SN * earlier                                  # earlier same-node

                prefR = E @ req                                   # [P,R]
                prefN = jnp.sum(E, axis=1)                        # [P]
                avail_claim = comm.gather_cols(avail.T, claims, offset, n_loc)
                avail_claim = jnp.moveaxis(avail_claim, -1, 0)    # [P,R]
                npods_claim = comm.gather_cols(npods, claims, offset, n_loc)
                maxp_claim = comm.gather_cols(node["maxpods"], claims, offset, n_loc)
                res_ok = jnp.all(req + prefR <= avail_claim, axis=-1)
                res_ok &= (npods_claim + prefN + 1.0 <= maxp_claim)

                if f_ports:
                    overlap = (pod["ports"] @ pod["ports"].T) > 0  # [P,P]
                    port_conf = jnp.sum(E * overlap, axis=1) > 0
                else:
                    port_conf = jnp.zeros(P, bool)

                conf = jnp.zeros(P, bool)
                spread_over_any = jnp.zeros(P, bool)   # failed the static quota
                both = (has[:, None] & has[None, :]).astype(jnp.float32) * earlier
                if rs:
                    slab_lo = lax.axis_index(comm.axis) * P_S
                    sl = functools.partial(
                        lax.dynamic_slice_in_dim, start_index=slab_lo,
                        slice_size=P_S, axis=0)

                    def unsl(x):  # slab verdicts -> replicated full [P]
                        return lax.all_gather(x, comm.axis, tiled=True)

                    both_s = sl(both)                             # [P_S,P]
                    conf_s = jnp.zeros(P_S, bool)
                    spread_s = jnp.zeros(P_S, bool)
                Dpqs = []   # rs: per-slot [P_S,P] slabs, reused by cohort
                for c in range(caps.c_cap if f_cons else 0):
                    kind = pod["c_kind"][:, c]
                    sg = jnp.clip(pod["c_sg"][:, c], 0)
                    dom_rows = dom_sg[sg]                         # [P,N] local
                    if rs:
                        # slab of the [P,P] matrix: dom of q's claim under
                        # p's sg, for this shard's P/S rows only
                        Dpq = comm.gather_cols_rs(dom_rows, claims, offset,
                                                  n_loc, fill=-1.0)  # [P_S,P]
                        Dpqs.append(Dpq)
                        kind_s, sg_s = sl(kind), sl(sg)
                        own = Dpq[s_iota, slab_lo + s_iota]       # [P_S]
                        same_dom = (Dpq == own[:, None]) & (own[:, None] >= 0)
                        q_incs = pod["inc_sg"].T[sg_s]            # [P_S,P]
                        k_same = jnp.sum(both_s * same_dom * q_incs, axis=1)
                        conf_s |= (kind_s == C_ANTI_AFFINITY) & (k_same > 0)
                        cnt_own = cd_sg[sg_s, jnp.clip(own, 0)
                                        .astype(jnp.int32)]       # [P_S]
                        over = (cnt_own + sl(pod["c_selfmatch"][:, c])
                                + k_same - sl(minmatches[c][:, 0])) \
                            > sl(pod["c_maxskew"][:, c])
                        is_spread = (kind_s == C_SPREAD_HARD) & (own >= 0)
                        spread_s |= is_spread & over
                        conf_s |= sl(boot_flags[c]) & (
                            jnp.sum(both_s * q_incs, axis=1) > 0)
                        continue
                    Dpq = comm.gather_cols(dom_rows, claims, offset, n_loc,
                                           fill=-1.0)             # [P,P]: dom of q's claim under p's sg
                    own = Dpq[p_iota, p_iota][:, None]            # [P,1] p's own domain
                    same_dom = (Dpq == own) & (own >= 0)
                    q_incs = pod["inc_sg"].T[sg]                  # [P,P]: inc of q for p's sg
                    k_same = jnp.sum(both * same_dom * q_incs, axis=1)  # [P]
                    # required anti-affinity: both entrants see gathered==0, so
                    # any earlier same-domain incrementer must serialize
                    conf |= (kind == C_ANTI_AFFINITY) & (k_same > 0)
                    # HARD spread static quota: count + self + k_earlier - min
                    # <= maxSkew is valid at ANY interleaving (the min can only
                    # rise as other claims commit).  Pods over the static quota
                    # are NOT immediately conflicted — the cohort pass below
                    # re-admits ranks that a round-robin interleaving covers.
                    own = Dpq[p_iota, p_iota]                     # [P] own domain
                    cnt_own = cd_sg[jnp.clip(sg, 0), jnp.clip(own, 0)
                                    .astype(jnp.int32)]           # [P]
                    minm = minmatches[c][:, 0]
                    selfm_c = pod["c_selfmatch"][:, c]
                    skew_c = pod["c_maxskew"][:, c]
                    over = (cnt_own + selfm_c + k_same - minm) > skew_c
                    is_spread = (kind == C_SPREAD_HARD) & (own >= 0)
                    spread_over_any |= is_spread & over
                    # affinity bootstrap: serialize against any incrementing q
                    conf |= boot_flags[c] & (jnp.sum(both * q_incs, axis=1) > 0)
                if rs and f_cons:
                    conf |= unsl(conf_s)
                    spread_over_any |= unsl(spread_s)
                for a in range(caps.asg_cap if f_asg else 0):
                    dom_a = comm.gather_cols(dom_asg[a], claims, offset, n_loc,
                                             fill=-1.0)           # [P]
                    same_a = (dom_a[:, None] == dom_a[None, :]) & (dom_a[:, None] >= 0)
                    conf |= (pod["match_asg"][:, a] > 0) & (
                        jnp.sum(both * same_a * pod["inc_asg"][None, :, a], axis=1) > 0)

                accept = has & active & res_ok & ~port_conf & ~conf \
                    & ~spread_over_any
                if f_cons:
                    # ---- spread cohort (water-filling) admission ----
                    # The static quota admits ~maxSkew pods per domain per
                    # wave -> O(batch/(domains*skew)) waves (measured 1377
                    # for 4096 pods / 3 zones / skew 1).  Water-filling: a
                    # pour that lands on a current-minimum domain is ALWAYS
                    # sequentially valid (count+1-min = 1 <= maxSkew), so any
                    # end state reachable by filling lowest-domains-first is
                    # valid.  Pours can raise every domain to
                    #   L = min over eligible domains of
                    #         (count + committed + pool) + maxSkew
                    # (the stuck minimum after every pool drains is >= the
                    # min term, and levels above it stay within the skew).
                    # A candidate at new-rank r' in domain d therefore admits
                    # when count_d + committed_d + r' + self <= L.  Pods with
                    # more than one hard-spread slot are excluded from pools
                    # and cohort (their commit depends on the OTHER slot, so
                    # counting them could overstate a pool); they fall back
                    # to the static quota.  Two fixpoint rounds let the first
                    # round's commits raise the second round's levels.
                    other_ok = has & active & res_ok & ~port_conf & ~conf
                    n_hard = jnp.zeros(P, jnp.int32)
                    for c in range(caps.c_cap):
                        n_hard = n_hard + (
                            pod["c_kind"][:, c] == C_SPREAD_HARD).astype(
                            jnp.int32)
                    cand = other_ok & spread_over_any & (n_hard <= 1)
                    dom_acc0 = comm.gather_cols(dom_sg, claims, offset, n_loc,
                                                fill=-1.0)        # [SG,P]
                    sg_iota2 = jnp.arange(caps.sg_cap)[:, None]
                    dom_acc0_ix = jnp.clip(dom_acc0, 0).astype(jnp.int32)
                    committed = accept
                    for _it in range(COHORT_ITERS):
                        new_ok = cand & ~committed
                        comm_f = committed.astype(jnp.float32)
                        new_f = new_ok.astype(jnp.float32)
                        ok_all = new_ok
                        if rs:
                            ok_all_s = sl(new_ok)                 # [P_S]
                        for c in range(caps.c_cap):
                            kind = pod["c_kind"][:, c]
                            sg = jnp.clip(pod["c_sg"][:, c], 0)
                            dom_rows = dom_sg[sg]
                            w = pod["inc_sg"].T * comm_f[None, :] * (
                                dom_acc0 >= 0)
                            m_sg = jnp.zeros_like(cd_sg).at[
                                sg_iota2, dom_acc0_ix].add(w)     # [SG,N-dom]
                            wp = pod["inc_sg"].T * new_f[None, :] * (
                                dom_acc0 >= 0)
                            pool_sg = jnp.zeros_like(cd_sg).at[
                                sg_iota2, dom_acc0_ix].add(wp)
                            fill = cd_sg + m_sg + pool_sg
                            gath = jnp.where(
                                dom_sg >= 0,
                                jnp.take_along_axis(fill, jnp.clip(dom_sg, 0),
                                                    axis=1),
                                jnp.inf)                          # [SG,N]
                            elig_c = sel_mask & (dom_rows >= 0)
                            # [P,1] pmin: cheap; stays full-width in rs mode
                            floor = comm.rowmin(gath[sg], elig_c, jnp.inf)[:, 0]
                            floor = jnp.where(jnp.isfinite(floor), floor, 0.0)
                            level = floor + pod["c_maxskew"][:, c]
                            if rs:
                                # reuse the conflict pass's slab — one
                                # reduce-scatter per slot per wave total
                                Dpq = Dpqs[c]
                                sg_s, kind_s = sl(sg), sl(kind)
                                own = Dpq[s_iota, slab_lo + s_iota]
                                same_dom = (Dpq == own[:, None]) \
                                    & (own[:, None] >= 0)
                                q_incs = pod["inc_sg"].T[sg_s]
                                rprime = jnp.sum(both_s * same_dom * q_incs
                                                 * new_f[None, :], axis=1)
                                own_ix = jnp.clip(own, 0).astype(jnp.int32)
                                cond = (cd_sg[sg_s, own_ix]
                                        + m_sg[sg_s, own_ix] + rprime
                                        + sl(pod["c_selfmatch"][:, c])) \
                                    <= sl(level)
                                is_spread = (kind_s == C_SPREAD_HARD) \
                                    & (own >= 0)
                                ok_all_s &= (~is_spread) | cond
                                continue
                            Dpq = comm.gather_cols(dom_rows, claims, offset,
                                                   n_loc, fill=-1.0)
                            own = Dpq[p_iota, p_iota]
                            same_dom = (Dpq == own[:, None]) & (own[:, None] >= 0)
                            q_incs = pod["inc_sg"].T[sg]
                            rprime = jnp.sum(both * same_dom * q_incs
                                             * new_f[None, :], axis=1)
                            own_ix = jnp.clip(own, 0).astype(jnp.int32)
                            m_own = m_sg[sg, own_ix]
                            cnt_own = cd_sg[sg, own_ix]
                            cond = (cnt_own + m_own + rprime
                                    + pod["c_selfmatch"][:, c]) <= level
                            is_spread = (kind == C_SPREAD_HARD) & (own >= 0)
                            ok_all &= (~is_spread) | cond
                        if rs:
                            ok_all = new_ok & unsl(ok_all_s)
                        committed = committed | (new_ok & ok_all)
                    accept = committed

                # ---- commit ----
                acc_oh = onehot * accept[:, None]                 # [P,N] local rows
                used = used + acc_oh.T @ req
                used_nz = used_nz + acc_oh.T @ req_nz
                npods = npods + jnp.sum(acc_oh, axis=0)
                if f_ports:
                    ports = jnp.minimum(ports + acc_oh.T @ pod["ports"], 1.0)

                if f_cons:
                    dom_acc = comm.gather_cols(dom_sg, claims, offset, n_loc,
                                               fill=-1.0)         # [SG,P]
                    w_sg = (pod["inc_sg"].T * accept[None, :] * (dom_acc >= 0))
                    cd_sg = cd_sg.at[jnp.arange(caps.sg_cap)[:, None],
                                     jnp.clip(dom_acc, 0).astype(jnp.int32)].add(w_sg)
                if f_asg:
                    dom_acc_a = comm.gather_cols(dom_asg, claims, offset, n_loc,
                                                 fill=-1.0)       # [ASG,P]
                    w_asg = (pod["inc_asg"].T * accept[None, :] * (dom_acc_a >= 0))
                    cd_asg = cd_asg.at[jnp.arange(caps.asg_cap)[:, None],
                                       jnp.clip(dom_acc_a, 0).astype(jnp.int32)].add(w_asg)

                if os.environ.get("KTPU_WAVE_DEBUG") and not isinstance(
                        claims, jax.core.Tracer):  # pragma: no cover - debug
                    # sync-point: env-gated debug dump (off in production)
                    _WAVE_DEBUG.append(jax.device_get({
                        "claims": claims, "has": has, "res_ok": res_ok,
                        "conf": conf, "over": spread_over_any,
                        "accept": accept, "active": active}))
                assigned = jnp.where(accept, claims, assigned)
                progress = jnp.any(accept)
                active = active & ~accept & progress  # no progress -> give up
                return (used, used_nz, npods, ports, cd_sg, cd_asg,
                        assigned, active, progress, wcount + 1)

            return wave

        wave = mk_wave(pod, sel_mask, static_mask, static_score, noise,
                       pk_static)

        def cond(state):
            active = state[7]
            wcount = state[9]
            go = jnp.any(active) & (wcount < max_waves)
            if tail_p:
                # hand the stragglers to the compacted tail loop the
                # moment they fit its sub-batch
                go &= jnp.sum(active.astype(jnp.int32)) > tail_p
            return go

        P_assigned = jnp.full((P,), -1, jnp.int32)
        state0 = (node["used"], node["used_nz"], node["npods"],
                  node["port_mask"], node["cd_sg"], node["cd_asg"],
                  P_assigned, pod["p_valid"], jnp.array(True), jnp.array(0))
        state = lax.while_loop(cond, wave, state0)
        if tail_p:
            (used, used_nz, npods, ports, cd_sg, cd_asg,
             assigned, active, _progress, wcount) = state
            # gather the (at most tail_p) still-active pods to the front;
            # padding rows gather INACTIVE pods, whose active[idx] is
            # False, so they commit nothing in the sub-loop
            _vals, idx = lax.top_k(active.astype(jnp.float32), tail_p)
            sub_pod = {k: v[idx] for k, v in pod.items()}
            sub_wave = mk_wave(sub_pod, sel_mask[idx], static_mask[idx],
                               static_score[idx], noise[idx], None)
            sub0 = (used, used_nz, npods, ports, cd_sg, cd_asg,
                    assigned[idx], active[idx], jnp.array(True), wcount)

            def cond_tail(st):
                return jnp.any(st[7]) & (st[9] < max_waves)

            sub = lax.while_loop(cond_tail, sub_wave, sub0)
            assigned = assigned.at[idx].set(sub[6])
            active = active.at[idx].set(sub[7])
            state = (sub[0], sub[1], sub[2], sub[3], sub[4], sub[5],
                     assigned, active, sub[8], sub[9])
        return {"assignments": state[6], "waves": state[9],
                "used": state[0], "used_nz": state[1], "npods": state[2],
                "port_mask": state[3], "cd_sg": state[4], "cd_asg": state[5]}


    return assign


# ---------------------------------------------------------------------------
# SCAN solver (strict sequential semantics; parity oracle)
# ---------------------------------------------------------------------------

def _make_scan_core(caps: Caps, w: dict, comm: _Comm):

    def _resource_fit(req, alloc, used, npods, maxpods):
        fits = jnp.all(req[None, :] <= alloc - used, axis=1)
        return fits & (npods + 1.0 <= maxpods)

    def _fit_scores(req_nz, alloc, used_nz):
        a = alloc[:, :2]
        u = (used_nz[:, :2] + req_nz[None, :2])
        util = jnp.where(a > 0, jnp.minimum(u / jnp.maximum(a, 1.0), 1.0), 1.0)
        least = jnp.mean((1.0 - util), axis=1) * 100.0
        mean = jnp.mean(util, axis=1, keepdims=True)
        std = jnp.sqrt(jnp.mean((util - mean) ** 2, axis=1))
        return least, (1.0 - std) * 100.0

    def assign(node: dict, pod: dict) -> dict[str, jnp.ndarray]:
        n_loc = node["alloc"].shape[0]
        pod = _fold_ns_masks(node, pod)
        offset = comm.my_offset(n_loc)
        sel_mask, static_mask, static_score = _static_mask_and_score(
            node, pod, comm, offset)
        alloc = node["alloc"]
        dom_sg, dom_asg = node["dom_sg"], node["dom_asg"]
        n_iota = jnp.arange(n_loc)

        def step(carry, xs):
            used, used_nz, npods, ports, cd_sg, cd_asg = carry
            (req, req_nz, p_valid, p_ports, p_sel_mask, p_static_mask,
             p_static_score, c_kind, c_sg, c_maxskew, c_selfmatch, c_weight,
             inc_sg, inc_asg, match_asg) = xs

            mask = p_static_mask
            mask &= _resource_fit(req, alloc, used, npods, node["maxpods"])
            mask &= (ports @ p_ports) == 0

            adom = jnp.clip(dom_asg, 0)
            acnt = jnp.take_along_axis(cd_asg, adom, axis=1)
            acnt = jnp.where(dom_asg >= 0, acnt, 0.0)
            blocked = (match_asg[:, None] * (acnt > 0)).sum(0) > 0
            mask &= ~blocked

            least, balanced = _fit_scores(req_nz, alloc, used_nz)
            score = w["fit"] * least + w["balanced"] * balanced
            score = score + w["taint"] * p_static_score

            for c in range(caps.c_cap):
                kind = c_kind[c]
                sg = jnp.clip(c_sg[c], 0)
                dom = dom_sg[sg]
                cnt_row = cd_sg[sg]
                gathered = jnp.where(dom >= 0, cnt_row[jnp.clip(dom, 0)], 0.0)
                has_dom = dom >= 0
                active = kind != C_NONE

                elig = p_sel_mask & has_dom
                minmatch = jnp.min(jnp.where(elig, gathered, jnp.inf))
                minmatch = lax.pmin(minmatch, comm.axis) if comm.axis else minmatch
                minmatch = jnp.where(jnp.isfinite(minmatch), minmatch, 0.0)
                total = jnp.sum(cnt_row)

                spread_ok = (gathered + c_selfmatch[c] - minmatch) <= c_maxskew[c]
                spread_ok &= has_dom
                aff_ok = (gathered > 0) | ((total == 0) & (c_selfmatch[c] > 0))
                aff_ok &= has_dom
                anti_ok = jnp.where(has_dom, gathered == 0, True)

                ok = jnp.where(kind == C_SPREAD_HARD, spread_ok,
                               jnp.where(kind == C_AFFINITY, aff_ok,
                                         jnp.where(kind == C_ANTI_AFFINITY,
                                                   anti_ok, True)))
                mask &= ok | ~active

                masked = jnp.where(mask, gathered, 0.0)
                smx = jnp.max(masked)
                smx = lax.pmax(smx, comm.axis) if comm.axis else smx
                smn = jnp.min(jnp.where(mask, gathered, jnp.inf))
                smn = lax.pmin(smn, comm.axis) if comm.axis else smn
                smn = jnp.where(jnp.isfinite(smn), smn, 0.0)
                rng = jnp.maximum(smx - smn, 1.0)
                spread_score = (smx - gathered) * 100.0 / rng
                score += jnp.where(kind == C_SPREAD_SCORE,
                                   w["spread"] * spread_score, 0.0)
                score += jnp.where(kind == C_PREF_AFFINITY,
                                   w["affinity"] * c_weight[c] * gathered, 0.0)

            feasible = mask & p_valid
            nfeas = jnp.sum(feasible.astype(jnp.int32))
            nfeas = lax.psum(nfeas, comm.axis) if comm.axis else nfeas
            any_ok = nfeas > 0
            masked_score = jnp.where(feasible, score, NEG)
            local_best = jnp.max(masked_score)
            local_idx = jnp.argmax(masked_score)
            if comm.axis:
                best_all = lax.all_gather(local_best, comm.axis)
                idx_all = lax.all_gather(local_idx, comm.axis)
                shard = jnp.argmax(best_all)
                j_global = shard * n_loc + idx_all[shard]
            else:
                j_global = local_idx
            j_global = jnp.where(any_ok, j_global, -1)

            local_j = j_global - offset
            place = (n_iota == local_j) & any_ok
            placef = place.astype(jnp.float32)
            used = used + placef[:, None] * req[None, :]
            used_nz = used_nz + placef[:, None] * req_nz[None, :]
            npods = npods + placef
            ports = jnp.minimum(ports + placef[:, None] * p_ports[None, :], 1.0)

            mine = (local_j >= 0) & (local_j < n_loc) & any_ok
            jj = jnp.clip(local_j, 0, n_loc - 1)
            d_sg = dom_sg[:, jj]
            d_asg = dom_asg[:, jj]
            if comm.axis:
                d_sg = lax.psum((d_sg + 1) * mine.astype(jnp.int32), comm.axis) - 1
                d_asg = lax.psum((d_asg + 1) * mine.astype(jnp.int32), comm.axis) - 1
            upd_sg = inc_sg * (d_sg >= 0) * any_ok
            cd_sg = cd_sg.at[jnp.arange(caps.sg_cap), jnp.clip(d_sg, 0)].add(upd_sg)
            upd_asg = inc_asg * (d_asg >= 0) * any_ok
            cd_asg = cd_asg.at[jnp.arange(caps.asg_cap), jnp.clip(d_asg, 0)].add(upd_asg)

            return (used, used_nz, npods, ports, cd_sg, cd_asg), j_global

        xs = (pod["req"], pod["req_nz"], pod["p_valid"], pod["ports"],
              sel_mask, static_mask, static_score,
              pod["c_kind"], pod["c_sg"], pod["c_maxskew"], pod["c_selfmatch"],
              pod["c_weight"], pod["inc_sg"], pod["inc_asg"], pod["match_asg"])
        carry0 = (node["used"], node["used_nz"], node["npods"], node["port_mask"],
                  node["cd_sg"], node["cd_asg"])
        carry, assignments = lax.scan(step, carry0, xs)
        return {"assignments": assignments, "used": carry[0], "npods": carry[2]}

    return assign


def build_assign_fn(caps: Caps, weights: dict[str, float] | None = None,
                    mode: str = "wave"):
    """Single-device jitted assignment: fn(node, pod) -> dict."""
    # compile-cached: built once per Caps at backend setup; the returned
    # callable (and its jit cache) is held by the caller for all waves
    return jax.jit(make_assign_core(caps, weights, axis_name=None, mode=mode))


# ---------------------------------------------------------------------------
# Packed transport + resident device state
#
# The axon/TPU transport has ~70ms fixed latency PER host->device buffer, so
# the per-batch wire format is ONE 1-D f32 buffer: pod floats, pod ints
# (bitcast), and a bounded row-patch section that reconciles external state
# changes (deletes/forgets) into the device-resident aggregates.  This is
# the in-process realization of the north star's "tensorized snapshot delta
# over a gRPC shim" (BASELINE.json): the shim ships deltas, never the world.
# ---------------------------------------------------------------------------

# device-resident wave state: the aggregate arrays the wave core consumes
# and re-emits, plus a scalar generation counter ("gen") the core never
# sees — the step fn increments it every wave and echoes it in the result
# tail, so the host can fence a resolve against state that was rebuilt
# (or a patch that was lost) while the wave was in flight.
AGGREGATE_KEYS = ("used", "used_nz", "npods", "port_mask", "cd_sg", "cd_asg")
STATE_KEYS = AGGREGATE_KEYS + ("gen",)
SEL_V = 8       # max ids per any-of label group (more -> escape hatch)
FORB_V = 8      # max forbidden label ids per pod
KEY_V = 4       # max ids per Exists key group


class PackSpec:
    """Offsets for the single packed pod+patch buffer.

    plain=True is the PLAIN-variant wire format: just req/req_nz plus an
    untol_hard bitmask and validity — ~6x less upload per batch than the
    full layout, which matters on a high-latency/limited-bandwidth link
    (the tunneled chip; the north star's gRPC shim regime)."""

    def __init__(self, caps: Caps, p_cap: int, k_cap: int,
                 plain: bool = False):
        assert caps.t_cap <= 31 and caps.pt_cap <= 31, "bitmask packing caps"
        assert caps.sg_cap <= 31 and caps.asg_cap <= 31
        assert caps.g_cap <= 31 and caps.kg_cap <= 31 and caps.kl_cap <= 62
        self.caps, self.p_cap, self.k_cap = caps, p_cap, k_cap
        self.plain = plain
        C, G, KG = caps.c_cap, caps.g_cap, caps.kg_cap
        if plain:
            self.f_f = 2 * caps.r
            self.f_i = 2  # untol_hard bits | p_valid
        else:
            self.f_f = 2 * caps.r + 3 * C
            # 13 fixed int columns (12 legacy + pod_ns) then the blocks
            self.f_i = 13 + 2 * C + G * SEL_V + FORB_V + KG * KEY_V
        self.f_patch = 2 * caps.r + 1 + caps.pt_cap
        self.a = p_cap * self.f_f
        self.b = p_cap * self.f_i
        self.total = self.a + self.b + k_cap + k_cap * self.f_patch


def _bits(mask_2d: np.ndarray) -> np.ndarray:
    """[P,W<=31] 0/1 float -> int32 bitmask [P]."""
    w = mask_2d.shape[1]
    return (mask_2d.astype(np.int64) @ (1 << np.arange(w, dtype=np.int64))
            ).astype(np.int32)


def pack_pod_batch(batch, spec: PackSpec,
                   patch_rows: np.ndarray | None = None,
                   patch_vals: np.ndarray | None = None,
                   out: np.ndarray | None = None) -> np.ndarray:
    """PodBatch (+ optional row patches) -> single 1-D f32 buffer.

    `out`, when given, is a preallocated f32[spec.total] staging buffer
    (the backend's ping-pong ring): every slot is overwritten here, so a
    recycled buffer needs no clearing, and the final concatenate-copy of
    the allocate-per-wave path is skipped."""
    caps, P, K = spec.caps, spec.p_cap, spec.k_cap
    C, G, KG = caps.c_cap, caps.g_cap, caps.kg_cap
    if spec.plain:
        pf = np.concatenate([batch.req, batch.req_nz],
                            axis=1).astype(np.float32)
        pi = np.zeros((P, spec.f_i), np.int32)
        pi[:, 0] = _bits(batch.untol_hard)
        pi[:, 1] = batch.p_valid.astype(np.int32)
        rows = np.full(K, -1, np.int32)
        vals = np.zeros((K, spec.f_patch), np.float32)
        if patch_rows is not None and len(patch_rows):
            n = min(len(patch_rows), K)
            rows[:n] = patch_rows[:n]
            vals[:n] = patch_vals[:n]
        return _pack_out(spec, pf, pi, rows, vals, out)
    # full wire format: materialize any lazy (None == zeros) fields the
    # dense layout ships (see flatten.PodBatch laziness contract)
    for _nm in ("untol_prefer", "ports", "key_forb", "match_asg", "inc_asg",
                "inc_sg", "pod_ns", "sel_any_active", "key_any_active",
                "node_row", "c_kind", "c_sg", "c_maxskew", "c_selfmatch",
                "c_weight", "sel_ids", "sel_forb_ids", "key_ids"):
        batch.ensure(caps, _nm)
    pf = np.concatenate([batch.req, batch.req_nz, batch.c_maxskew,
                         batch.c_selfmatch, batch.c_weight],
                        axis=1).astype(np.float32)
    pi = np.zeros((P, spec.f_i), np.int32)
    pi[:, 0] = _bits(batch.untol_hard)
    pi[:, 1] = _bits(batch.untol_prefer)
    pi[:, 2] = _bits(batch.ports)
    kf = batch.key_forb
    pi[:, 3] = _bits(kf[:, :31])
    pi[:, 4] = _bits(kf[:, 31:62]) if kf.shape[1] > 31 else 0
    pi[:, 5] = _bits(np.minimum(batch.match_asg, 1))
    pi[:, 6] = _bits(np.minimum(batch.inc_asg, 1))
    pi[:, 7] = _bits(np.minimum(batch.inc_sg, 1))
    pi[:, 8] = _bits(batch.sel_any_active)
    pi[:, 9] = _bits(batch.key_any_active)
    pi[:, 10] = batch.p_valid.astype(np.int32)
    pi[:, 11] = batch.node_row
    pi[:, 12] = batch.pod_ns
    o = 13
    pi[:, o:o + C] = batch.c_kind; o += C
    pi[:, o:o + C] = batch.c_sg; o += C
    pi[:, o:o + G * SEL_V] = batch.sel_ids.reshape(P, G * SEL_V); o += G * SEL_V
    pi[:, o:o + FORB_V] = batch.sel_forb_ids; o += FORB_V
    pi[:, o:o + KG * KEY_V] = batch.key_ids.reshape(P, KG * KEY_V)

    rows = np.full(K, -1, np.int32)
    vals = np.zeros((K, spec.f_patch), np.float32)
    if patch_rows is not None and len(patch_rows):
        n = min(len(patch_rows), K)
        rows[:n] = patch_rows[:n]
        vals[:n] = patch_vals[:n]
    return _pack_out(spec, pf, pi, rows, vals, out)


def _pack_out(spec: PackSpec, pf, pi, rows, vals,
              out: np.ndarray | None) -> np.ndarray:
    """Assemble the wire buffer: concatenate (fresh allocation) or fill
    `out` segment-by-segment — each segment is fully overwritten."""
    if out is None:
        return np.concatenate([
            pf.ravel(), pi.view(np.float32).ravel(),
            rows.view(np.float32), vals.ravel()]).astype(np.float32)
    a, b, K = spec.a, spec.b, spec.k_cap
    out[:a] = pf.ravel()
    out[a:a + b] = pi.view(np.float32).ravel()
    out[a + b:a + b + K] = rows.view(np.float32)
    out[a + b + K:] = vals.ravel()
    return out


def _unpack(buf, spec: PackSpec, features: frozenset = ALL_FEATURES):
    caps, P, K = spec.caps, spec.p_cap, spec.k_cap
    C, G, KG = caps.c_cap, caps.g_cap, caps.kg_cap
    R, L, KL = caps.r, caps.l_cap, caps.kl_cap
    pf = buf[:spec.a].reshape(P, spec.f_f)
    pi = lax.bitcast_convert_type(buf[spec.a:spec.a + spec.b],
                                  jnp.int32).reshape(P, spec.f_i)
    prow = lax.bitcast_convert_type(
        buf[spec.a + spec.b:spec.a + spec.b + K], jnp.int32)
    pval = buf[spec.a + spec.b + K:].reshape(K, spec.f_patch)

    def unbits(word, width):
        return ((word[:, None] >> jnp.arange(width)) & 1).astype(jnp.float32)

    if spec.plain:
        # PLAIN wire format: everything the elided code paths would read
        # is a traced zero constant (no transfer, folded at compile time)
        zc = jnp.zeros
        pod = {
            "req": pf[:, :R], "req_nz": pf[:, R:2 * R],
            "untol_hard": unbits(pi[:, 0], caps.t_cap),
            "p_valid": pi[:, 1] > 0,
            "untol_prefer": zc((P, caps.t_cap), jnp.float32),
            "ports": zc((P, caps.pt_cap), jnp.float32),
            "key_forb": zc((P, KL), jnp.float32),
            "match_asg": zc((P, caps.asg_cap), jnp.float32),
            "inc_asg": zc((P, caps.asg_cap), jnp.float32),
            "inc_sg": zc((P, caps.sg_cap), jnp.float32),
            "sel_any_active": zc((P, caps.g_cap), jnp.float32),
            "key_any_active": zc((P, caps.kg_cap), jnp.float32),
            "node_row": jnp.full((P,), -1, jnp.int32),
            "c_kind": jnp.zeros((P, C), jnp.int32),
            "c_sg": jnp.zeros((P, C), jnp.int32),
            "c_maxskew": zc((P, C), jnp.float32),
            "c_selfmatch": zc((P, C), jnp.float32),
            "c_weight": zc((P, C), jnp.float32),
            "sel_any": zc((P, G, L), jnp.float32),
            "sel_forb": zc((P, L), jnp.float32),
            "key_any": zc((P, KG, KL), jnp.float32),
        }
        return pod, prow, pval

    o = 13
    c_kind = pi[:, o:o + C]; o += C
    c_sg = pi[:, o:o + C]; o += C
    sel_ids = pi[:, o:o + G * SEL_V].reshape(P, G, SEL_V); o += G * SEL_V
    forb_ids = pi[:, o:o + FORB_V]; o += FORB_V
    key_ids = pi[:, o:o + KG * KEY_V].reshape(P, KG, KEY_V)

    if "selectors" in features:
        lid = jnp.arange(L)
        sel_any = ((sel_ids[:, :, :, None] == lid) &
                   (sel_ids[:, :, :, None] >= 0)).any(2).astype(jnp.float32)
        sel_forb = ((forb_ids[:, :, None] == lid) &
                    (forb_ids[:, :, None] >= 0)).any(1).astype(jnp.float32)
        kid = jnp.arange(KL)
        key_any = ((key_ids[:, :, :, None] == kid) &
                   (key_ids[:, :, :, None] >= 0)).any(2).astype(jnp.float32)
        kf_bits = jnp.concatenate([unbits(pi[:, 3], min(KL, 31)),
                                   unbits(pi[:, 4], max(KL - 31, 1))], axis=1)
        key_forb = kf_bits[:, :KL]
    else:
        sel_any = jnp.zeros((P, G, L), jnp.float32)
        sel_forb = jnp.zeros((P, L), jnp.float32)
        key_any = jnp.zeros((P, KG, KL), jnp.float32)
        key_forb = jnp.zeros((P, KL), jnp.float32)

    pod = {
        "req": pf[:, :R], "req_nz": pf[:, R:2 * R],
        "c_maxskew": pf[:, 2 * R:2 * R + C],
        "c_selfmatch": pf[:, 2 * R + C:2 * R + 2 * C],
        "c_weight": pf[:, 2 * R + 2 * C:2 * R + 3 * C],
        "untol_hard": unbits(pi[:, 0], caps.t_cap),
        "untol_prefer": unbits(pi[:, 1], caps.t_cap),
        "ports": unbits(pi[:, 2], caps.pt_cap),
        "key_forb": key_forb,
        "match_asg": unbits(pi[:, 5], caps.asg_cap),
        "inc_asg": unbits(pi[:, 6], caps.asg_cap),
        "inc_sg": unbits(pi[:, 7], caps.sg_cap),
        "sel_any_active": unbits(pi[:, 8], caps.g_cap),
        "key_any_active": unbits(pi[:, 9], caps.kg_cap),
        "p_valid": pi[:, 10] > 0,
        "node_row": pi[:, 11],
        "pod_ns": pi[:, 12],
        "c_kind": c_kind, "c_sg": c_sg,
        "sel_any": sel_any, "sel_forb": sel_forb, "key_any": key_any,
    }
    return pod, prow, pval


def _apply_patches(state: dict, prow, pval, caps: Caps):
    """Overwrite patched node rows of the dynamic aggregates (prow=-1 no-op)."""
    R, PT = caps.r, caps.pt_cap
    n = state["used"].shape[0]
    valid = (prow >= 0)
    r = jnp.clip(prow, 0, n - 1)
    vf = valid.astype(jnp.float32)[:, None]

    def setrows(arr, new):
        cur = arr[r]
        return arr.at[r].add((new - cur) * vf)

    state = dict(state)
    state["used"] = setrows(state["used"], pval[:, :R])
    state["used_nz"] = setrows(state["used_nz"], pval[:, R:2 * R])
    npods_new = pval[:, 2 * R]
    cur = state["npods"][r]
    state["npods"] = state["npods"].at[r].add((npods_new - cur) * vf[:, 0])
    state["port_mask"] = setrows(state["port_mask"], pval[:, 2 * R + 1:])
    return state


def build_packed_assign_fn(caps: Caps, p_cap: int, k_cap: int = 1024,
                           weights: dict[str, float] | None = None,
                           features: frozenset = ALL_FEATURES,
                           max_waves: int | None = None):
    """fn(state, static_node, buf) -> (new_state, result).
    `state` is device-resident and donated; `buf` is the single per-batch
    upload produced by pack_pod_batch.  `result` is int32[p_cap+2]:
    assignments for each pod slot, then the wave count, then the state
    generation after this step — one array so the host pulls the whole
    answer (and the generation fence) in ONE device transfer (a second
    scalar pull costs a full tunnel round trip).
    `features` selects a specialized kernel variant (the backend keeps one
    per feature set and picks per batch based on what the batch actually
    uses).  `max_waves` overrides the wave ceiling: the backend caps the
    MAIN constraint kernel at a few waves and drains the straggler tail
    through a small retry kernel instead (a tail wave at full [P,N] cost
    admits a handful of pods; see TPUBatchBackend retry path)."""
    spec = PackSpec(caps, p_cap, k_cap, plain=(features == PLAIN_FEATURES))
    if max_waves is None:
        # wave ceiling: constraint batches can legitimately need many
        # waves (hard spread admits ~domains*maxSkew pods per wave), and
        # the loop exits the moment nothing is active or progress stops —
        # so for the constraint-carrying variant the cap is p_cap (the
        # absolute worst case of one forced serialization per wave),
        # while the plain variant converges in O(contention) and keeps a
        # tight bound
        max_waves = 128 if features == PLAIN_FEATURES else max(128, p_cap)
    core = _make_wave_core(caps, {"fit": 1.0, "balanced": 1.0, "spread": 2.0,
                                  "affinity": 1.0, "taint": 1.0,
                                  **(weights or {})}, _Comm(None), max_waves,
                           features)

    # compile-cached: built once per Caps at backend setup; one resident
    # jit cache serves every wave against the packed transport.  The
    # packed upload (argnum 2) is donated alongside the resident state:
    # with two waves in flight the device would otherwise hold both
    # waves' upload buffers live for the full step — donation lets XLA
    # reclaim the transport the moment the unpack consumes it, keeping
    # HBM flat at any pipeline depth (the host keeps its own staging
    # copy for fenced re-runs, so nothing re-reads the device buffer).
    @functools.partial(jax.jit, donate_argnums=(0, 2))
    def fn(state, static_node, buf):
        gen = state["gen"] + 1
        dyn = {k: state[k] for k in AGGREGATE_KEYS}
        pod, prow, pval = _unpack(buf, spec, features)
        dyn = _apply_patches(dyn, prow, pval, caps)
        out = core({**static_node, **dyn}, pod)
        new_state = {k: out[k] for k in AGGREGATE_KEYS}
        new_state["gen"] = gen
        result = jnp.concatenate([
            out["assignments"].astype(jnp.int32),
            out["waves"].reshape(1).astype(jnp.int32),
            gen.reshape(1).astype(jnp.int32)])
        return new_state, result

    return fn, spec
