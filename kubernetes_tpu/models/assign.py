"""Batched pod->node assignment on device.

This is the TPU replacement for the reference's HOT LOOPS (SURVEY.md §3.1):
  findNodesThatPassFilters (schedule_one.go:512)  -> feasibility masks
  RunScorePlugins          (runtime/framework.go:903) -> score matrix
  selectHost               (schedule_one.go:777)  -> masked argmax
  + the implicit cache.assume() between per-pod cycles -> in-scan running
    sums (resources, pod counts, host ports, topology/affinity domain
    counts), which is what makes a batch of K pods produce the same
    placements the reference produces scheduling them one at a time
    (SURVEY.md §7 hard part #1).

Structure:
  static phase (vectorized over P x N, MXU matmuls):
      label-selector any-of groups   einsum('pgl,nl->pgn')
      forbidden labels / keys        matmul
      untolerated-taint counts       matmul
      (these mirror NodeAffinity / NodeUnschedulable / TaintToleration /
       NodeName filters)
  scan phase (lax.scan over the P pods in queue order):
      NodeResourcesFit mask from running used/npods sums
      NodePorts conflict from running port mask
      PodTopologySpread / InterPodAffinity from running domain counts
      LeastAllocated + BalancedAllocation + spread/affinity scores
      masked argmax -> placement -> state update

Multi-chip: the node axis shards across a jax Mesh (parallel/mesh.py wraps
this in shard_map).  Every cross-node reduction goes through the _Comm
layer: max/min/sum become pmax/pmin/psum over ICI, the argmax becomes a
per-shard top-1 + all_gather + global pick, and the domain-count updates are
replicated via a psum of the winning shard's domain ids.  That is the
"shard the long axis, per-core top-k, global reduce" recipe from SURVEY.md
§5 (long-context analog).

All shapes are static (derived from flatten.Caps), so one compilation
serves every batch; arrays are padded and masked.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops.flatten import (
    C_AFFINITY, C_ANTI_AFFINITY, C_NONE, C_PREF_AFFINITY, C_SPREAD_HARD,
    C_SPREAD_SCORE, CORE_R, Caps,
)

NEG = -1e9


class _Comm:
    """Reduction layer: local ops when axis_name is None, ICI collectives
    inside shard_map otherwise."""

    def __init__(self, axis_name: str | None):
        self.axis = axis_name

    def max(self, x):
        m = jnp.max(x)
        return lax.pmax(m, self.axis) if self.axis else m

    def min(self, x):
        m = jnp.min(x)
        return lax.pmin(m, self.axis) if self.axis else m

    def sum(self, x):
        s = jnp.sum(x)
        return lax.psum(s, self.axis) if self.axis else s

    def rowmax(self, x, mask, fill):
        """max over the node axis (last) of a [P,N] array under mask."""
        m = jnp.max(jnp.where(mask, x, fill), axis=-1, keepdims=True)
        return lax.pmax(m, self.axis) if self.axis else m

    def argmax(self, score, n_loc: int):
        """Global argmax over the (possibly sharded) node axis.
        Returns (j_global, best_score)."""
        local_best = jnp.max(score)
        local_idx = jnp.argmax(score)
        if not self.axis:
            return local_idx, local_best
        best_all = lax.all_gather(local_best, self.axis)   # [S]
        idx_all = lax.all_gather(local_idx, self.axis)     # [S]
        shard = jnp.argmax(best_all)
        return shard * n_loc + idx_all[shard], best_all[shard]

    def my_offset(self, n_loc: int):
        if not self.axis:
            return 0
        return lax.axis_index(self.axis) * n_loc

    def replicate_from_owner(self, value, owner_mask, sentinel_shift=1):
        """All shards learn `value` (int array) held by the shard where
        owner_mask is True; value entries may be -1 (encoded via +shift)."""
        if not self.axis:
            return value
        enc = (value + sentinel_shift) * owner_mask.astype(value.dtype)
        return lax.psum(enc, self.axis) - sentinel_shift


def _static_mask_and_score(node: dict, pod: dict, comm: _Comm, offset):
    """Vectorized P x N feasibility independent of in-batch placements.

    Returns (sel_mask, static_mask, static_score):
      sel_mask    - node-affinity/selector-only eligibility (used for the
                    spread min-match domain set, which the reference computes
                    over affinity-eligible nodes only, filtering.go:261)
      static_mask - sel_mask AND taints AND nodeName pin AND validity
      static_score- PreferNoSchedule taint score contribution (0..100)
    """
    valid = node["valid"][None, :]                        # [1,N]
    label = node["label_mask"]                            # [N,L]
    keym = node["key_mask"]                               # [N,KL]

    # any-of label groups: group satisfied if node has >=1 of its ids
    hits = jnp.einsum("pgl,nl->pgn", pod["sel_any"], label)
    group_ok = (hits > 0) | (pod["sel_any_active"][:, :, None] == 0)
    sel_ok = jnp.all(group_ok, axis=1)                    # [P,N]
    khits = jnp.einsum("pgk,nk->pgn", pod["key_any"], keym)
    kgroup_ok = (khits > 0) | (pod["key_any_active"][:, :, None] == 0)
    sel_ok &= jnp.all(kgroup_ok, axis=1)
    sel_ok &= (pod["sel_forb"] @ label.T) == 0            # NotIn
    sel_ok &= (pod["key_forb"] @ keym.T) == 0             # DoesNotExist
    sel_mask = sel_ok & valid

    # taints (TaintToleration + NodeUnschedulable-as-taint)
    hard = (pod["untol_hard"] @ node["taint_mask"].T) == 0
    # spec.nodeName pin (node_row is a GLOBAL row index)
    n_idx = offset + jnp.arange(label.shape[0])[None, :]
    pin = (pod["node_row"][:, None] < 0) | (n_idx == pod["node_row"][:, None])

    static_mask = sel_mask & hard & pin

    prefer_cnt = pod["untol_prefer"] @ node["taint_mask"].T   # [P,N]
    mx = comm.rowmax(prefer_cnt, static_mask, 0.0)
    static_score = jnp.where(mx > 0, (mx - prefer_cnt) * 100.0 / jnp.maximum(mx, 1.0), 100.0)
    return sel_mask, static_mask, static_score


def _resource_fit(req: jnp.ndarray, alloc: jnp.ndarray, used: jnp.ndarray,
                  npods: jnp.ndarray, maxpods: jnp.ndarray) -> jnp.ndarray:
    """NodeResourcesFit (fit.go:253) for one pod against all nodes: [N]."""
    fits = jnp.all(req[None, :] <= alloc - used, axis=1)
    return fits & (npods + 1.0 <= maxpods)


def _fit_scores(req_nz: jnp.ndarray, alloc: jnp.ndarray, used_nz: jnp.ndarray
                ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """LeastAllocated + BalancedAllocation over cpu/mem dims: ([N],[N])."""
    a = alloc[:, :2]
    u = (used_nz[:, :2] + req_nz[None, :2])
    util = jnp.where(a > 0, jnp.minimum(u / jnp.maximum(a, 1.0), 1.0), 1.0)
    least = jnp.mean((1.0 - util), axis=1) * 100.0
    mean = jnp.mean(util, axis=1, keepdims=True)
    std = jnp.sqrt(jnp.mean((util - mean) ** 2, axis=1))
    balanced = (1.0 - std) * 100.0
    return least, balanced


def make_assign_core(caps: Caps, weights: dict[str, float] | None = None,
                     axis_name: str | None = None):
    """The assignment program body.  Call under jit (single device) or
    inside shard_map with the node axis sharded (parallel/mesh.py)."""
    w = {"fit": 1.0, "balanced": 1.0, "spread": 2.0, "affinity": 1.0,
         "taint": 1.0, **(weights or {})}
    comm = _Comm(axis_name)

    def assign(node: dict, pod: dict) -> dict[str, jnp.ndarray]:
        n_loc = node["alloc"].shape[0]
        offset = comm.my_offset(n_loc)
        sel_mask, static_mask, static_score = _static_mask_and_score(
            node, pod, comm, offset)

        alloc = node["alloc"]
        dom_sg = node["dom_sg"]          # [SG,N]  (N = local shard)
        dom_asg = node["dom_asg"]        # [ASG,N]
        n_iota = jnp.arange(n_loc)

        def step(carry, xs):
            used, used_nz, npods, ports, cd_sg, cd_asg = carry
            (req, req_nz, p_valid, p_ports, p_sel_mask, p_static_mask,
             p_static_score, c_kind, c_sg, c_maxskew, c_selfmatch, c_weight,
             inc_sg, inc_asg, match_asg) = xs

            mask = p_static_mask
            mask &= _resource_fit(req, alloc, used, npods, node["maxpods"])
            mask &= (ports @ p_ports) == 0                     # NodePorts

            # existing pods' (and earlier batch pods') anti-affinity
            # blocked[n] = any asg matching this pod with count>0 in n's domain
            adom = jnp.clip(dom_asg, 0)                        # [ASG,N]
            acnt = jnp.take_along_axis(cd_asg, adom, axis=1)   # [ASG,N]
            acnt = jnp.where(dom_asg >= 0, acnt, 0.0)
            blocked = (match_asg[:, None] * (acnt > 0)).sum(0) > 0
            mask &= ~blocked

            least, balanced = _fit_scores(req_nz, alloc, used_nz)
            score = w["fit"] * least + w["balanced"] * balanced
            score = score + w["taint"] * p_static_score

            # constraints (unrolled over C; all kinds computed, selected by mask)
            for c in range(caps.c_cap):
                kind = c_kind[c]
                sg = jnp.clip(c_sg[c], 0)
                dom = dom_sg[sg]                               # [N]
                cnt_row = cd_sg[sg]                            # [D] (replicated)
                gathered = jnp.where(dom >= 0, cnt_row[jnp.clip(dom, 0)], 0.0)
                has_dom = dom >= 0
                active = kind != C_NONE

                # min over domains present among sel-eligible nodes
                elig = p_sel_mask & has_dom
                minmatch = comm.min(jnp.where(elig, gathered, jnp.inf))
                minmatch = jnp.where(jnp.isfinite(minmatch), minmatch, 0.0)
                total = jnp.sum(cnt_row)  # cd replicated: no psum needed

                spread_ok = (gathered + c_selfmatch[c] - minmatch) <= c_maxskew[c]
                spread_ok &= has_dom
                aff_ok = (gathered > 0) | ((total == 0) & (c_selfmatch[c] > 0))
                aff_ok &= has_dom
                anti_ok = jnp.where(has_dom, gathered == 0, True)

                ok = jnp.where(kind == C_SPREAD_HARD, spread_ok,
                               jnp.where(kind == C_AFFINITY, aff_ok,
                                         jnp.where(kind == C_ANTI_AFFINITY,
                                                   anti_ok, True)))
                mask &= ok | ~active

                # score kinds: fewer matches better for spread; weighted count
                # for preferred affinity (sign carried by weight)
                smx = comm.max(jnp.where(mask, gathered, 0.0))
                smn = comm.min(jnp.where(mask, gathered, jnp.inf))
                smn = jnp.where(jnp.isfinite(smn), smn, 0.0)
                rng = jnp.maximum(smx - smn, 1.0)
                spread_score = (smx - gathered) * 100.0 / rng
                score += jnp.where(kind == C_SPREAD_SCORE,
                                   w["spread"] * spread_score, 0.0)
                score += jnp.where(kind == C_PREF_AFFINITY,
                                   w["affinity"] * c_weight[c] * gathered, 0.0)

            feasible = mask & p_valid
            any_ok = comm.sum(feasible.astype(jnp.int32)) > 0
            j_global, _best = comm.argmax(jnp.where(feasible, score, NEG), n_loc)
            j_global = jnp.where(any_ok, j_global, -1)

            # state updates (the in-batch assume()); local one-hot
            local_j = j_global - offset
            place = (n_iota == local_j) & any_ok               # [N] local
            placef = place.astype(jnp.float32)
            used = used + placef[:, None] * req[None, :]
            used_nz = used_nz + placef[:, None] * req_nz[None, :]
            npods = npods + placef
            ports = jnp.minimum(ports + placef[:, None] * p_ports[None, :], 1.0)

            # winning node's domain ids, replicated to all shards
            mine = (local_j >= 0) & (local_j < n_loc) & any_ok
            jj = jnp.clip(local_j, 0, n_loc - 1)
            d_sg = comm.replicate_from_owner(dom_sg[:, jj], mine)   # [SG]
            d_asg = comm.replicate_from_owner(dom_asg[:, jj], mine)
            upd_sg = inc_sg * (d_sg >= 0) * any_ok
            cd_sg = cd_sg.at[jnp.arange(caps.sg_cap), jnp.clip(d_sg, 0)].add(upd_sg)
            upd_asg = inc_asg * (d_asg >= 0) * any_ok
            cd_asg = cd_asg.at[jnp.arange(caps.asg_cap), jnp.clip(d_asg, 0)].add(upd_asg)

            return (used, used_nz, npods, ports, cd_sg, cd_asg), j_global

        xs = (pod["req"], pod["req_nz"], pod["p_valid"], pod["ports"],
              sel_mask, static_mask, static_score,
              pod["c_kind"], pod["c_sg"], pod["c_maxskew"], pod["c_selfmatch"],
              pod["c_weight"], pod["inc_sg"], pod["inc_asg"], pod["match_asg"])
        carry0 = (node["used"], node["used_nz"], node["npods"], node["port_mask"],
                  node["cd_sg"], node["cd_asg"])
        carry, assignments = lax.scan(step, carry0, xs)
        return {"assignments": assignments, "used": carry[0], "npods": carry[2]}

    return assign


def build_assign_fn(caps: Caps, weights: dict[str, float] | None = None):
    """Single-device jitted assignment: fn(node, pod) -> dict."""
    return jax.jit(make_assign_core(caps, weights, axis_name=None))
