"""Batched pod->node assignment on device.

This is the TPU replacement for the reference's HOT LOOPS (SURVEY.md §3.1):
  findNodesThatPassFilters (schedule_one.go:512)  -> feasibility masks
  RunScorePlugins          (runtime/framework.go:903) -> score matrix
  selectHost               (schedule_one.go:777)  -> masked argmax
  + the implicit cache.assume() between per-pod cycles -> in-scan running
    sums (resources, pod counts, host ports, topology/affinity domain
    counts), which is what makes a batch of K pods produce the same
    placements the reference produces scheduling them one at a time
    (SURVEY.md §7 hard part #1).

Structure:
  static phase (vectorized over P x N, MXU matmuls):
      label-selector any-of groups   einsum('pgl,nl->pgn')
      forbidden labels / keys        matmul
      untolerated-taint counts       matmul
      (these mirror NodeAffinity / NodeUnschedulable / TaintToleration /
       NodeName filters)
  scan phase (lax.scan over the P pods in queue order):
      NodeResourcesFit mask from running used/npods sums
      NodePorts conflict from running port mask
      PodTopologySpread / InterPodAffinity from running domain counts
      LeastAllocated + BalancedAllocation + spread/affinity scores
      masked argmax -> placement -> state update

All shapes are static (derived from flatten.Caps), so one compilation
serves every batch; arrays are padded and masked.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.flatten import (
    C_AFFINITY, C_ANTI_AFFINITY, C_NONE, C_PREF_AFFINITY, C_SPREAD_HARD,
    C_SPREAD_SCORE, CORE_R, Caps,
)

NEG = -1e9


def _static_mask_and_score(node: dict, pod: dict) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Vectorized P x N feasibility independent of in-batch placements.

    Returns (sel_mask, static_mask, static_score):
      sel_mask    - node-affinity/selector-only eligibility (used for the
                    spread min-match domain set, which the reference computes
                    over affinity-eligible nodes only, filtering.go:261)
      static_mask - sel_mask AND taints AND nodeName pin AND validity
      static_score- PreferNoSchedule taint score contribution (0..100)
    """
    valid = node["valid"][None, :]                        # [1,N]
    label = node["label_mask"]                            # [N,L]
    keym = node["key_mask"]                               # [N,KL]

    # any-of label groups: group satisfied if node has >=1 of its ids
    hits = jnp.einsum("pgl,nl->pgn", pod["sel_any"], label)
    group_ok = (hits > 0) | (pod["sel_any_active"][:, :, None] == 0)
    sel_ok = jnp.all(group_ok, axis=1)                    # [P,N]
    khits = jnp.einsum("pgk,nk->pgn", pod["key_any"], keym)
    kgroup_ok = (khits > 0) | (pod["key_any_active"][:, :, None] == 0)
    sel_ok &= jnp.all(kgroup_ok, axis=1)
    sel_ok &= (pod["sel_forb"] @ label.T) == 0            # NotIn
    sel_ok &= (pod["key_forb"] @ keym.T) == 0             # DoesNotExist
    sel_mask = sel_ok & valid

    # taints (TaintToleration + NodeUnschedulable-as-taint)
    hard = (pod["untol_hard"] @ node["taint_mask"].T) == 0
    # spec.nodeName pin
    n_idx = jnp.arange(label.shape[0])[None, :]
    pin = (pod["node_row"][:, None] < 0) | (n_idx == pod["node_row"][:, None])

    static_mask = sel_mask & hard & pin

    prefer_cnt = pod["untol_prefer"] @ node["taint_mask"].T   # [P,N]
    mx = jnp.max(jnp.where(static_mask, prefer_cnt, 0.0), axis=1, keepdims=True)
    static_score = jnp.where(mx > 0, (mx - prefer_cnt) * 100.0 / jnp.maximum(mx, 1.0), 100.0)
    return sel_mask, static_mask, static_score


def _resource_fit(req: jnp.ndarray, alloc: jnp.ndarray, used: jnp.ndarray,
                  npods: jnp.ndarray, maxpods: jnp.ndarray) -> jnp.ndarray:
    """NodeResourcesFit (fit.go:253) for one pod against all nodes: [N]."""
    fits = jnp.all(req[None, :] <= alloc - used, axis=1)
    return fits & (npods + 1.0 <= maxpods)


def _fit_scores(req_nz: jnp.ndarray, alloc: jnp.ndarray, used_nz: jnp.ndarray
                ) -> tuple[jnp.ndarray, jnp.ndarray]:
    """LeastAllocated + BalancedAllocation over cpu/mem dims: ([N],[N])."""
    a = alloc[:, :2]
    u = (used_nz[:, :2] + req_nz[None, :2])
    util = jnp.where(a > 0, jnp.minimum(u / jnp.maximum(a, 1.0), 1.0), 1.0)
    least = jnp.mean((1.0 - util), axis=1) * 100.0
    mean = jnp.mean(util, axis=1, keepdims=True)
    std = jnp.sqrt(jnp.mean((util - mean) ** 2, axis=1))
    balanced = (1.0 - std) * 100.0
    return least, balanced


def build_assign_fn(caps: Caps, weights: dict[str, float] | None = None):
    """Compile the batched assignment for the given static capacities.

    Returns fn(node_arrays, pod_arrays) -> (assignments i32[P], used, npods)
    where assignments[p] is the node row or -1.
    """
    w = {"fit": 1.0, "balanced": 1.0, "spread": 2.0, "affinity": 1.0,
         "taint": 1.0, **(weights or {})}

    @jax.jit
    def assign(node: dict, pod: dict) -> dict[str, jnp.ndarray]:
        sel_mask, static_mask, static_score = _static_mask_and_score(node, pod)

        alloc = node["alloc"]
        dom_sg = node["dom_sg"]          # [SG,N]
        dom_asg = node["dom_asg"]        # [ASG,N]
        n_iota = jnp.arange(alloc.shape[0])

        def step(carry, xs):
            used, used_nz, npods, ports, cd_sg, cd_asg = carry
            (req, req_nz, p_valid, p_ports, p_sel_mask, p_static_mask,
             p_static_score, c_kind, c_sg, c_maxskew, c_selfmatch, c_weight,
             inc_sg, inc_asg, match_asg) = xs

            mask = p_static_mask
            mask &= _resource_fit(req, alloc, used, npods, node["maxpods"])
            mask &= (ports @ p_ports) == 0                     # NodePorts

            # existing pods' (and earlier batch pods') anti-affinity
            # blocked[n] = any asg matching this pod with count>0 in n's domain
            adom = jnp.clip(dom_asg, 0)                        # [ASG,N]
            acnt = jnp.take_along_axis(cd_asg, adom, axis=1)   # [ASG,N]
            acnt = jnp.where(dom_asg >= 0, acnt, 0.0)
            blocked = (match_asg[:, None] * (acnt > 0)).sum(0) > 0
            mask &= ~blocked

            score = w["fit"] * 0.0
            least, balanced = _fit_scores(req_nz, alloc, used_nz)
            score = w["fit"] * least + w["balanced"] * balanced
            score = score + w["taint"] * p_static_score

            # constraints (unrolled over C; all kinds computed, selected by mask)
            for c in range(caps.c_cap):
                kind = c_kind[c]
                sg = jnp.clip(c_sg[c], 0)
                dom = dom_sg[sg]                               # [N]
                cnt_row = cd_sg[sg]                            # [D]
                gathered = jnp.where(dom >= 0, cnt_row[jnp.clip(dom, 0)], 0.0)
                has_dom = dom >= 0
                active = kind != C_NONE

                # min over domains present among sel-eligible nodes
                elig = p_sel_mask & has_dom
                minmatch = jnp.min(jnp.where(elig, gathered, jnp.inf))
                minmatch = jnp.where(jnp.isfinite(minmatch), minmatch, 0.0)
                total = jnp.sum(cnt_row)

                spread_ok = (gathered + c_selfmatch[c] - minmatch) <= c_maxskew[c]
                spread_ok &= has_dom
                aff_ok = (gathered > 0) | ((total == 0) & (c_selfmatch[c] > 0))
                aff_ok &= has_dom
                anti_ok = jnp.where(has_dom, gathered == 0, True)

                ok = jnp.where(kind == C_SPREAD_HARD, spread_ok,
                               jnp.where(kind == C_AFFINITY, aff_ok,
                                         jnp.where(kind == C_ANTI_AFFINITY,
                                                   anti_ok, True)))
                mask &= ok | ~active

                # score kinds: fewer matches better for spread; weighted count
                # for preferred affinity (sign carried by weight)
                smx = jnp.max(jnp.where(mask, gathered, 0.0))
                smn = jnp.min(jnp.where(mask, gathered, jnp.inf))
                smn = jnp.where(jnp.isfinite(smn), smn, 0.0)
                rng = jnp.maximum(smx - smn, 1.0)
                spread_score = (smx - gathered) * 100.0 / rng
                score += jnp.where(kind == C_SPREAD_SCORE,
                                   w["spread"] * spread_score, 0.0)
                score += jnp.where(kind == C_PREF_AFFINITY,
                                   w["affinity"] * c_weight[c] * gathered, 0.0)

            feasible = mask & p_valid
            any_ok = jnp.any(feasible)
            j = jnp.argmax(jnp.where(feasible, score, NEG))
            j = jnp.where(any_ok, j, -1)

            # state updates (the in-batch assume())
            place = (n_iota == j) & any_ok                     # [N]
            placef = place.astype(jnp.float32)
            used = used + placef[:, None] * req[None, :]
            used_nz = used_nz + placef[:, None] * req_nz[None, :]
            npods = npods + placef
            ports = jnp.minimum(ports + placef[:, None] * p_ports[None, :], 1.0)

            jj = jnp.clip(j, 0)
            d_sg = dom_sg[:, jj]                               # [SG]
            upd_sg = inc_sg * (d_sg >= 0) * any_ok
            cd_sg = cd_sg.at[jnp.arange(caps.sg_cap), jnp.clip(d_sg, 0)].add(upd_sg)
            d_asg = dom_asg[:, jj]
            upd_asg = inc_asg * (d_asg >= 0) * any_ok
            cd_asg = cd_asg.at[jnp.arange(caps.asg_cap), jnp.clip(d_asg, 0)].add(upd_asg)

            return (used, used_nz, npods, ports, cd_sg, cd_asg), j

        xs = (pod["req"], pod["req_nz"], pod["p_valid"], pod["ports"],
              sel_mask, static_mask, static_score,
              pod["c_kind"], pod["c_sg"], pod["c_maxskew"], pod["c_selfmatch"],
              pod["c_weight"], pod["inc_sg"], pod["inc_asg"], pod["match_asg"])
        carry0 = (node["used"], node["used_nz"], node["npods"], node["port_mask"],
                  node["cd_sg"], node["cd_asg"])
        carry, assignments = jax.lax.scan(step, carry0, xs)
        return {"assignments": assignments, "used": carry[0], "npods": carry[2]}

    return assign
