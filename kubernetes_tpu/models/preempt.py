"""Batched preemption candidate search — the device half of PostFilter.

Reference: pkg/scheduler/framework/preemption/preemption.go —
  DryRunPreemption (:579) fans goroutines out over candidate nodes, each
  one simulating "remove all lower-priority pods, does the preemptor
  fit?"; the reference samples candidates (GetOffsetAndNumCandidates)
  rather than scanning every node.

TPU-native reshape: the "remove all lower-priority victims" probe is a
pure arithmetic refilter — free'[p,n] = alloc[n] - (used[n] -
reclaimable[g(p),n]) — so ALL failed pods × ALL nodes evaluate in one
fused device op, grouped by pod priority (pods of equal priority see the
same reclaimable matrix).  The device returns each pod's top-k candidate
rows ranked by fewest-potential-victims (the dominant term of
pickOneNodeForPreemption's ordering); the host then runs the exact
reprieve/PDB dry-run (scheduler/preemption.py) on just those k nodes,
preserving reference victim-selection semantics while the O(pods*nodes)
scan stays on device.

Like the reference's sampling, top-k is a candidate LIMIT, not an
approximation of victim selection: every returned candidate is re-proved
host-side by the full filter plugin set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG = -1e30


@functools.partial(jax.jit, static_argnames=("k",))
def _preempt_candidates(alloc, used, npods, maxpods, valid,
                        reclaim, reclaim_np, group_idx, req, active, k):
    """Per failed pod: top-k candidate node rows where preempting every
    lower-priority pod would make it fit.

    alloc/used: f32[N,R]; npods/maxpods: f32[N]; valid: bool[N]
    reclaim: f32[G,N,R] resources reclaimable per priority group
    reclaim_np: f32[G,N] pod-count reclaimable per priority group
    group_idx: i32[P] pod -> priority group; req: f32[P,R]
    active: bool[P] (padding rows inactive)
    returns (rows i32[P,k], feasible_count i32[P])
    """
    rec = reclaim[group_idx]          # [P,N,R]
    rec_np = reclaim_np[group_idx]    # [P,N]
    free = alloc[None, :, :] - (used[None, :, :] - rec)
    fits = jnp.all(req[:, None, :] <= free + 1e-6, axis=-1)
    fits &= (npods[None, :] - rec_np + 1.0) <= maxpods[None, :]
    fits &= valid[None, :]
    fits &= rec_np > 0.0              # no victims -> plain FitError, not
    fits &= active[:, None]           # a preemption candidate
    # rank: fewest potential victims first (pickOneNode's dominant
    # term); WITHIN an equal-victim-count tier, per-POD hash noise
    # deliberately dominates the ordering — equal-priority preemptors
    # otherwise rank every node identically and a whole failure wave
    # converges on the same k candidates: the first k pods nominate
    # them, the rest find every candidate claimed (nominated-pods
    # filter) and re-fail into backoff, draining a 500-pod wave k pods
    # at a time (measured: 31 rounds, ~80 s).  The reference
    # decorrelates the same way with a RANDOM candidate-sampling offset
    # (GetOffsetAndNumCandidates).  The 1e-9*headroom term is a
    # deterministic last-resort tiebreak under identical noise only.
    # Headroom is per-resource NORMALIZED (free fraction of allocatable,
    # summed): raw unit sums let the largest-magnitude resource dominate
    # — a node with 256Gi of free memory outranks one with 64 free CPUs
    # on absolute numbers alone, so heterogeneous-memory fleets ranked
    # on memory bytes, not balance.
    headroom = jnp.sum(jnp.maximum(free, 0.0)
                       / jnp.maximum(alloc, 1e-9)[None, :, :], axis=-1)
    P, N = fits.shape
    tie = (((jnp.arange(P, dtype=jnp.uint32)[:, None]
             * jnp.uint32(2654435761))
            ^ (jnp.arange(N, dtype=jnp.uint32)[None, :]
               * jnp.uint32(40503)))
           % jnp.uint32(65536)).astype(jnp.float32) / 65536.0
    score = jnp.where(fits, -rec_np + 1e-9 * headroom + 0.1 * tie, NEG)
    vals, rows = jax.lax.top_k(score, k)
    rows = jnp.where(vals > NEG / 2, rows, -1)
    return rows, jnp.sum(fits, axis=1, dtype=jnp.int32)


def preempt_candidates(alloc, used, npods, maxpods, valid, reclaim,
                       reclaim_np, group_idx, req, active, k: int):
    """Host entry: numpy in, numpy out (one blocking device round trip —
    preemption is the rare path, latency over throughput)."""
    rows, count = _preempt_candidates(
        jnp.asarray(alloc), jnp.asarray(used), jnp.asarray(npods),
        jnp.asarray(maxpods), jnp.asarray(valid), jnp.asarray(reclaim),
        jnp.asarray(reclaim_np), jnp.asarray(group_idx), jnp.asarray(req),
        jnp.asarray(active), k)
    # sync-point: preemption host entry — the one explicit blocking pull
    return jax.device_get((rows, count))


# -- full DryRunPreemption (victim tensors) -------------------------------
#
# The kernel above LIMITS candidates and leaves victim selection to the
# host Evaluator.  This one IS the dry run: per preemptor x every node,
# remove all lower-priority victims, fit-check, greedy reprieve
# (PDB-violating first, then highest priority first — preemption.go's
# selectVictimsOnNode re-add order), emitting the per-(pod,node) planes
# of pickOneNodeForPreemption's exact lexicographic key plus the full
# victim masks.  All of it is masked prefix arithmetic under one jit —
# zero host round trips per pod; the host takes the key argmin per pod
# so a whole wave can conflict-resolve (exclude nodes claimed by
# earlier winners, fold their nominations) without a device call per
# preemptor.
#
# Exactness envelope (the caller gates everything outside it to the
# Evaluator): plain preemptors, no inter-pod-affinity groups live, PDB
# scope covered by the device bits, no victim-slot overflow on reachable
# nodes.  Priorities stay int32 end-to-end (f32 loses exactness past
# 2^24); the priority-SUM tie-break key is f32 and therefore approximate
# only when victim priority sums exceed 2^24 — documented, and the two
# earlier keys (violations, highest victim priority) dominate it.

I32_MAX = 2**31 - 1


@jax.jit
def _preempt_dry_run(alloc, used, npods, maxpods, valid, taint_mask,
                     vict_prio, vict_req, vict_pdb, vict_over,
                     nom_used, nom_np, group_idx, req, prio, untol_hard,
                     active):
    """alloc/used f32[N,R]; npods/maxpods f32[N]; valid bool[N];
    taint_mask f32[N,T]; vict_prio i32[N,V] (VICT_PAD-filled);
    vict_req f32[N,V,R]; vict_pdb f32[N,V]; vict_over bool[N];
    nom_used f32[G,N,R] / nom_np f32[G,N] capacity claimed by pods
    nominated at >= the group's priority (RunFilterPluginsWithNominatedPods);
    group_idx i32[P]; req f32[P,R]; prio i32[P]; untol_hard f32[P,T];
    active bool[P].
    returns the full per-(pod,node) dry-run planes — the host commit
    loop (ops/backend.preempt_batch) runs pickOneNodeForPreemption's
    lexicographic pick over them so it can exclude nodes claimed by
    earlier winners of the SAME wave without another device call:
      (cand bool[P,N], viol f32[P,N], highest i32[P,N], psum f32[P,N],
       nvic f32[P,N], victims bool[P,N,V], overflow_hit bool[P])."""
    P, R = req.shape
    N, V = vict_prio.shape
    eps = 1e-6

    # a PAD slot's priority is I32_MAX, above any clamped real priority,
    # so the single compare also masks empty slots
    elig = vict_prio[None, :, :] < prio[:, None, None]          # [P,N,V]
    eligf = elig.astype(jnp.float32)
    freed = jnp.einsum("pnv,nvr->pnr", eligf, vict_req)         # [P,N,R]
    freed_np = jnp.sum(eligf, axis=-1)                          # [P,N]

    eff_used = used[None, :, :] + nom_used[group_idx]           # [P,N,R]
    eff_np = npods[None, :] + nom_np[group_idx]                 # [P,N]
    taint_ok = jnp.einsum("pt,nt->pn", untol_hard, taint_mask) == 0.0

    free0 = alloc[None, :, :] - eff_used + freed
    slack0 = maxpods[None, :] - (eff_np - freed_np)
    fits0 = jnp.all(req[:, None, :] <= free0 + eps, axis=-1)
    fits0 &= slack0 >= 1.0
    fits0 &= valid[None, :] & taint_ok
    fits0 &= freed_np > 0.0             # empty `potential` -> no candidate
    fits0 &= active[:, None]

    # reprieve order is per-NODE (preemptor-independent): violating
    # first, then highest priority first, slot index (== stable
    # ascending ni.pods order) last — jnp.lexsort's LAST key is primary
    slot_iota = jnp.arange(V, dtype=jnp.int32)
    ordv = jnp.lexsort((jnp.broadcast_to(slot_iota[None, :], (N, V)),
                        -vict_prio, -vict_pdb), axis=-1)        # [N,V]

    # greedy re-add: V static steps, each one "does the preemptor still
    # fit with this victim back?" — reprieved victims return their
    # resources and pod slot before the next step is judged
    free = free0
    slack = slack0
    reprieved = jnp.zeros((P, N, V), bool)
    for s in range(V):
        j = ordv[:, s]                                          # [N]
        onehot = (slot_iota[None, :] == j[:, None])             # [N,V]
        onehotf = onehot.astype(jnp.float32)
        vreq_j = jnp.einsum("nv,nvr->nr", onehotf, vict_req)    # [N,R]
        elig_j = jnp.einsum("nv,pnv->pn", onehotf, eligf) > 0.0
        free_try = free - vreq_j[None, :, :]
        ok = elig_j & jnp.all(req[:, None, :] <= free_try + eps, axis=-1)
        ok &= (slack - 1.0) >= 1.0
        free = jnp.where(ok[:, :, None], free_try, free)
        slack = jnp.where(ok, slack - 1.0, slack)
        reprieved |= ok[:, :, None] & onehot[None, :, :]

    victims = elig & ~reprieved                                  # [P,N,V]
    victf = victims.astype(jnp.float32)
    nvic = jnp.sum(victf, axis=-1)                               # [P,N]
    viol = jnp.sum(victf * vict_pdb[None, :, :], axis=-1)        # [P,N]
    highest = jnp.max(jnp.where(victims, vict_prio[None, :, :],
                                jnp.int32(-I32_MAX)), axis=-1)
    highest = jnp.where(nvic > 0.0, highest, 0)                  # [P,N]
    psum = jnp.sum(victf * vict_prio[None, :, :].astype(jnp.float32),
                   axis=-1)                                      # [P,N]

    # a dry run whose reprieve pass spared everyone is NOT a candidate
    # (selectVictimsOnNode: `if not victims: return None`); overflow rows
    # carry a truncated victim set, so they never win on device — the
    # caller escapes any preemptor that can reach one
    cand = fits0 & (nvic > 0.0) & (~vict_over)[None, :]
    overflow_hit = jnp.any(
        vict_over[None, :] & valid[None, :] & taint_ok & active[:, None],
        axis=1)
    return (cand, viol, highest, psum, nvic, victims, overflow_hit)


def preempt_dry_run(alloc, used, npods, maxpods, valid, taint_mask,
                    vict_prio, vict_req, vict_pdb, vict_over,
                    nom_used, nom_np, group_idx, req, prio, untol_hard,
                    active):
    """Host entry: numpy in, numpy out (one blocking round trip)."""
    out = _preempt_dry_run(
        jnp.asarray(alloc), jnp.asarray(used), jnp.asarray(npods),
        jnp.asarray(maxpods), jnp.asarray(valid), jnp.asarray(taint_mask),
        jnp.asarray(vict_prio), jnp.asarray(vict_req),
        jnp.asarray(vict_pdb), jnp.asarray(vict_over),
        jnp.asarray(nom_used), jnp.asarray(nom_np),
        jnp.asarray(group_idx), jnp.asarray(req), jnp.asarray(prio),
        jnp.asarray(untol_hard), jnp.asarray(active))
    # sync-point: dry-run host entry — the one explicit blocking pull
    return jax.device_get(out)
