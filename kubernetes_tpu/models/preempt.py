"""Batched preemption candidate search — the device half of PostFilter.

Reference: pkg/scheduler/framework/preemption/preemption.go —
  DryRunPreemption (:579) fans goroutines out over candidate nodes, each
  one simulating "remove all lower-priority pods, does the preemptor
  fit?"; the reference samples candidates (GetOffsetAndNumCandidates)
  rather than scanning every node.

TPU-native reshape: the "remove all lower-priority victims" probe is a
pure arithmetic refilter — free'[p,n] = alloc[n] - (used[n] -
reclaimable[g(p),n]) — so ALL failed pods × ALL nodes evaluate in one
fused device op, grouped by pod priority (pods of equal priority see the
same reclaimable matrix).  The device returns each pod's top-k candidate
rows ranked by fewest-potential-victims (the dominant term of
pickOneNodeForPreemption's ordering); the host then runs the exact
reprieve/PDB dry-run (scheduler/preemption.py) on just those k nodes,
preserving reference victim-selection semantics while the O(pods*nodes)
scan stays on device.

Like the reference's sampling, top-k is a candidate LIMIT, not an
approximation of victim selection: every returned candidate is re-proved
host-side by the full filter plugin set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

NEG = -1e30


@functools.partial(jax.jit, static_argnames=("k",))
def _preempt_candidates(alloc, used, npods, maxpods, valid,
                        reclaim, reclaim_np, group_idx, req, active, k):
    """Per failed pod: top-k candidate node rows where preempting every
    lower-priority pod would make it fit.

    alloc/used: f32[N,R]; npods/maxpods: f32[N]; valid: bool[N]
    reclaim: f32[G,N,R] resources reclaimable per priority group
    reclaim_np: f32[G,N] pod-count reclaimable per priority group
    group_idx: i32[P] pod -> priority group; req: f32[P,R]
    active: bool[P] (padding rows inactive)
    returns (rows i32[P,k], feasible_count i32[P])
    """
    rec = reclaim[group_idx]          # [P,N,R]
    rec_np = reclaim_np[group_idx]    # [P,N]
    free = alloc[None, :, :] - (used[None, :, :] - rec)
    fits = jnp.all(req[:, None, :] <= free + 1e-6, axis=-1)
    fits &= (npods[None, :] - rec_np + 1.0) <= maxpods[None, :]
    fits &= valid[None, :]
    fits &= rec_np > 0.0              # no victims -> plain FitError, not
    fits &= active[:, None]           # a preemption candidate
    # rank: fewest potential victims first (pickOneNode's dominant
    # term); WITHIN an equal-victim-count tier, per-POD hash noise
    # deliberately dominates the ordering — equal-priority preemptors
    # otherwise rank every node identically and a whole failure wave
    # converges on the same k candidates: the first k pods nominate
    # them, the rest find every candidate claimed (nominated-pods
    # filter) and re-fail into backoff, draining a 500-pod wave k pods
    # at a time (measured: 31 rounds, ~80 s).  The reference
    # decorrelates the same way with a RANDOM candidate-sampling offset
    # (GetOffsetAndNumCandidates).  The 1e-9*headroom term is a
    # deterministic last-resort tiebreak under identical noise only.
    headroom = jnp.sum(jnp.maximum(free, 0.0), axis=-1)
    P, N = fits.shape
    tie = (((jnp.arange(P, dtype=jnp.uint32)[:, None]
             * jnp.uint32(2654435761))
            ^ (jnp.arange(N, dtype=jnp.uint32)[None, :]
               * jnp.uint32(40503)))
           % jnp.uint32(65536)).astype(jnp.float32) / 65536.0
    score = jnp.where(fits, -rec_np + 1e-9 * headroom + 0.1 * tie, NEG)
    vals, rows = jax.lax.top_k(score, k)
    rows = jnp.where(vals > NEG / 2, rows, -1)
    return rows, jnp.sum(fits, axis=1, dtype=jnp.int32)


def preempt_candidates(alloc, used, npods, maxpods, valid, reclaim,
                       reclaim_np, group_idx, req, active, k: int):
    """Host entry: numpy in, numpy out (one blocking device round trip —
    preemption is the rare path, latency over throughput)."""
    rows, count = _preempt_candidates(
        jnp.asarray(alloc), jnp.asarray(used), jnp.asarray(npods),
        jnp.asarray(maxpods), jnp.asarray(valid), jnp.asarray(reclaim),
        jnp.asarray(reclaim_np), jnp.asarray(group_idx), jnp.asarray(req),
        jnp.asarray(active), k)
    import numpy as np
    return np.asarray(rows), np.asarray(count)
