"""Tensorization + device-side predicates for the TPU scheduling path."""

from .flatten import BatchEncoder, Caps, ClusterTensors, PodBatch  # noqa: F401
