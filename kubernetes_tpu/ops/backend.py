"""TPUBatchBackend — the bridge between the scheduler and the device kernel.

This is the in-process equivalent of the BASELINE north star's
`TPUBatchAssign` plugin + gRPC shim (the shim's wire form lives in
apiserver/batch_service.py): it drains a batch from the queue (done by
scheduler.schedule_batch), flattens the snapshot delta into tensors
(ops/flatten.py), runs feasibility+score+assignment on device
(models/assign.py), and hands back per-pod placements that the scheduler
feeds through the ordinary assume/Reserve/Permit/bind tail.

Escape hatch: pods whose constraints exceed the tensor encoding (vocab
overflow, Gt/Lt node affinity, nominated preemption, ...) come back with a
SKIP status and the scheduler routes them through the per-pod oracle path —
wrong answers are structurally impossible, only coverage varies.
"""

from __future__ import annotations

import logging
import threading
from typing import Sequence

import numpy as np

from ..models.assign import build_assign_fn
from ..scheduler.cache import Snapshot
from ..scheduler.scheduler import BatchBackend
from ..scheduler.types import SKIP, UNSCHEDULABLE, PodInfo, Status
from .flatten import BatchEncoder, Caps, ClusterTensors, VocabFullError

logger = logging.getLogger(__name__)

ESCAPE_STATUS_CODE = SKIP  # scheduler routes SKIP results to schedule_one


class TPUBatchBackend(BatchBackend):
    def __init__(self, caps: Caps | None = None, batch_size: int = 256,
                 weights: dict[str, float] | None = None):
        self.caps = caps or Caps()
        self.batch_size = batch_size
        self.tensors = ClusterTensors(self.caps)
        self.encoder = BatchEncoder(self.tensors, batch_size)
        self._assign = build_assign_fn(self.caps, weights)
        self._device_node: dict | None = None
        self._device_version = -1
        self._lock = threading.Lock()

    # -- BatchBackend ----------------------------------------------------

    def assign(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot
               ) -> list[tuple[int | None, Status | None]]:
        import jax.numpy as jnp

        with self._lock:
            try:
                self.tensors.update_from_snapshot(snapshot)
                batch = self.encoder.encode(list(pod_infos))
            except VocabFullError as e:
                logger.warning("tensorization overflow (%s); whole batch -> oracle path", e)
                return [(None, Status(SKIP, str(e)))] * len(pod_infos)

            cd_sg, cd_asg = self.tensors.domain_base_counts()
            if self._device_version != self.tensors.static_version:
                t = self.tensors
                self._device_node = {
                    "alloc": jnp.asarray(t.alloc),
                    "maxpods": jnp.asarray(t.maxpods),
                    "valid": jnp.asarray(t.valid),
                    "taint_mask": jnp.asarray(t.taint_mask),
                    "label_mask": jnp.asarray(t.label_mask),
                    "key_mask": jnp.asarray(t.key_mask),
                    "dom_sg": jnp.asarray(t.dom_sg),
                    "dom_asg": jnp.asarray(t.dom_asg),
                }
                self._device_version = self.tensors.static_version
            node = dict(self._device_node)
            # dynamic state always re-uploaded: the snapshot is authoritative
            # (it already includes pods assumed by previous batches)
            node["used"] = jnp.asarray(self.tensors.used)
            node["used_nz"] = jnp.asarray(self.tensors.used_nz)
            node["npods"] = jnp.asarray(self.tensors.npods)
            node["port_mask"] = jnp.asarray(self.tensors.port_mask)
            node["cd_sg"] = jnp.asarray(cd_sg)
            node["cd_asg"] = jnp.asarray(cd_asg)

            pod = {
                "req": jnp.asarray(batch.req),
                "req_nz": jnp.asarray(batch.req_nz),
                "p_valid": jnp.asarray(batch.p_valid),
                "untol_hard": jnp.asarray(batch.untol_hard),
                "untol_prefer": jnp.asarray(batch.untol_prefer),
                "sel_any": jnp.asarray(batch.sel_any),
                "sel_any_active": jnp.asarray(batch.sel_any_active),
                "sel_forb": jnp.asarray(batch.sel_forb),
                "key_any": jnp.asarray(batch.key_any),
                "key_any_active": jnp.asarray(batch.key_any_active),
                "key_forb": jnp.asarray(batch.key_forb),
                "ports": jnp.asarray(batch.ports),
                "node_row": jnp.asarray(batch.node_row),
                "c_kind": jnp.asarray(batch.c_kind),
                "c_sg": jnp.asarray(batch.c_sg),
                "c_maxskew": jnp.asarray(batch.c_maxskew),
                "c_selfmatch": jnp.asarray(batch.c_selfmatch),
                "c_weight": jnp.asarray(batch.c_weight),
                "inc_sg": jnp.asarray(batch.inc_sg),
                "inc_asg": jnp.asarray(batch.inc_asg),
                "match_asg": jnp.asarray(batch.match_asg),
            }
            out = self._assign(node, pod)
            assignments = np.asarray(out["assignments"])

        escapes = set(batch.escape)
        results: list[tuple[int | None, Status | None]] = []
        for i in range(len(pod_infos)):
            if i >= self.batch_size or i in escapes:
                results.append((None, Status(SKIP, "escape to per-pod path")))
                continue
            row = int(assignments[i])
            if row < 0:
                results.append((None, Status(
                    UNSCHEDULABLE, "no feasible node (TPU batch filter)")))
            else:
                results.append((row, None))
        return results

    def node_name(self, idx: int) -> str:
        name = self.tensors.node_name(idx)
        if name is None:
            raise KeyError(f"no node at row {idx}")
        return name
