"""TPUBatchBackend — the bridge between the scheduler and the device kernel.

This is the in-process equivalent of the BASELINE north star's
`TPUBatchAssign` plugin + gRPC shim: it drains a batch from the queue (done
by scheduler.schedule_batch), flattens the snapshot delta into tensors
(ops/flatten.py), runs feasibility+score+assignment on device
(models/assign.py wave solver), and hands back per-pod placements that the
scheduler feeds through the ordinary assume/Reserve/Permit/bind tail.

Transport design (the TPU link has ~70ms fixed latency per transfer, so
round trips are the budget — exactly the regime the north star's gRPC shim
targets):
  * node dynamic aggregates (used/npods/ports/domain counts) live ON DEVICE
    between batches; the kernel returns the updated state and we donate it
    back in — zero steady-state node-side traffic.
  * a host numpy mirror replays the kernel's updates; each batch the
    authoritative snapshot arrays are diffed against the mirror, and rows
    changed by EXTERNAL events (pod deleted, bind failed/forgotten, node
    resized) ride a bounded row-patch section of the single packed upload.
    Mirror mismatch beyond the patch budget or domain-count divergence
    falls back to a full dynamic refresh.
  * the pod batch itself is ONE 1-D f32 buffer (ints bitcast), see
    models/assign.PackSpec.

Escape hatch: pods whose constraints exceed the tensor encoding (vocab
overflow, Gt/Lt node affinity, nominated preemption, ...) come back with a
SKIP status and the scheduler routes them through the per-pod oracle path —
wrong answers are structurally impossible, only coverage varies.
"""

from __future__ import annotations

import copy
import dataclasses
import gc
import logging
import os
import pickle
import threading
import time
import zlib
from typing import Sequence

import numpy as np

from ..api import meta
from ..component_base import tracing
from ..component_base.timeline import default_timeline
from ..models.assign import (
    ALL_FEATURES, PLAIN_FEATURES, STATE_KEYS, PackSpec,
    build_packed_assign_fn, pack_pod_batch,
)
from ..scheduler.cache import Snapshot
from ..scheduler.scheduler import BatchBackend
from ..scheduler.types import ERROR, SKIP, UNSCHEDULABLE, PodInfo, Status
from .flatten import (
    C_AFFINITY, C_ANTI_AFFINITY, C_PREF_AFFINITY, C_SPREAD_HARD,
    C_SPREAD_SCORE, BatchEncoder, Caps, ClusterTensors, PodBatch,
    VocabFullError, slice_pod_batch,
)

logger = logging.getLogger(__name__)

DYN_FIELDS = ("used", "used_nz", "npods", "port_mask")

_static_patch_jit = None


# static array split: the selector-side arrays (label/key masks + topology
# domains) are read ONLY by the constraint-carrying kernel variant — at
# 100k nodes they are ~140 MB of the ~160 MB static payload, so the plain
# path never ships them (models/assign._static_mask_and_score reads them
# behind the "selectors" feature gate)
STATIC_CORE = ("alloc", "maxpods", "valid", "taint_mask")
# sg_ns_mask/asg_ns_mask have NO node axis (per-slot namespace masks for
# namespaceSelector terms): they are excluded from the row-patch path and
# ride full uploads only — every mask mutation sets tensors.static_full
STATIC_SEL = ("label_mask", "key_mask", "dom_sg", "dom_asg",
              "sg_ns_mask", "asg_ns_mask")
# victim tensors (batched preemption) are a THIRD upload channel, keyed
# by tensors.vict_version: binds dirty victim rows every batch, but the
# rebuild+upload happen only at preemption time — and must not
# invalidate the static cache (a STATIC_CORE re-upload is multi-MB at
# big N).  Over the remote seam they ride the /static verb (own body
# section), so the checkpoint replay restores them on worker resync.
STATIC_VICT = ("vict_prio", "vict_req", "vict_pdb", "vict_over")

_core_patch_jit = None
_sel_patch_jit = None
_vict_patch_jit = None


def _apply_static_patch(static, rows, alloc_v, maxpods_v, valid_v, taint_v):
    """Row-wise scatter into the RESIDENT core static arrays, so a handful
    of changed nodes costs a few KB of transfer instead of a full
    re-upload.  rows are padded with -1; the jitted scatter is built once
    (shapes vary only in the padded row count, by powers of two)."""
    global _core_patch_jit
    if _core_patch_jit is None:
        import jax
        import jax.numpy as jnp

        # compile-cached: lazy module-level singleton (the `global`
        # guard above); one cache serves every patch upload
        @jax.jit
        def go(static, rows, alloc_v, maxpods_v, valid_v, taint_v):
            n = static["alloc"].shape[0]
            # padding scatters to an OUT-OF-BOUNDS sentinel and is dropped.
            # Do NOT route padding to a masked write of row 0: if row 0 is
            # also genuinely patched, duplicate-index set() picks an
            # arbitrary winner and can resurrect the stale value.
            li = jnp.where(rows >= 0, rows, n)

            def put(a, v):
                return a.at[li].set(v, mode="drop")

            out = dict(static)
            out["alloc"] = put(static["alloc"], alloc_v)
            out["maxpods"] = put(static["maxpods"], maxpods_v)
            out["valid"] = put(static["valid"], valid_v)
            out["taint_mask"] = put(static["taint_mask"], taint_v)
            return out

        _core_patch_jit = go
    return _core_patch_jit(static, rows, alloc_v, maxpods_v, valid_v,
                           taint_v)


def _apply_sel_patch(sel, rows, label_v, key_v, dom_sg_v, dom_asg_v):
    """Row-wise scatter for the selector-side static arrays (same padding
    contract as _apply_static_patch)."""
    global _sel_patch_jit
    if _sel_patch_jit is None:
        import jax
        import jax.numpy as jnp

        # compile-cached: lazy module-level singleton (the `global`
        # guard above); one cache serves every patch upload
        @jax.jit
        def go(sel, rows, label_v, key_v, dom_sg_v, dom_asg_v):
            n = sel["label_mask"].shape[0]
            li = jnp.where(rows >= 0, rows, n)
            out = dict(sel)
            out["label_mask"] = sel["label_mask"].at[li].set(
                label_v, mode="drop")
            out["key_mask"] = sel["key_mask"].at[li].set(key_v, mode="drop")
            out["dom_sg"] = sel["dom_sg"].at[:, li].set(dom_sg_v, mode="drop")
            out["dom_asg"] = sel["dom_asg"].at[:, li].set(
                dom_asg_v, mode="drop")
            return out

        _sel_patch_jit = go
    return _sel_patch_jit(sel, rows, label_v, key_v, dom_sg_v, dom_asg_v)


def _apply_vict_patch(vict, rows, prio_v, req_v, pdb_v, over_v):
    """Row-wise scatter for the victim tensors (same padding contract as
    _apply_static_patch: rows padded with -1 scatter out of bounds)."""
    global _vict_patch_jit
    if _vict_patch_jit is None:
        import jax
        import jax.numpy as jnp

        # compile-cached: lazy module-level singleton (the `global`
        # guard above); one cache serves every patch upload
        @jax.jit
        def go(vict, rows, prio_v, req_v, pdb_v, over_v):
            n = vict["vict_prio"].shape[0]
            li = jnp.where(rows >= 0, rows, n)
            out = dict(vict)
            out["vict_prio"] = vict["vict_prio"].at[li].set(
                prio_v, mode="drop")
            out["vict_req"] = vict["vict_req"].at[li].set(req_v, mode="drop")
            out["vict_pdb"] = vict["vict_pdb"].at[li].set(pdb_v, mode="drop")
            out["vict_over"] = vict["vict_over"].at[li].set(
                over_v, mode="drop")
            return out

        _vict_patch_jit = go
    return _vict_patch_jit(vict, rows, prio_v, req_v, pdb_v, over_v)


# dispatch() sentinel: an earlier batch is still in flight and this batch
# needs row patches / a refresh, which would clobber the in-flight batch's
# device-side accounting.  The caller must resolve the in-flight batch and
# finish its tail (so the authoritative tensors catch up), then re-dispatch.
FLUSH_FIRST = object()


# -- checkpointed warm-start (zero-downtime operations) --------------------
#
# A checkpoint is the HOST half of the backend only: the ClusterTensors
# (numpy arrays + slot allocator + vocabularies + selector-group buckets)
# plus per-node adoption digests and the informer resourceVersions the
# state was current at.  Device state is deliberately absent — every
# lineage rebuilds it through its own _upload_static/_full_refresh on the
# first dispatch, which is what makes one checkpoint format portable
# across the single-chip, sharded and remote-seam backends.

CHECKPOINT_MAGIC = b"KTPUCKPT"

# Payload schema: exactly the keys the warm-start reader consumes.
# Adding, removing or renaming a field MUST bump CHECKPOINT_SCHEMA_VERSION
# and re-record the digest comment below — a version-mismatched checkpoint
# is rejected (cold start), never silently misread (ktpu-lint rule
# checkpoint-versioned enforces the bump).
CHECKPOINT_FIELDS = (
    "caps",
    "batch_size",
    "lineage",
    "objects",
    "resource_versions",
    "tensors",
    "warm_digests",
)
# schema-digest: 2576856108@v1
CHECKPOINT_SCHEMA_VERSION = 1


class CheckpointError(RuntimeError):
    """Checkpoint unusable (missing, corrupt, schema/caps mismatch).
    warm_start raises BEFORE touching any backend state, so the caller
    falls back to an ordinary cold start — never partial installs."""


def _warm_digest(ni) -> tuple:
    """Content signature of one NodeInfo, comparable ACROSS processes
    (generation counters are per-process and useless here): the node
    object's resourceVersion plus the resident pod set with per-pod
    resourceVersions.  Equal digests => the row encode would be
    bit-identical, so the checkpointed row can be adopted as-is."""
    return (meta.resource_version(ni.node),
            tuple(sorted((pi.key, meta.resource_version(pi.pod))
                         for pi in ni.pods)))


def _trace_parent():
    """The scheduler-installed batch root span for THIS thread (see
    component_base/tracing use_span), or None when the pipeline is
    untraced or the root was not sampled — callers then skip every span
    and attribute computation, so tracing off costs nothing on the
    dispatch path."""
    span = tracing.current_span()
    if span is None or not span.sampled:
        return None
    return span


def decode_results(assignments, n: int, batch_size: int, escapes: set,
                   row_names: list, no_fit_msg: str,
                   nofit_escapes: set | None = None
                   ) -> list[tuple[str | None, Status | None]]:
    """Shared assignment decode (single-chip + sharded backends): map each
    pod slot to (node_name, status).  `row_names` is the tensors' row_names
    list CAPTURED AT DISPATCH — a later dispatch may recycle rows, so names
    must resolve against the batch's own view.  It is a list of STRINGS on
    purpose: the zero-copy cache view shares live NodeInfos whose .node the
    cache nulls in place when a drained node still holds pods, so resolving
    NodeInfo.name across the dispatch->resolve gap can yield "" — and a
    bind to nodeName "" is a silently lost pod (nothing ever requeues it).

    `nofit_escapes`: pods whose constraints rode COLLIDED (shared)
    selector-group buckets — for them a no-fit verdict is an upper-bound
    artifact, so they go to the per-pod oracle instead of
    UNSCHEDULABLE.  A placement is always sound; only no-fit needs the
    re-proof (flatten.GroupBucket)."""
    rows = np.asarray(assignments, np.int64).tolist()  # ONE bulk convert,
    # not int(arr[i]) per pod (np scalar indexing costs ~0.5µs each)
    results: list[tuple[str | None, Status | None]] = []
    for i in range(n):
        if i >= batch_size or (escapes and i in escapes):
            results.append((None, Status(SKIP, "escape to per-pod path")))
            continue
        row = rows[i]
        if row < 0 and nofit_escapes and i in nofit_escapes:
            results.append((None, Status(
                SKIP, "no-fit under shared constraint buckets; "
                      "per-pod re-proof")))
            continue
        if row < 0:
            results.append((None, Status(UNSCHEDULABLE, no_fit_msg)))
            continue
        name = row_names[row]
        if not name:
            # invariant violation (device placed onto an invalid row):
            # surface it loudly — the device-side capacity claim is now
            # phantom until the next refresh, and silently reporting
            # "no feasible node" would mask the encoding bug
            results.append((None, Status(
                ERROR, f"device assigned row {row} with no node name "
                       "(encoder/valid-mask bug)")))
        else:
            results.append((name, None))
    return results


def record_batch_stats(stats: dict, lock, results, n: int) -> None:
    """Escape accounting shared by the single-chip and sharded backends:
    pods seen / pods skipped to the per-pod oracle (encoder escapes +
    collided-bucket no-fit re-proofs) — the coverage metric the
    high-cardinality bench reports."""
    esc = sum(1 for _nm, s in results if s is not None and s.is_skip())
    with lock:
        stats["pods"] = stats.get("pods", 0) + n
        stats["escaped"] = stats.get("escaped", 0) + esc


class ResidentHostMirror:
    """Host-side replay mirror shared by the single-chip and sharded
    backends: the device keeps the node dynamics resident; the host
    mirror replays the kernel's commit rules so the next dispatch can
    diff authoritative-vs-mirror and upload only externally-changed rows.
    Consumers provide: self.tensors, self._mirror, self._f_patch,
    self._k_cap, self.batch_size."""

    # warm-start adoption digests ({node name: _warm_digest}), installed
    # by warm_start and consumed one-shot by _try_warm_adopt.  The class
    # default is an always-empty dict (never mutated: every touch is
    # guarded by truthiness) so cold-started backends pay nothing.
    _warm_pending: dict = {}

    def prefetch(self, snapshot) -> None:
        """Idle-time tensor sync: absorb node churn into the host arrays
        while nothing is queued or in flight, so the next dispatch's
        tracked update sees only fresh deltas (a 100k-node creation flood
        otherwise lands inside the first scheduling cycle).  Re-encoded
        rows carry into the next dispatch's patch diff."""
        with self._lock:
            if self._unresolved:
                return
            if self._warm_pending:
                self._warm_sweep(snapshot)
            epoch_fn = getattr(snapshot, "epoch", None)
            epoch = epoch_fn() if epoch_fn is not None else None
            if epoch is not None and epoch == self._last_epoch:
                return  # nothing external changed: the scan is a no-op
            t_sync = time.monotonic()
            try:
                dirty = set(self.tensors.update_from_snapshot_tracked(
                    snapshot))
            except VocabFullError:
                self._state = None  # force a refresh on next dispatch
                return
            finally:
                self.stats["flatten_seconds"] = self.stats.get(
                    "flatten_seconds", 0.0) + (time.monotonic() - t_sync)
            self._carry_dirty |= dirty
            self._last_epoch = epoch
            # no compaction here — idle-prefetch timing is wall-clock
            # driven; reclamation happens at the dispatch gate so row
            # reuse order is a pure function of the wave/event stream

    def _needs_full(self, batch: PodBatch) -> bool:
        """Batches using selectors/constraints/ports/pins need the
        constraint-carrying kernel; the common plain case runs a variant
        with those code paths elided (models/assign PLAIN_FEATURES).
        Lazy PodBatch fields: None == all-zeros == feature absent."""
        def nz(a):
            return a is not None and a.any()
        t = self.tensors
        return bool(
            t.sgs or t.asgs or nz(batch.c_kind)
            or nz(batch.sel_any_active) or nz(batch.key_any_active)
            or nz(batch.sel_forb) or nz(batch.key_forb)
            or nz(batch.ports) or nz(batch.untol_prefer)
            or (batch.node_row is not None and (batch.node_row >= 0).any()))

    def _diff_patches(self, dirty_rows) -> tuple[np.ndarray, np.ndarray] | None:
        """Rows where authoritative != mirror (read-only; mirror untouched).
        None -> too many (refresh).  Vectorized: a 16k-bind batch dirties
        16k rows every dispatch, and a per-row python compare loop cost
        ~150ms at that scale."""
        t, m = self.tensors, self._mirror
        if not dirty_rows:
            return np.empty(0, np.int32), np.empty((0, self._f_patch),
                                                   np.float32)
        cand = np.fromiter(dirty_rows, np.int64, len(dirty_rows))
        changed = ((t.used[cand] != m["used"][cand]).any(axis=1)
                   | (t.used_nz[cand] != m["used_nz"][cand]).any(axis=1)
                   | (t.npods[cand] != m["npods"][cand])
                   | (t.port_mask[cand] != m["port_mask"][cand]).any(axis=1))
        rows_a = cand[changed].astype(np.int32)
        if len(rows_a) > self._k_cap:
            return None
        if not len(rows_a):
            return np.empty(0, np.int32), np.empty((0, self._f_patch),
                                                   np.float32)
        vals = np.concatenate([
            t.used[rows_a], t.used_nz[rows_a], t.npods[rows_a][:, None],
            t.port_mask[rows_a]], axis=1).astype(np.float32)
        return rows_a, vals

    def _sync_mirror_rows(self, rows_a: np.ndarray) -> None:
        """Bring the mirror in line with what the device will hold after the
        row patch uploads authoritative values."""
        t, m = self.tensors, self._mirror
        for f in DYN_FIELDS:
            m[f][rows_a] = getattr(t, f)[rows_a]

    def _mirror_from_tensors(self, cd_sg: np.ndarray,
                             cd_asg: np.ndarray) -> None:
        t = self.tensors
        self._mirror = {
            "used": t.used.copy(), "used_nz": t.used_nz.copy(),
            "npods": t.npods.copy(), "port_mask": t.port_mask.copy(),
            "cd_sg": cd_sg.copy(), "cd_asg": cd_asg.copy(),
        }

    def _replay(self, batch: PodBatch, assignments: np.ndarray) -> None:
        """Apply the kernel's commit rules to the host mirror.  Fully
        vectorized: np.add.at / maximum.at accumulate correctly when many
        pods land on the same row (a per-pod Python loop here cost
        ~15ms/batch at bench shapes)."""
        t, m = self.tensors, self._mirror
        n = min(len(assignments), self.batch_size)
        rows = np.asarray(assignments[:n], np.int64)
        placed = np.nonzero(rows >= 0)[0]
        if placed.size == 0:
            return
        prow = rows[placed]
        np.add.at(m["used"], prow, batch.req[placed])
        np.add.at(m["used_nz"], prow, batch.req_nz[placed])
        np.add.at(m["npods"], prow, 1.0)
        if batch.ports is not None:
            np.maximum.at(m["port_mask"], prow, batch.ports[placed])
        if batch.inc_sg is not None:
            for sg in range(len(t.sgs)):
                inc = placed[batch.inc_sg[placed, sg] > 0]
                if inc.size:
                    d = t.dom_sg[sg, rows[inc]]
                    np.add.at(m["cd_sg"][sg], d[d >= 0], 1.0)
        if batch.inc_asg is not None:
            for a in range(len(t.asgs)):
                inc = placed[batch.inc_asg[placed, a] > 0]
                if inc.size:
                    d = t.dom_asg[a, rows[inc]]
                    np.add.at(m["cd_asg"][a], d[d >= 0], 1.0)

    # -- event-driven tensor maintenance (incremental flatten) -----------

    # compact when tombstoned slots exceed n_cap / COMPACT_TOMBSTONE_DIV
    # (and never while a wave is in flight — it references rows by index)
    COMPACT_TOMBSTONE_DIV = 16

    def note_node_event(self, event_type: str, name: str, view) -> None:
        """Node informer feed: apply one add/update/delete event as a
        targeted row patch on the resident host tensors, so the wave-time
        drain finds the row already generation-current and the device
        upload shrinks to the genuinely-changed rows.  `view` is the
        cache's CacheFlattenView; the NodeInfo is read under the cache
        lock (backend lock -> cache lock, the order dispatch takes).  Any
        patch-path error leaves the event pending for the wave-time drain
        — the full re-flatten is the recovery path, never lost state."""
        run_node = getattr(view, "run_locked_node", None)
        if run_node is None:
            return
        t0 = time.monotonic()
        with self._lock:
            t = self.tensors

            def _apply(ni):
                if ni is None:
                    if self._warm_pending:
                        self._warm_pending.pop(name, None)
                    return t.patch_remove(name)
                if self._warm_pending and self._try_warm_adopt(name, ni):
                    return None  # row adopted verbatim: nothing dirty
                return t.patch_node(name, ni)

            try:
                row = run_node(name, _apply)
            except VocabFullError:
                self._state = None  # force a refresh on next dispatch
                return
            except Exception:
                logger.exception(
                    "node event patch failed; deferring to wave drain")
                return
            finally:
                self.stats["patch_seconds"] = self.stats.get(
                    "patch_seconds", 0.0) + (time.monotonic() - t0)
            if row is not None:
                self._carry_dirty.add(row)
                self.stats["event_patches"] = self.stats.get(
                    "event_patches", 0) + 1
            # NO compaction here: event arrival time relative to the
            # in-flight window depends on pipeline depth, and compaction
            # order is visible in row tie-breaks (see the dispatch gate,
            # which reclaims at the wave boundary deterministically)

    def _maybe_compact(self) -> None:
        """Reclaim tombstoned row slots (caller holds the backend lock).
        Skipped while any wave is in flight: an in-flight batch resolves
        against rows captured by index at dispatch.  Only the warm-start
        sweep calls this (boot-time, before any wave, so deterministic);
        steady-state reclamation lives in the dispatch gate, where it is
        anchored to the wave boundary and cannot vary with pipeline
        depth."""
        t = self.tensors
        if self._unresolved:
            return
        if (t.tombstone_count() * self.COMPACT_TOMBSTONE_DIV
                >= self.caps.n_cap):
            if t.compact():
                self.stats["compactions"] = self.stats.get(
                    "compactions", 0) + 1

    def maintenance_snapshot(self) -> dict:
        """Tensor-maintenance readout for the observatory: occupancy /
        tombstone gauges plus the patched-vs-reflattened wave counters
        (scheduler.expose_metrics incs the counter metrics by deltas)."""
        with self._lock:
            t = self.tensors
            s = self.stats
            return {
                "row_occupancy": t.row_occupancy(),
                "tombstone_rows": t.tombstone_count(),
                "waves_patched": s.get("waves_patched", 0),
                "waves_reflattened": s.get("waves_reflattened", 0),
                "event_patches": s.get("event_patches", 0),
                "compactions": s.get("compactions", 0),
                "gen_stale_waves": s.get("gen_stale_waves", 0),
                "patch_seconds": s.get("patch_seconds", 0.0),
                "flatten_seconds": s.get("flatten_seconds", 0.0),
            }

    def _restore_state_from_mirror(self) -> None:
        """Generation-fence recovery: rebuild the device wave state from
        the host replay mirror — which already includes every replay
        committed so far, so re-running a fenced wave's retained chunk
        buffers against this state reproduces exactly what a healthy
        wave would have produced.  Bumping the host generation FIRST
        also fences any pipelined successor dispatched off the stale
        lineage: it self-heals at its own resolve."""
        import jax.numpy as jnp
        m = self._mirror
        self._gen += 1
        state = {k: jnp.asarray(m[k]) for k in
                 ("used", "used_nz", "npods", "port_mask",
                  "cd_sg", "cd_asg")}
        state["gen"] = jnp.asarray(self._gen, jnp.int32)
        self._state = state
        self.stats["gen_recoveries"] = self.stats.get(
            "gen_recoveries", 0) + 1

    # -- checkpointed warm-start (zero-downtime operations) ---------------

    def checkpoint_mirror(self, path: str, *, snapshot=None,
                          resource_versions=None, objects=None) -> dict:
        """Serialize the resident host state to `path` (atomic
        tmp+rename): tensors, per-node adoption digests, the informer
        resourceVersions the state was current at, and optionally the
        raw objects to prime a restarted informer with.  Taken under the
        backend lock between waves (the drain path resolves in-flight
        work first), so the payload is a consistent cut.

        Pass `snapshot` (the cache flatten view) to catch the tensors up
        with binds committed after the last drain before cutting.  A
        digest is only recorded for rows whose generation markers are
        current with the NodeInfo they alias — node_infos are the live
        cache objects, mutated in place after encode, so a stale row's
        digest would certify content the tensors don't hold."""
        with self._lock:
            t = self.tensors
            if snapshot is not None:
                t.update_from_snapshot_tracked(snapshot)
            digests = {}
            for row, ni in enumerate(t.node_infos):
                if (ni is not None and t.valid[row]
                        and t.gen[row] == ni.generation
                        and t.node_gen[row] == ni.node_generation):
                    digests[ni.name] = _warm_digest(ni)
            # Serialize a shallow copy with the NodeInfo graph stripped:
            # node_infos are THIS process's live cache objects — the
            # restarted process rebuilds its own from the primed informer
            # and re-links them row-by-row through _try_warm_adopt, so
            # shipping the graph only bloats the blob and dominates the
            # unpickle (the object graph costs ~100x the raw arrays to
            # load).  _dyn_digest goes with it: warm_start resets both
            # before install.  The numpy arrays are shared references;
            # pickle copies them into the blob untouched.
            t_ser = copy.copy(t)
            t_ser.node_infos = [None] * t.caps.n_cap
            t_ser._dyn_digest = [None] * t.caps.n_cap
            payload = {
                "caps": dataclasses.asdict(self.caps),
                "batch_size": self.batch_size,
                "lineage": getattr(self, "census_kind", "tpu"),
                "objects": objects,
                "resource_versions": dict(resource_versions or {}),
                "tensors": t_ser,
                "warm_digests": digests,
            }
            # cyclic GC off for the bulk dump: the serializer allocates
            # millions of temporaries and every generational collection
            # re-walks the (large, live) cache heap a draining scheduler
            # holds — measured ~6x on the load side at the 100k tier
            gc_was = gc.isenabled()
            gc.disable()
            try:
                blob = pickle.dumps(payload,
                                    protocol=pickle.HIGHEST_PROTOCOL)
            finally:
                if gc_was:
                    gc.enable()
        header = (CHECKPOINT_MAGIC
                  + CHECKPOINT_SCHEMA_VERSION.to_bytes(4, "big")
                  + zlib.crc32(blob).to_bytes(4, "big"))
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(header + blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return {"path": path, "bytes": len(header) + len(blob),
                "nodes": len(digests)}

    def warm_start(self, path: str) -> dict:
        """Install a checkpoint into this (freshly constructed) backend.

        Validation happens before any mutation: bad magic, schema-version
        mismatch, body corruption or caps mismatch raise CheckpointError
        and leave the backend untouched (the caller cold-starts).  On
        success the tensors are installed with every per-process currency
        marker reset stale — gen/node_gen/_dyn_digest carry ANOTHER
        process's cache counters, and a coincidental match against this
        process's generations would let _sync_rows/patch_node silently
        skip a changed row.  Rows regain currency only through
        _try_warm_adopt's content-digest check as the (primed) informer
        replays them; anything unadopted re-encodes through the ordinary
        sync paths.  Returns {resource_versions, objects, nodes,
        lineage} so the caller can prime its informers and re-sync only
        the delta since the checkpoint."""
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointError(f"checkpoint unreadable: {e}") from e
        hlen = len(CHECKPOINT_MAGIC) + 8
        if len(raw) < hlen or not raw.startswith(CHECKPOINT_MAGIC):
            raise CheckpointError("not a ktpu checkpoint (bad magic)")
        version = int.from_bytes(raw[len(CHECKPOINT_MAGIC):
                                     len(CHECKPOINT_MAGIC) + 4], "big")
        if version != CHECKPOINT_SCHEMA_VERSION:
            raise CheckpointError(
                f"checkpoint schema v{version} != supported "
                f"v{CHECKPOINT_SCHEMA_VERSION}")
        crc = int.from_bytes(raw[hlen - 4:hlen], "big")
        # memoryview, not raw[hlen:]: slicing would copy the body (a
        # second ~hundreds-of-MB buffer at the 100k tier) and the double
        # allocation measurably slows the unpickle that follows
        blob = memoryview(raw)[hlen:]
        if zlib.crc32(blob) != crc:
            raise CheckpointError("checkpoint body corrupt (crc mismatch)")
        # cyclic GC off for the bulk load: unpickling the object payload
        # allocates millions of small containers, and with a warm cache
        # heap already resident each generational collection re-walks it
        # all — measured ~6x wall-clock on the load at the 100k tier
        gc_was = gc.isenabled()
        gc.disable()
        try:
            payload = pickle.loads(blob)
        except Exception as e:  # noqa: BLE001 - any unpickle failure
            raise CheckpointError(f"checkpoint undecodable: {e}") from e
        finally:
            if gc_was:
                gc.enable()
        if (not isinstance(payload, dict)
                or set(payload) != set(CHECKPOINT_FIELDS)):
            raise CheckpointError("checkpoint payload shape mismatch")
        if payload["caps"] != dataclasses.asdict(self.caps):
            raise CheckpointError(
                "checkpoint caps do not match this backend's caps")
        t = payload["tensors"]
        with self._lock:
            # stale-currency reset: see docstring.  Full-upload flags are
            # forced so the first dispatch rebuilds every device channel
            # from the installed tensors.
            # patch-ok: pre-install currency reset on a detached tensor
            # set — no device copy exists yet to desynchronize
            t.gen[:] = -1
            t.node_gen[:] = -1
            t._dyn_digest = [None] * t.caps.n_cap
            t.static_full = True
            t.vict_full = True
            t.static_dirty_rows = set()
            self.tensors = t
            self.encoder = BatchEncoder(t, self.batch_size)
            self._state = None
            self._static_node = None
            self._static_version = -1
            if hasattr(self, "_static_sel"):
                self._static_sel = None
                self._sel_stale = True
            if hasattr(self, "_static_vict"):
                self._static_vict = None
                self._vict_version = -1
            self._mirror = None
            self._unresolved = []
            self._carry_dirty = set()
            self._last_epoch = None
            if hasattr(self, "_fence_pending"):
                self._fence_pending = 0
            if hasattr(self, "_stage_pins"):
                self._stage_pins.clear()
            if hasattr(self, "_journal"):
                # remote seam: the replay journal and the ready-to-post
                # checkpoint bodies describe the PRE-restart state
                self._journal = []
                self._journal_overflow = False
                self._ckpt_static_body = None
                self._ckpt_refresh_body = None
            self._warm_pending = dict(payload["warm_digests"])
            self.stats["warm_starts"] = self.stats.get(
                "warm_starts", 0) + 1
        return {"resource_versions": payload["resource_versions"],
                "objects": payload["objects"],
                "nodes": len(payload["warm_digests"]),
                "lineage": payload["lineage"]}

    def _try_warm_adopt(self, name: str, ni) -> bool:
        """Adopt one checkpointed row for a live NodeInfo (caller holds
        the backend lock; the NodeInfo is read under the cache lock).
        One-shot per name: the digest is popped, and only an exact
        content match restores the row's generation currency — a
        mismatch (node or pods changed across the restart) leaves the
        row stale so patch_node/_sync_rows re-encode it."""
        dg = self._warm_pending.pop(name, None)
        if dg is None:
            return False
        t = self.tensors
        row = t.row_of.get(name)
        if row is None or not t.valid[row] or dg != _warm_digest(ni):
            return False
        t.node_infos[row] = ni
        # patch-ok: digest-proven adoption — the row's encoded content
        # already equals this NodeInfo, only the currency stamps move
        t.gen[row] = ni.generation
        t.node_gen[row] = ni.node_generation
        self.stats["warm_adopted"] = self.stats.get("warm_adopted", 0) + 1
        return True

    def _warm_sweep(self, snapshot) -> int:
        """One-shot warm alignment (caller holds the backend lock): in a
        single pass under the cache lock, adopt every checkpointed row
        whose live NodeInfo content-matches its digest, drop rows for
        nodes no longer live (deleted during the restart window), and
        retire the leftover digests — from here on the ordinary sync
        paths own every row.  The initial informer replay arrives as a
        BULK ADDED burst (scheduler._on_node_events) that bypasses
        note_node_event, so the first prefetch/dispatch calls this
        before its snapshot sync.  Returns the rows dropped."""
        t = self.tensors
        dropped = 0

        def go(infos):
            nonlocal dropped
            live = set()
            for ni in infos:
                live.add(ni.name)
                self._try_warm_adopt(ni.name, ni)
            for name in list(t.row_of):
                if name not in live and t.patch_remove(name) is not None:
                    dropped += 1

        run_locked = getattr(snapshot, "run_locked", None)
        if run_locked is not None:
            run_locked(go)
        else:
            go(snapshot.node_info_list)
        self._warm_pending = {}
        if dropped:
            self._maybe_compact()
        return dropped

    def warm_align(self, snapshot) -> int:
        """Public wrapper around the warm sweep, for callers (procrun
        child boot) that want alignment at a deterministic point — right
        after cache sync — instead of lazily at the first wave."""
        with self._lock:
            if not self._warm_pending:
                return 0
            return self._warm_sweep(snapshot)


class TPUBatchBackend(ResidentHostMirror, BatchBackend):
    census_kind = "tpu"

    def __init__(self, caps: Caps | None = None, batch_size: int = 256,
                 weights: dict[str, float] | None = None, k_cap: int = 1024,
                 full_batch_cap: int | None = None):
        self.caps = caps or Caps()
        self.batch_size = batch_size
        self.tensors = ClusterTensors(self.caps)
        self.encoder = BatchEncoder(self.tensors, batch_size)
        # The constraint-carrying ("full") kernel variant materializes
        # ~58 bytes per (pod, node) cell in [P,N] planes (at 100k nodes a
        # 16k batch wants ~100G HBM) AND its wave tail runs [P,P]
        # conflict matrices for up to ~P waves when hard constraints
        # serialize admission (3-zone spreading admits ~zones*maxSkew
        # pods per wave).  Both costs cap the full variant at its own P —
        # hard ceiling 1024, lower if HBM demands — and oversized
        # constraint batches chunk through it with resident state
        # chaining, while the PLAIN variant (Pallas fused tile, no [P,N]
        # planes, O(contention) waves) keeps the whole batch.
        if full_batch_cap is None:
            budget = float(os.environ.get("KTPU_FULL_HBM_BUDGET", 11e9))
            fit = int(budget / (64 * self.caps.n_cap))
            # ceiling 4096 (was 1024): the [P,P] wave tail converges in
            # ~13 waves at P=4096/N=5632 and the chip does the whole
            # batch in one ~0.6s call vs 4 serial chunked calls — the
            # old 1024 ceiling was set before group-level domain gathers
            # fixed the wave cost.  HBM still caps it at big N (100k
            # nodes -> 1024).
            full_batch_cap = 4096
            while full_batch_cap > 256 and full_batch_cap > fit:
                full_batch_cap //= 2
        self.full_cap = min(full_batch_cap, batch_size)
        # MAIN constraint-kernel wave cap: the first couple of waves
        # admit ~98% of a batch (water-filling + multi-claim prefix
        # sums); the tail waves each admit a handful of stragglers at
        # full [P,N] cost.  Setting a cap (e.g. 3) drains that tail
        # through the small retry kernel (resolve()) instead — a win
        # ONLY when a device call is cheap: each retry chunk is its own
        # device round trip, so over the ~100-300ms tunnel the extra
        # RTs cost more than the in-call tail waves they replace (A/B
        # on the tunnel: TopologySpreading 9.1k pods/s uncapped vs 3.6k
        # with cap 3).  Default 0 = uncapped main kernel, no retry;
        # direct-attached deployments (~0.1ms dispatch) should set
        # KTPU_FULL_MAIN_WAVES=3.  Read per-instance (like the HBM
        # budget above), not at import.
        self.FULL_MAIN_WAVES = int(
            os.environ.get("KTPU_FULL_MAIN_WAVES", "0"))
        # A/B baseline knob: disable the epoch fast path so every wave
        # pays the snapshot re-encode (flatten honors the same env by
        # forcing the O(nodes) full scan) — the pre-incremental world,
        # used by bench to pin the maintenance win in-band
        self.FORCE_REFLATTEN = bool(os.environ.get("KTPU_FORCE_REFLATTEN"))
        self._fn_full = None   # built lazily / in warmup
        self._spec_full = None
        self._fn_full_small = None   # straggler retry kernel (lazy)
        self._spec_full_small = None
        self._spec_plain = None
        self._static_sel = None   # selector-side static arrays (lazy)
        self._sel_stale = True
        self._spec = PackSpec(self.caps, batch_size, k_cap)
        self._f_patch = self._spec.f_patch
        self._weights = weights
        self._fn_plain = None  # built lazily on first plain batch
        self._k_cap = k_cap
        self._lock = threading.Lock()
        # device-resident state + host replay mirror
        self._state = None          # dict of device arrays (STATE_KEYS)
        self._static_node = None    # dict of device arrays (rarely changes)
        self._static_version = -1
        self._static_vict = None    # device victim tensors (lazy; preempt)
        self._vict_version = -1
        self._mirror: dict[str, np.ndarray] | None = None
        # dispatched-but-unresolved batches (pipeline bookkeeping) and node
        # rows whose dirtiness must survive an early-exit dispatch attempt
        self._unresolved: list[object] = []
        self._carry_dirty: set[int] = set()
        # cache external-mutation epoch at last tensor sync: when the view
        # reports the same epoch, every change since was our own replayed
        # binds and the whole re-encode + mirror diff is skipped
        self._last_epoch: int | None = None
        # host-side expectation of the device state-generation counter:
        # _device_step bumps it 1:1 with the kernel's own gen+1, so a
        # resolve whose result tail disagrees proves the wave chained on
        # state the host never committed (lost patch / restored worker)
        self._gen = 0
        # steady-state pipeline fence: >0 while a fenced wave (one that
        # dispatched with mid-pipeline patches deliberately excluded from
        # its upload) has not yet resolved.  Its first device run is
        # known-stale by construction — the extra gen bump at dispatch
        # guarantees the fence trips — and the authoritative result comes
        # from the mirror-restored re-run at its resolve.  While a fence
        # is pending, further patch-carrying dispatches FLUSH_FIRST: a
        # second fence would have to replay against a mirror the pending
        # one has not finished restoring.
        self._fence_pending = 0
        # host staging ring for packed upload buffers: the device copy is
        # DONATED to the step (HBM stays flat at any pipeline depth), and
        # the host buffer is recycled wave-to-wave instead of allocated
        # per dispatch.  Pinned ids are buffers a dispatched-but-
        # unresolved wave retains for a possible fenced re-run — the ring
        # never hands those out.
        self._stage_ring: list[np.ndarray] = []
        self._stage_pins: set[int] = set()
        self.stats = {"batches": 0, "full_refresh": 0, "patched_rows": 0,
                      "waves": 0, "flush_first": 0, "waves_patched": 0,
                      "waves_reflattened": 0, "event_patches": 0,
                      "patch_seconds": 0.0, "flatten_seconds": 0.0}
        # batch-telemetry drains (scheduler._finish_batch): per-(plugin,
        # reason) escape tallies applied as Counter DELTAS (inc-only), and
        # per-batch telemetry dicts (mask densities, feasible nodes,
        # waves) for the gauge/histogram metrics.  Own lock: dispatch and
        # resolve both hold self._lock while tallying.
        self._esc_lock = threading.Lock()
        self._escape_pending: dict[tuple[str, str], int] = {}
        self._telemetry_pending: list[dict] = []

    # -- namespace events ------------------------------------------------

    def note_namespace_event(self, event_type: str, obj, old=None) -> None:
        """Namespace informer feed: keep the flattener's namespace-label
        cache (namespaceSelector resolution) in sync with the cluster.
        Runs under the backend lock so a relabel is applied atomically
        between batches — the next encode sees the new resolved sets."""
        with self._lock:
            self.tensors.note_namespace(obj, deleted=event_type == "DELETED")

    def note_pdb_event(self, event_type: str, obj, old=None) -> None:
        """PodDisruptionBudget informer feed: keeps the flattener's PDB
        cache in sync so the device victim PDB-coverage bits stay exact.
        Coverage bits re-encode lazily at the next preemption wave."""
        with self._lock:
            self.tensors.note_pdb(obj, deleted=event_type == "DELETED")

    # -- device sync -----------------------------------------------------

    def warmup(self) -> None:
        """Compile both kernel variants and initialize the device backend
        before the first real batch.  Backend bring-up (~seconds on a
        tunneled chip) and jit compile otherwise land inside the first
        scheduling cycle, which both hurts first-pod latency and pollutes
        throughput measurement windows."""
        import jax
        import jax.numpy as jnp
        with self._lock:
            if self._static_node is None:
                self._upload_static()
            cd_sg, cd_asg = self.tensors.domain_base_counts()
            if self._state is None:
                self._full_refresh(cd_sg, cd_asg)
            batch = self.encoder.encode([])
            empty = (np.empty(0, np.int32),
                     np.empty((0, self._f_patch), np.float32))
            # an all-invalid batch leaves the resident state numerically
            # unchanged, so running it through both variants is free
            self._ensure_full()
            a = self._device_step("full", pack_pod_batch(
                slice_pod_batch(batch, 0, 0, self.full_cap),
                self._spec_full, *empty))
            if self.FULL_MAIN_WAVES:
                self._ensure_full_small()
                a = self._device_step("full_small", pack_pod_batch(
                    slice_pod_batch(batch, 0, 0, self._retry_cap()),
                    self._spec_full_small, *empty))
            self._ensure_plain()
            a = self._device_step("plain", pack_pod_batch(
                batch, self._spec_plain, *empty))
            # sync-point: warmup barrier — block until the round trip lands
            jax.device_get(a)
            self._warm_preempt()

    def device_census(self, variants: tuple = ("full", "plain")) -> dict:
        """Static cost census of the compiled step variants: lower each
        one with the backend's own host tensors (shape-exact; nothing
        executes on the device) and walk the optimized HLO
        (component_base/profiling).  Works identically for the remote
        backend — the step fns are built client-side and the worker
        compiles the same program.  Costs a fresh AOT compile per
        variant, so callers reach this only through the profiling:
        stanza (Scheduler.run_device_census)."""
        from ..component_base import profiling
        with self._lock:
            t = self.tensors
            cd_sg, cd_asg = t.domain_base_counts()
            state = {"used": t.used, "used_nz": t.used_nz,
                     "npods": t.npods, "port_mask": t.port_mask,
                     "cd_sg": cd_sg, "cd_asg": cd_asg,
                     "gen": np.int32(0)}
            static_core = {k: getattr(t, k) for k in STATIC_CORE}
            batch = self.encoder.encode([])
            empty = (np.empty(0, np.int32),
                     np.empty((0, self._f_patch), np.float32))
            plans = []
            if "full" in variants:
                self._ensure_full()
                sel = {k: getattr(t, k) for k in STATIC_SEL}
                buf = pack_pod_batch(
                    slice_pod_batch(batch, 0, 0, self.full_cap),
                    self._spec_full, *empty)
                plans.append(("full", self._fn_full,
                              {**static_core, **sel}, buf))
            if "plain" in variants:
                fn = self._ensure_plain()
                buf = pack_pod_batch(batch, self._spec_plain, *empty)
                plans.append(("plain", fn, static_core, buf))
        # the AOT lowering/compile runs OUTSIDE the backend lock: a
        # multi-second census must not stall a concurrent dispatch
        return {name: profiling.census_lowered(fn.lower(state, static, buf))
                for name, fn, static, buf in plans}

    def _warm_preempt(self) -> None:
        """Compile the preemption dry-run kernel (and make the victim
        tensors resident) with an all-inactive pod chunk, specialized
        to the common single-priority-group wave shape.  Like the
        dispatch variants above, the cold compile otherwise lands
        inside the first preemption wave and is charged to its pods."""
        self._ensure_vict()
        c = self.caps
        P = self.PREEMPT_P_CAP
        self._preempt_step({
            "req": np.zeros((P, c.r), np.float32),
            "prio": np.zeros(P, np.int32),
            "untol_hard": np.zeros((P, c.t_cap), np.float32),
            "group_idx": np.zeros(P, np.int32),
            "nom_used": np.zeros((1, c.n_cap, c.r), np.float32),
            "nom_np": np.zeros((1, c.n_cap), np.float32),
            "active": np.zeros(P, bool)})

    def _stage_buf(self, total: int) -> np.ndarray:
        """Hand out a host staging buffer of `total` f32 slots from the
        ping-pong ring (caller holds the lock).  The buffer is PINNED
        until the wave that packed into it resolves or is abandoned: an
        unresolved wave retains its buffer for a possible fenced re-run,
        so recycling it early would corrupt the replay.  The ring is
        bounded — under deep latency-mode pipelines overflow buffers are
        plain one-shot allocations that die with their wave."""
        for arr in self._stage_ring:
            if arr.size == total and id(arr) not in self._stage_pins:
                self._stage_pins.add(id(arr))
                return arr
        arr = np.empty(total, np.float32)
        if len(self._stage_ring) < 16:
            self._stage_ring.append(arr)
        self._stage_pins.add(id(arr))
        return arr

    def _device_step(self, variant: str, buf: np.ndarray):
        """Run one packed batch through the device and return the result
        vector handle (assignments + wave count).  THE remote-worker seam:
        everything above this call is host bookkeeping; everything below
        is device execution — RemoteTPUBatchBackend overrides exactly the
        device-touching methods (_device_step/_upload_static/
        _full_refresh) to ship the same byte payloads to a worker process
        (the north star's scheduler<->JAX-worker shim boundary)."""
        import jax.numpy as jnp
        if variant == "full":
            self._ensure_sel()
            fn = self._fn_full
            static = {**self._static_node, **self._static_sel}
        elif variant == "full_small":
            self._ensure_sel()
            fn = self._ensure_full_small()
            static = {**self._static_node, **self._static_sel}
        else:
            fn = self._fn_plain
            static = self._static_node
        self._state, rd = fn(self._state, static, jnp.asarray(buf))
        self._gen += 1  # the kernel computes the identical state.gen + 1
        # start the result's D2H transfer NOW: on a tunneled chip a
        # blocking pull costs ~90ms of fixed round-trip latency per call
        # (measured: the assignments vector is ~1KB — it is all latency),
        # while an async copy overlaps the flight with host work and the
        # later resolve() completes in single-digit ms
        copy_async = getattr(rd, "copy_to_host_async", None)
        if copy_async is not None:
            copy_async()
        return rd

    def _ensure_sel(self) -> None:
        """Upload the selector-side static arrays if missing/stale (lazy:
        only the full kernel variant reads them)."""
        if self._static_sel is None or self._sel_stale:
            import jax.numpy as jnp
            t = self.tensors
            self._static_sel = {k: jnp.asarray(getattr(t, k))
                                for k in STATIC_SEL}
            self._sel_stale = False

    RETRY_ROUNDS_MAX = 32  # defensive bound; rounds stop at no-progress

    def _ensure_full(self):
        if self._fn_full is None:
            self._fn_full, self._spec_full = build_packed_assign_fn(
                self.caps, self.full_cap, self._k_cap, self._weights,
                max_waves=self.FULL_MAIN_WAVES or None)
        return self._fn_full

    def _retry_cap(self) -> int:
        # Small: straggler waves serialize hard when every leftover
        # claims the current-min spread domain (the level floor is held
        # by domains with no candidates, so ~maxSkew pods admit per
        # wave) — P=128 makes such a wave ~16x cheaper than P=512, and
        # chunk-to-chunk state chaining re-balances claims between
        # chunks anyway.
        return min(128, self.full_cap)

    def _ensure_full_small(self):
        """The straggler retry kernel: same constraint wave body at a
        small P with the EXHAUSTIVE wave budget, so capped-main leftovers
        drain at ~(P_small/P)^2 of a main wave's cost and the
        no-progress fixpoint guarantee is preserved."""
        if self._fn_full_small is None:
            self._fn_full_small, self._spec_full_small = \
                build_packed_assign_fn(
                    self.caps, self._retry_cap(), self._k_cap,
                    self._weights)
        return self._fn_full_small

    def _ensure_plain(self):
        if self._fn_plain is None:
            self._fn_plain, self._spec_plain = build_packed_assign_fn(
                self.caps, self.batch_size, self._k_cap, self._weights,
                features=PLAIN_FEATURES)
        return self._fn_plain

    S_PATCH_MAX = 8192  # above this many dirty rows a full upload is cheaper

    def _upload_static(self) -> None:
        """Sync the device's static node arrays with the host tensors.

        Full upload only when forced (first upload, vocab column
        backfills, or very many dirty rows); otherwise a row-wise scatter
        on the RESIDENT static arrays (donated).  The selector-side
        arrays (STATIC_SEL) update lazily: when they are not resident
        they are only marked stale — at 100k nodes they are ~140 MB that
        the plain variant never reads."""
        import jax.numpy as jnp
        t = self.tensors
        rows = t.static_dirty_rows
        # patch only when clearly cheaper than re-shipping the arrays: a
        # registration flood (rows ~ n_cap) wants the single full upload,
        # steady-state drift (a handful of rows) wants the tiny scatter
        full = (self._static_node is None or t.static_full
                or len(rows) > self.S_PATCH_MAX
                or len(rows) * 8 > self.caps.n_cap)
        if full:
            self._static_node = {k: jnp.asarray(getattr(t, k))
                                 for k in STATIC_CORE}
            if self._static_sel is not None:
                self._static_sel = {k: jnp.asarray(getattr(t, k))
                                    for k in STATIC_SEL}
                self._sel_stale = False
            else:
                self._sel_stale = True
        elif rows:
            k = 256  # pad floor bounds the number of distinct jit shapes
            while k < len(rows):
                k *= 2
            rows_a = np.full(k, -1, np.int32)
            rows_a[:len(rows)] = sorted(rows)
            safe = np.where(rows_a >= 0, rows_a, 0)
            jrows = jnp.asarray(rows_a)
            self._static_node = _apply_static_patch(
                self._static_node, jrows,
                jnp.asarray(t.alloc[safe]), jnp.asarray(t.maxpods[safe]),
                jnp.asarray(t.valid[safe]),
                jnp.asarray(t.taint_mask[safe]))
            if self._static_sel is not None:
                self._static_sel = _apply_sel_patch(
                    self._static_sel, jrows,
                    jnp.asarray(t.label_mask[safe]),
                    jnp.asarray(t.key_mask[safe]),
                    jnp.asarray(t.dom_sg[:, safe]),
                    jnp.asarray(t.dom_asg[:, safe]))
            else:
                self._sel_stale = True
            self.stats["static_patched_rows"] = self.stats.get(
                "static_patched_rows", 0) + len(rows)
        t.static_dirty_rows = set()
        t.static_full = False
        self._static_version = t.static_version

    def _ensure_vict(self) -> None:
        """Refresh + upload the victim tensors (STATIC_VICT channel).
        Full upload when forced (first upload, PDB flip, many rows);
        otherwise a row-wise scatter on the resident arrays — the same
        economics as _upload_static, on the preemption-wave cadence."""
        import jax.numpy as jnp
        t = self.tensors
        rows = t.refresh_victims()
        if (self._static_vict is not None and not t.vict_full
                and self._vict_version == t.vict_version):
            return
        full = (self._static_vict is None or t.vict_full or rows is None
                or len(rows) > self.S_PATCH_MAX
                or len(rows) * 8 > self.caps.n_cap)
        if full:
            self._static_vict = {k: jnp.asarray(getattr(t, k))
                                 for k in STATIC_VICT}
        else:
            k = 256
            while k < len(rows):
                k *= 2
            rows_a = np.full(k, -1, np.int32)
            rows_a[:len(rows)] = rows
            safe = np.where(rows_a >= 0, rows_a, 0)
            self._static_vict = _apply_vict_patch(
                self._static_vict, jnp.asarray(rows_a),
                jnp.asarray(t.vict_prio[safe]),
                jnp.asarray(t.vict_req[safe]),
                jnp.asarray(t.vict_pdb[safe]),
                jnp.asarray(t.vict_over[safe]))
            self.stats["vict_patched_rows"] = self.stats.get(
                "vict_patched_rows", 0) + len(rows)
        t.vict_full = False
        self._vict_version = t.vict_version

    def _full_refresh(self, cd_sg: np.ndarray, cd_asg: np.ndarray) -> None:
        import jax.numpy as jnp
        t = self.tensors
        self._state = {
            "used": jnp.asarray(t.used), "used_nz": jnp.asarray(t.used_nz),
            "npods": jnp.asarray(t.npods),
            "port_mask": jnp.asarray(t.port_mask),
            "cd_sg": jnp.asarray(cd_sg), "cd_asg": jnp.asarray(cd_asg),
            "gen": jnp.asarray(self._gen, jnp.int32),
        }
        self._mirror_from_tensors(cd_sg, cd_asg)
        self.stats["full_refresh"] += 1

    # -- batch telemetry (observability PR) ------------------------------

    def _tally_escape_pairs(self, pairs: dict) -> None:
        with self._esc_lock:
            pend = self._escape_pending
            for key, cnt in pairs.items():
                pend[key] = pend.get(key, 0) + cnt

    def _tally_batch_escapes(self, batch: PodBatch, n: int,
                             assignments=None) -> None:
        """Accumulate this batch's per-(plugin, reason) escape counts.
        Encoder escapes carry their reason from flatten.escape_reasons;
        collided-bucket no-fit re-proofs (decode_results nofit_escapes)
        are attributed to the encoder's shared-bucket transport."""
        pend: dict = {}
        esc = set(batch.escape)
        for i in esc:
            if i < n:
                key = (batch.escape_reasons.get(i)
                       or ("BatchEncoder", "unencodable"))
                pend[key] = pend.get(key, 0) + 1
        for i in set(batch.nofit_oracle):
            if (i < n and i not in esc and i < self.batch_size
                    and (assignments is None or assignments[i] < 0)):
                # nominated-node re-proofs carry their own reason
                # (flatten records it at encode); bare nofit_oracle
                # entries are the collided-bucket transport
                key = (batch.escape_reasons.get(i)
                       or ("BatchEncoder", "bucket_collision"))
                pend[key] = pend.get(key, 0) + 1
        if pend:
            self._tally_escape_pairs(pend)

    def drain_escape_reasons(self) -> dict:
        """Pop the pending {(plugin, reason): count} escape tallies; the
        scheduler incs scheduler_tpu_escape_total by these deltas."""
        with self._esc_lock:
            out, self._escape_pending = self._escape_pending, {}
        return out

    def drain_batch_telemetry(self) -> list[dict]:
        """Pop the pending per-batch telemetry dicts ({feasible_nodes,
        mask_density, waves, pods}) for the scheduler's gauge/histogram
        updates."""
        with self._esc_lock:
            out, self._telemetry_pending = self._telemetry_pending, []
        return out

    # -- stuck-wave watchdog support -------------------------------------

    def abandon_wave(self) -> None:
        """Watchdog cancel (scheduler._resolve_with_deadline): a cancelled
        wave's pods were requeued and never assumed, so its in-flight
        device accounting must not chain into the next dispatch.  Drop
        the pipeline bookkeeping and force a full tensor refresh from the
        authoritative cache view on the next batch.

        Lock acquisition is best-effort with a short timeout: the stuck
        resolve may be blocked inside a device pull while HOLDING the
        lock, and the watchdog must not hang the scheduling loop behind
        it.  The unlocked fallback is safe for this state: replacing
        _state/_last_epoch and clearing _unresolved only widens the next
        dispatch's refresh; resolve() tolerates its holder vanishing
        (the remove is try/except)."""
        got = self._lock.acquire(timeout=0.1)
        try:
            self._unresolved.clear()
            self._state = None
            self._last_epoch = None
            # the dropped chain takes any pending fence and retained
            # staging buffers with it: orphan resolves are ignored, and
            # the next dispatch full-refreshes from the cache view anyway
            self._fence_pending = 0
            self._stage_pins.clear()
            self.stats["abandoned_waves"] = (
                self.stats.get("abandoned_waves", 0) + 1)
        finally:
            if got:
                self._lock.release()

    def _mask_densities(self, batch: PodBatch, n: int) -> dict[str, float]:
        """Per-plugin-family constraint-mask density: the fraction of the
        batch's live slots carrying an active mask for that family.  The
        device kernel fuses filter+score, so these host-side numbers are
        what 'how selective was this batch' means per plugin."""
        nl = max(1, min(n, self.batch_size))

        def rows(a):
            if a is None:
                return None
            return (a[:nl].reshape(nl, -1) != 0).any(axis=1)

        def dens(*arrays):
            acc = None
            for a in arrays:
                r = rows(a)
                if r is not None:
                    acc = r if acc is None else (acc | r)
            return float(acc.sum()) / nl if acc is not None else 0.0

        def kind_dens(*kinds):
            if batch.c_kind is None:
                return 0.0
            ck = batch.c_kind[:nl]
            acc = np.zeros(nl, bool)
            for k in kinds:
                acc |= (ck == k).any(axis=1)
            return float(acc.sum()) / nl

        out = {
            "NodeAffinity": dens(batch.sel_any_active, batch.key_any_active,
                                 batch.sel_forb, batch.key_forb),
            "InterPodAffinity": kind_dens(C_AFFINITY, C_ANTI_AFFINITY,
                                          C_PREF_AFFINITY),
            "PodTopologySpread": kind_dens(C_SPREAD_HARD, C_SPREAD_SCORE),
            "TaintToleration": dens(batch.untol_hard, batch.untol_prefer),
            "NodePorts": dens(batch.ports),
        }
        if batch.node_row is not None:
            out["NodeName"] = float(
                (batch.node_row[:nl] >= 0).sum()) / nl
        return out

    def _score_densities(self, batch: PodBatch, n: int) -> dict[str, float]:
        """Score-phase twin of _mask_densities: the soft (weight-carrying)
        terms the kernel's score accumulation reads."""
        nl = max(1, min(n, self.batch_size))
        out = {"preferred_affinity": 0.0, "prefer_no_schedule": 0.0}
        if batch.c_weight is not None:
            out["preferred_affinity"] = float(
                (batch.c_weight[:nl] != 0).any(axis=1).sum()) / nl
        if batch.untol_prefer is not None:
            out["prefer_no_schedule"] = float(
                (batch.untol_prefer[:nl] != 0).any(axis=1).sum()) / nl
        return out

    # -- BatchBackend ----------------------------------------------------

    def dispatch(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot):
        """Host prep + async device dispatch.  Returns resolve() -> results,
        or the FLUSH_FIRST sentinel.

        The device call is dispatched but NOT waited on; the caller can
        overlap host work with the device round trip and call resolve() when
        it needs the answers.  Pipelining over an in-flight batch is allowed
        when this batch is clean (no patches, no refresh, no static change —
        the device chains its own resident accounting via the donated state)
        OR when it needs only dynamic row patches and no fence is already
        pending: that wave dispatches FENCED — gen-bumped so its first
        device run provably goes stale and the authoritative answer comes
        from the mirror-restored re-run at its resolve.  A full refresh or
        a static change returns FLUSH_FIRST instead: the caller must resolve
        the in-flight batch AND finish its assume tail (so the authoritative
        tensors catch up with the mirror), then call dispatch again — the
        dirty rows from this attempt are carried over so no external change
        is lost."""
        parent = _trace_parent()
        with self._lock:
            if self._warm_pending:
                self._warm_sweep(snapshot)
            # epoch fast path: if every cache change since the last sync
            # came from this backend's own batches (bulk assume + confirm),
            # the mirror replay already holds the truth — skip the O(nodes)
            # re-encode and the diff outright.  A mutation racing the epoch
            # read is caught by the NEXT dispatch (epoch monotonically
            # advances; _last_epoch only records the pre-sync value).
            epoch_fn = getattr(snapshot, "epoch", None)
            epoch = epoch_fn() if epoch_fn is not None else None
            skip_sync = (epoch is not None and self._state is not None
                         and epoch == self._last_epoch
                         and not self._carry_dirty
                         and not self.FORCE_REFLATTEN)
            f_sp = (parent.tracer.start_span("snapshot.flatten",
                                             parent=parent)
                    if parent is not None else None)
            try:
                if skip_sync:
                    dirty = set()
                else:
                    t_sync = time.monotonic()
                    dirty = set(self.tensors.update_from_snapshot_tracked(
                        snapshot))
                    dirty |= self._carry_dirty
                    self._last_epoch = epoch
                    t_sync_end = time.monotonic()
                    self.stats["flatten_seconds"] += t_sync_end - t_sync
                    if default_timeline.enabled:
                        # wave timeline: host tensor-maintenance leg
                        default_timeline.record("patch", t_sync, t_sync_end)
                batch = self.encoder.encode(list(pod_infos))
            except VocabFullError as e:
                logger.warning("tensorization overflow (%s); batch -> oracle path", e)
                # the tracked update may have partially applied: drop the
                # mirror-diff fast path and force a full dynamic refresh on
                # the next successful dispatch
                self._state = None
                self._carry_dirty = set()
                reason = ("constraint_capacity" if "constraint" in str(e)
                          else "vocab_full")
                self._tally_escape_pairs(
                    {("BatchEncoder", reason): len(pod_infos)})
                if f_sp is not None:
                    f_sp.add_event("vocab_overflow", error=str(e))
                    f_sp.end()
                results = [(None, Status(SKIP, str(e)))] * len(pod_infos)
                return lambda: results
            if f_sp is not None:
                f_sp.set_attribute("pods", len(pod_infos))
                f_sp.set_attribute("escaped", len(batch.escape))
                f_sp.set_attribute("dirty_rows", len(dirty))
                f_sp.set_attribute("sync_skipped", bool(skip_sync))
                f_sp.end()

            n_live = len(pod_infos)
            if n_live and not batch.p_valid[:min(n_live,
                                                self.batch_size)].any():
                # every pod escaped at encode (p_valid False for a live
                # slot <=> escape): nothing for the device — don't burn a
                # tunnel round trip on an all-invalid batch.  Preemption
                # retry waves land here: every nominated pod escapes to
                # the per-pod oracle by design, and each backoff trickle
                # used to cost a full device RT for zero placements.
                # The synced dirty rows carry so the next REAL dispatch
                # diffs them.
                self._carry_dirty = dirty
                self.stats["all_escape_skips"] = self.stats.get(
                    "all_escape_skips", 0) + 1
                self._tally_batch_escapes(batch, n_live)
                if parent is not None:
                    parent.add_event("all_escape_skip", pods=n_live)
                results = [
                    (None, Status(SKIP, "escape to per-pod path"))
                    ] * n_live

                def resolve_escaped():
                    # stats record OUTSIDE the lock (it re-acquires it)
                    record_batch_stats(self.stats, self._lock, results,
                                       n_live)
                    return results

                return resolve_escaped

            # per-plugin batch telemetry: filter-mask and score-term
            # densities + the device feasibility domain, recorded as span
            # attributes here and queued (at resolve, with the wave count)
            # for the scheduler's tpu_mask_density / tpu_feasible_nodes
            # metrics.  The device kernel fuses filter/score/solve in one
            # launch, so these two spans time the host-side telemetry
            # pass over the per-phase inputs — the solve span below is
            # the device-time phase.
            fm_sp = (parent.tracer.start_span("plugin.filter_masks",
                                              parent=parent)
                     if parent is not None else None)
            telem = {
                "pods": n_live,
                "feasible_nodes": int(self.tensors.valid.sum()),
                "mask_density": self._mask_densities(batch, n_live),
            }
            if fm_sp is not None:
                fm_sp.set_attribute("feasible_nodes",
                                    telem["feasible_nodes"])
                for plugin, d in telem["mask_density"].items():
                    fm_sp.set_attribute(plugin, round(d, 4))
                fm_sp.end()
            sc_sp = (parent.tracer.start_span("plugin.score", parent=parent)
                     if parent is not None else None)
            if sc_sp is not None:
                for term, d in self._score_densities(batch, n_live).items():
                    sc_sp.set_attribute(term, round(d, 4))
                sc_sp.end()

            inflight = bool(self._unresolved)
            # deterministic compaction point: compact() feeds reclaimed
            # slots to the free list, and free-list order decides which
            # row the next node add occupies — visible in device argmax
            # tie-breaks.  Anchoring reclamation to the wave boundary
            # (draining the pipeline first) keeps depth-1 and depth-2
            # runs bit-identical; event-time compaction fired only when
            # the pipeline happened to be idle, which depends on depth.
            if (self.tensors.tombstone_count() * self.COMPACT_TOMBSTONE_DIV
                    >= self.caps.n_cap):
                if inflight:
                    self._carry_dirty = dirty
                    self.stats["flush_first"] += 1
                    return FLUSH_FIRST
                if self.tensors.compact():
                    self.stats["compactions"] = self.stats.get(
                        "compactions", 0) + 1
            static_changed = self._static_version != self.tensors.static_version
            if skip_sync and not static_changed:
                patches = (np.empty(0, np.int32),
                           np.empty((0, self._spec.f_patch), np.float32))
                needs_refresh = needs_patch = False
            else:
                cd_sg, cd_asg = self.tensors.domain_base_counts()
                patches = None
                if self._state is not None:
                    if (np.array_equal(cd_sg, self._mirror["cd_sg"])
                            and np.array_equal(cd_asg, self._mirror["cd_asg"])):
                        patches = self._diff_patches(sorted(dirty))
                needs_refresh = self._state is None or patches is None
                needs_patch = patches is not None and len(patches[0]) > 0
            # pipeline admission: a full re-encode can never overlap an
            # in-flight wave (the mirror it would rebuild is mid-replay),
            # and only ONE fenced wave may ride the pipeline at a time.
            # A dynamic row patch while clean becomes a FENCED dispatch
            # instead of a flush: the patch lands in the mirror now, gen
            # is bumped so this wave's first device run provably trips
            # the fence, and the authoritative result comes from the
            # mirror-restored re-run at resolve — bit-identical to
            # flush-then-redispatch, minus the pipeline stall for every
            # OTHER wave.  STATIC changes never fence: _upload_static
            # swaps the resident static arrays, and a predecessor's
            # fenced/stale RE-RUN at resolve (unlike its first run, which
            # captured the old refs at the fn call) would read the new
            # arrays — resolving a past wave against future node state.
            will_fence = False
            if inflight and (needs_refresh or static_changed):
                self._carry_dirty = dirty
                self.stats["flush_first"] += 1
                return FLUSH_FIRST
            if inflight and needs_patch:
                if self._fence_pending:
                    self._carry_dirty = dirty
                    self.stats["flush_first"] += 1
                    return FLUSH_FIRST
                will_fence = True

            if static_changed:
                # pipeline is empty here (static change over an in-flight
                # wave flushed above), so no retained wave can re-run
                # against these swapped arrays
                self._upload_static()
            if needs_refresh:
                self._full_refresh(cd_sg, cd_asg)
                patches = (np.empty(0, np.int32),
                           np.empty((0, self._spec.f_patch), np.float32))
            elif needs_patch:
                self._sync_mirror_rows(patches[0])
            if will_fence:
                # the patch VALUES travel via the mirror rows just
                # synced, never via the retained upload buffer: the
                # in-flight predecessor's replay will ADD its commits
                # onto those mirror rows before this wave's re-run, and
                # a buffer-borne patch would SET them back to
                # pre-predecessor values at the re-run, wiping its
                # commits.
                self.stats["patched_rows"] += len(patches[0])
                patches = (np.empty(0, np.int32),
                           np.empty((0, self._spec.f_patch), np.float32))
                self._gen += 1  # guarantee this wave's fence trips
                self._fence_pending += 1
                self.stats["fenced_waves"] = self.stats.get(
                    "fenced_waves", 0) + 1
            # patched-vs-reflattened wave accounting: a wave that kept the
            # resident state (row patches or nothing) vs one that had to
            # rebuild it (the recovery path, not steady state)
            self.stats["waves_reflattened" if needs_refresh
                       else "waves_patched"] += 1
            self._carry_dirty = set()
            self.stats["patched_rows"] += len(patches[0])
            self.stats["epoch_skips"] = self.stats.get("epoch_skips", 0) + (
                1 if skip_sync else 0)

            import jax.numpy as jnp
            n = len(pod_infos)
            # plugin.assign_solve spans launch -> resolve (device time,
            # ended by resolve() below); tpu.h2d covers pack + upload +
            # kernel enqueue inside it
            solve_sp = (parent.tracer.start_span("plugin.assign_solve",
                                                 parent=parent)
                        if parent is not None else None)
            h2d_sp = (parent.tracer.start_span("tpu.h2d", parent=solve_sp)
                      if solve_sp is not None else None)
            t_h2d = time.monotonic()
            if self._needs_full(batch) and n > self.full_cap:
                # oversized constraint batch: chunk through the capped
                # full kernel; resident state chains chunk to chunk, so
                # intra-batch accounting stays exact.  Patches ride the
                # first chunk only.
                self._ensure_full()
                # chunk tuples retain the packed buffer + variant + the
                # expected device generation, so a fenced resolve can
                # re-run the identical chunks from restored state
                chunks = []
                p = patches
                for lo in range(0, n, self.full_cap):
                    hi = min(lo + self.full_cap, n)
                    cbuf = pack_pod_batch(
                        slice_pod_batch(batch, lo, hi, self.full_cap),
                        self._spec_full, p[0], p[1],
                        out=self._stage_buf(self._spec_full.total))
                    p = (np.empty(0, np.int32),
                         np.empty((0, self._f_patch), np.float32))
                    chunks.append((self._device_step("full", cbuf),
                                   # donate-ok: cbuf is the host staging
                                   # copy; a fenced re-run re-uploads it
                                   # (the donated transport is the fresh
                                   # jnp conversion in _device_step)
                                   lo, hi, "full", cbuf, self._gen))
            elif self._needs_full(batch):
                self._ensure_full()
                if self.full_cap == self.batch_size:
                    cb, hi = batch, self.batch_size
                else:
                    cb, hi = slice_pod_batch(batch, 0, n, self.full_cap), n
                cbuf = pack_pod_batch(cb, self._spec_full, patches[0],
                                      patches[1],
                                      out=self._stage_buf(
                                          self._spec_full.total))
                chunks = [(self._device_step("full", cbuf), 0, hi,
                           # donate-ok: host staging copy retained for
                           # fenced re-runs; _device_step re-converts
                           "full", cbuf, self._gen)]
            else:
                self.stats["plain"] = self.stats.get("plain", 0) + 1
                self._ensure_plain()
                # plain wire format: ~6x less upload than the full layout
                buf = pack_pod_batch(batch, self._spec_plain, patches[0],
                                     patches[1],
                                     out=self._stage_buf(
                                         self._spec_plain.total))
                chunks = [(self._device_step("plain", buf), 0,
                           # donate-ok: host staging copy retained for
                           # fenced re-runs; _device_step re-converts
                           self.batch_size, "plain", buf, self._gen)]
            if h2d_sp is not None:
                h2d_sp.set_attribute("chunks", len(chunks))
                h2d_sp.set_attribute(
                    "variant", "full" if self._needs_full(batch)
                    else "plain")
                h2d_sp.set_attribute("patched_rows", int(len(patches[0])))
                h2d_sp.end()
            # wave timeline: pack + upload + kernel enqueue (for the
            # remote seam this leg carries the wire round trip, which is
            # why h2d counts as a device stage in the idle-share union)
            t_launch = time.monotonic()
            if default_timeline.enabled:
                default_timeline.record("h2d", t_h2d, t_launch)
            self.stats["batches"] += 1
            holder = object()
            self._unresolved.append(holder)
            # row->name view AT DISPATCH: a later dispatch may recycle
            # rows (node deleted, slot reused), so resolve() must not read
            # the live tensors.  Names, not NodeInfos: the zero-copy cache
            # view shares live NodeInfos and a churn drain nulls .node in
            # place mid-wave, which would decode as nodeName ""
            row_names = list(self.tensors.row_names)

        was_full = self._needs_full(batch)

        def resolve() -> list[tuple[str | None, Status | None]]:
            nonlocal will_fence
            import jax
            batch_waves = 0
            try:
                with self._lock:
                    assignments = np.full(self.batch_size, -1, np.int64)
                    d2h_sp = (solve_sp.tracer.start_span("tpu.d2h",
                                                         parent=solve_sp)
                              if solve_sp is not None else None)
                    raw = []
                    # a fenced wave is stale BY CONSTRUCTION (the dispatch
                    # bumped gen past what its first device run can echo):
                    # start from the fence flag so the replay below is
                    # unconditional for it
                    stale = bool(will_fence)
                    t_d2h0 = time.monotonic()
                    for rd, _lo, _hi, _variant, _cbuf, expect in chunks:
                        # sync-point: wave resolve — THE pipeline's d2h pull
                        result = jax.device_get(rd)
                        stale = stale or int(result[-1]) != expect
                        raw.append(result)
                    if stale:
                        # generation fence tripped: the device state this
                        # wave chained on is not the lineage the host
                        # committed (mid-pipeline fence / lost patch /
                        # restored worker / chaos).  Recovery: rebuild the
                        # state from the replay mirror and re-run the
                        # retained chunk buffers in order — identical inputs
                        # against the authoritative state, so the accepted
                        # assignments are exactly what a healthy wave would
                        # have produced.  For a fenced wave this IS the
                        # steady-state pipeline discipline, not an anomaly —
                        # that wave simply degrades to depth-1.
                        if will_fence:
                            self.stats["fence_replays"] = self.stats.get(
                                "fence_replays", 0) + 1
                        else:
                            logger.warning(
                                "generation-stale wave (device gen mismatch);"
                                " re-running %d chunk(s) from restored state",
                                len(chunks))
                            self.stats["gen_stale_waves"] = self.stats.get(
                                "gen_stale_waves", 0) + 1
                        self._restore_state_from_mirror()
                        raw = []
                        for _rd, _lo, _hi, variant, cbuf, _expect in chunks:
                            # sync-point: recovery re-run resolves in line
                            raw.append(jax.device_get(
                                self._device_step(variant, cbuf)))
                    if default_timeline.enabled:
                        # wave timeline: device-step spans launch -> results
                        # landed (recovery re-runs included); d2h is the
                        # blocking pull inside it — nested on purpose, the
                        # idle-share union collapses the overlap
                        t_dev_end = time.monotonic()
                        default_timeline.record("device-step", t_launch,
                                                t_dev_end)
                        default_timeline.record("d2h", t_d2h0, t_dev_end)
                    for result, (_rd, lo, hi, *_rest) in zip(raw, chunks):
                        assignments[lo:hi] = result[:-2][:hi - lo]
                        batch_waves += int(result[-2])
                    if d2h_sp is not None:
                        d2h_sp.set_attribute("chunks", len(chunks))
                        d2h_sp.end()
                    self.stats["waves"] += batch_waves
                    self._replay(batch, assignments)
                    if was_full and self.FULL_MAIN_WAVES:
                        self._retry_stragglers(batch, assignments, n)
                    try:
                        self._unresolved.remove(holder)
                    except ValueError:  # pragma: no cover - double resolve
                        pass
            finally:
                # pins and the fence slot free even when the resolve
                # fails (seam raise): a fence that never cleared would
                # wedge every future patch dispatch behind FLUSH_FIRST
                for _rd, _lo, _hi, _variant, cbuf, _expect in chunks:
                    self._stage_pins.discard(id(cbuf))
                if will_fence:
                    self._fence_pending = max(0, self._fence_pending - 1)
                    will_fence = False
            if solve_sp is not None:
                solve_sp.set_attribute("waves", batch_waves)
                solve_sp.set_attribute("pods", n)
                solve_sp.end()
            out = decode_results(assignments, n, self.batch_size,
                                 set(batch.escape), row_names,
                                 "no feasible node (TPU batch filter)",
                                 nofit_escapes=set(batch.nofit_oracle))
            self._tally_batch_escapes(batch, n, assignments)
            telem["waves"] = batch_waves
            with self._esc_lock:
                self._telemetry_pending.append(telem)
                del self._telemetry_pending[:-64]  # bounded drain queue
            record_batch_stats(self.stats, self._lock, out, n)
            return out

        return resolve

    def _retry_stragglers(self, batch, assignments: np.ndarray,
                          n: int) -> None:
        """Drain a capped main run's leftovers through the small retry
        kernel (caller holds the lock; mutates `assignments` in place).

        The main constraint kernel stops after FULL_MAIN_WAVES waves —
        by then ~98% of a batch is placed and each further full-[P,N]
        wave admits a handful of stragglers (claims that landed in an
        over-level spread domain re-claim toward the min domain next
        wave).  Re-offering the leftovers at a small P costs
        ~(P_small/P)^2 per wave and runs the EXHAUSTIVE wave budget, so
        the overall fixpoint (retry until no progress) matches the
        uncapped kernel's placements-or-stuck guarantee.  Retry steps
        chain the same resident device state as ordinary batches, and
        the mirror replay is purely additive, so commit order between an
        already-inflight next batch and these retries cannot diverge."""
        import jax

        from ..ops.flatten import gather_pod_batch
        self._ensure_full_small()  # spec needed below before the step
        skip = set(batch.escape)
        cap = self._retry_cap()
        empty = (np.empty(0, np.int32),
                 np.empty((0, self._f_patch), np.float32))
        for _round in range(self.RETRY_ROUNDS_MAX):
            left = [i for i in range(min(n, self.batch_size))
                    if assignments[i] < 0 and i not in skip]
            if not left:
                return
            one_chunk = len(left) <= cap
            placed_this_round = 0
            for lo in range(0, len(left), cap):
                idx = left[lo:lo + cap]
                rb = gather_pod_batch(batch, idx, cap)
                buf = pack_pod_batch(rb, self._spec_full_small, *empty)
                # sync-point: straggler retry resolves synchronously
                res = jax.device_get(self._device_step("full_small", buf))
                if int(res[-1]) != self._gen:
                    # generation fence: restore from the mirror (which
                    # already includes this batch's replays) and re-post
                    # the identical retry buffer
                    self.stats["gen_stale_waves"] = self.stats.get(
                        "gen_stale_waves", 0) + 1
                    self._restore_state_from_mirror()
                    # sync-point: recovery re-run resolves in line
                    res = jax.device_get(
                        # donate-ok: identical host retry buffer; the
                        # re-post re-converts and re-donates on device
                        self._device_step("full_small", buf))
                self.stats["waves"] += int(res[-2])
                sub = res[:-2]
                self._replay(rb, sub)
                for j, orig in enumerate(idx):
                    if sub[j] >= 0:
                        assignments[orig] = sub[j]
                        placed_this_round += 1
            self.stats["retries"] = self.stats.get("retries", 0) + 1
            if not placed_this_round or one_chunk:
                # a single chunk ran the EXHAUSTIVE wave budget over the
                # entire leftover set — that IS the fixpoint; another
                # round would re-dispatch the identical set to place
                # nothing (cross-round progress only exists when earlier
                # CHUNKS' placements unblock later ones)
                return

    def assign(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot
               ) -> list[tuple[str | None, Status | None]]:
        resolve = self.dispatch(pod_infos, snapshot)
        if resolve is FLUSH_FIRST:  # pragma: no cover - sync caller, no inflight
            raise RuntimeError("FLUSH_FIRST with no pipelined caller")
        return resolve()

    # -- batched preemption (PostFilter's device half) -------------------

    # Failed pods per device call (padded).  Each chunk is a full device
    # round trip (~120-300ms over the tunnel), so a 500-pod preemption
    # wave at cap 32 paid 16 serial RTs — measured as the second-largest
    # cost of the PreemptionBasic bench.  [P,N] working set at 256 and
    # n_cap 110336 is ~113MB — comfortably inside HBM.
    PREEMPT_P_CAP = 256
    PREEMPT_G_CAP = 8    # distinct priority groups per device call

    def _req_vec(self, res) -> np.ndarray:
        """Resource -> the flattener's [R] request layout (flatten.py
        encode(): core columns + scalar-vocab slots)."""
        from .flatten import CORE_R
        v = np.zeros(self.caps.r, np.float32)
        v[0] = res.milli_cpu
        v[1] = res.memory
        v[2] = res.ephemeral_storage
        for name, val in (res.scalar or {}).items():
            sid = self.tensors.scalar_vocab.lookup(name)
            if sid is not None:  # victims with unknown scalars reclaim
                v[CORE_R + sid] = val  # nothing the incoming pod can use
        return v

    def preempt_candidates(self, pod_infos: Sequence[PodInfo], k: int = 16
                           ) -> list[list[str] | None]:
        """For each FitError pod, the top-k candidate node names where
        removing every lower-priority pod would make it fit (device masked
        refilter, models/preempt.py), best first.  None = this pod needs a
        host full scan (priority-group overflow).  The host re-proves every
        candidate with the full filter set, so this is a candidate LIMIT
        (like the reference's DryRunPreemption sampling), never a wrong
        answer."""
        from ..models.preempt import preempt_candidates as dev_fn
        out: list[list[str] | None] = [None] * len(pod_infos)
        with self._lock:
            t = self.tensors
            prios = sorted({pi.priority for pi in pod_infos})
            groups = prios[:self.PREEMPT_G_CAP]
            gid_of = {p: g for g, p in enumerate(groups)}
            G, N, R = max(len(groups), 1), self.caps.n_cap, self.caps.r
            reclaim = np.zeros((G, N, R), np.float32)
            reclaim_np = np.zeros((G, N), np.float32)
            thresholds = np.asarray(groups or [0], np.float32)
            for row, ni in enumerate(t.node_infos):
                if ni is None or not t.valid[row]:
                    continue
                for vp in ni.pods:
                    gmask = vp.priority < thresholds  # groups this victim
                    if not gmask.any():               # is reclaimable for
                        continue
                    rv = self._req_vec(vp.request)
                    reclaim[gmask, row] += rv
                    reclaim_np[gmask, row] += 1.0
            row_names = list(t.row_names)
            alloc, used = t.alloc.copy(), t.used.copy()
            npods, maxpods = t.npods.copy(), t.maxpods.copy()
            valid = t.valid.copy()

        P = self.PREEMPT_P_CAP
        idxs = [i for i, pi in enumerate(pod_infos)
                if pi.priority in gid_of]
        for at in range(0, len(idxs), P):
            chunk = idxs[at:at + P]
            req = np.zeros((P, self.caps.r), np.float32)
            group_idx = np.zeros(P, np.int32)
            active = np.zeros(P, bool)
            for j, i in enumerate(chunk):
                req[j] = self._req_vec(pod_infos[i].request)
                group_idx[j] = gid_of[pod_infos[i].priority]
                active[j] = True
            rows, _count = dev_fn(alloc, used, npods, maxpods, valid,
                                  reclaim, reclaim_np, group_idx, req,
                                  active, k)
            for j, i in enumerate(chunk):
                names = [row_names[r] for r in rows[j] if r >= 0
                         and row_names[r] is not None]
                out[i] = names
        return out

    # -- full device DryRunPreemption (victim tensors) --------------------

    def victim_occupancy(self) -> float:
        """Fraction of victim slots in use across live rows (the
        tpu_victim_occupancy gauge feed)."""
        with self._lock:
            return self.tensors.victim_occupancy()

    def _preempt_step(self, body: dict):
        """Run one padded preemptor chunk through the dry-run kernel
        against the RESIDENT node state + victim tensors.  THE remote
        seam for preemption: RemoteTPUBatchBackend overrides exactly
        this method (ships `body`; the worker combines it with ITS
        resident static/dynamic/victim arrays)."""
        from ..models.preempt import preempt_dry_run
        t = self.tensors
        st = self._state
        used = st["used"] if st is not None else t.used
        npods = st["npods"] if st is not None else t.npods
        s = self._static_node
        v = self._static_vict
        return preempt_dry_run(
            s["alloc"], used, npods, s["maxpods"], s["valid"],
            s["taint_mask"], v["vict_prio"], v["vict_req"], v["vict_pdb"],
            v["vict_over"], body["nom_used"],
            body["nom_np"], body["group_idx"], body["req"], body["prio"],
            body["untol_hard"], body["active"])

    def preempt_batch(self, pod_infos: Sequence[PodInfo],
                      node_ord_of: dict, nominated=()):
        """Full device-side DryRunPreemption for a wave of plain,
        preemption-eligible failed pods: per pod, the reference-selected
        candidate node + exact victim set + PDB violation count — one
        device call per PREEMPT_P_CAP chunk instead of a host dry run per
        (pod, node) pair.

        The kernel returns the per-(pod,node) dry-run planes; selection
        happens HERE, in caller order, so one wave conflict-resolves
        without a device call per preemptor: unclaimed nodes keep their
        kernel keys untouched (a nomination only changes its own node's
        columns), and a node claimed by an earlier winner is either
        proved closed by a host feasibility bound or re-proved exactly
        by a host replay of the kernel's dry run with the claims folded
        in — bit-identical to running the sequential Evaluator pod by
        pod, nominating each winner before the next.

        node_ord_of: {node_name: snapshot.list() position}, the
        selection tie-break of last resort — it makes the pick
        bit-identical to the host Evaluator's `min()` over
        find_candidates order.  nominated: [(PodInfo, node_name)] pods
        currently holding nominations, folded into per-priority-group
        claimed capacity exactly as RunFilterPluginsWithNominatedPods
        does (only >=-priority nominations claim).

        Returns (results, escapes): results[i] = (node_name,
        [victim pod keys], num_pdb_violations) when the device selected
        a candidate, None when it proved there is none; escapes[i] = a
        reason string when pod i must fall back to the per-pod Evaluator
        (such i always have results[i] = None).  The exactness envelope
        is gated HERE: anything the kernel does not model escapes with a
        distinct reason instead of risking divergence."""
        n = len(pod_infos)
        out: list[tuple | None] = [None] * n
        escapes: dict[int, str] = {}
        # the serialization the wave's answers are exact against: live
        # indices in finalization order (commit or proved-None), i.e.
        # submission order minus escapes.  The parity suite replays the
        # sequential Evaluator oracle along it, folding each winner's
        # nomination before the next pod.
        self.last_wave_order: list[int] = []
        with self._lock:
            t = self.tensors
            live: list[int] = []
            if t.asgs or t.ns_anti_kv or t.ns_anti_complex:
                # resident anti-affinity groups can veto the preemptor in
                # the Evaluator's full filter set, which the kernel does
                # not model — the wave falls back wholesale
                for i in range(n):
                    escapes[i] = "constraint_groups"
            else:
                # PDB parity gate: the device coverage bit is computed
                # against ALL blocking PDBs, the Evaluator lists only the
                # preemptor's namespace — they agree exactly iff every
                # blocking PDB lives in that namespace
                bns = {ns for ns, _sel in t.pdb_blocking()}
                for i, pi in enumerate(pod_infos):
                    if bns and bns != {pi.key.split("/", 1)[0]}:
                        escapes[i] = "pdb_scope"
                    else:
                        live.append(i)
                prios = sorted({pod_infos[i].priority for i in live})
                if len(prios) > self.PREEMPT_G_CAP:
                    keep = set(prios[:self.PREEMPT_G_CAP])
                    for i in list(live):
                        if pod_infos[i].priority not in keep:
                            escapes[i] = "priority_groups"
                    live = [i for i in live if i not in escapes]
                    prios = prios[:self.PREEMPT_G_CAP]
            if live:
                from .flatten import untolerated_hard
                self._ensure_vict()
                if (self._static_node is None
                        or self._static_version != t.static_version):
                    self._upload_static()
                if self._state is None:
                    # a preemption wave before any dispatch (or on the
                    # remote seam, a worker holding no /refresh yet):
                    # make the dynamic state resident so both halves run
                    # the kernel against the same used/npods
                    cd_sg, cd_asg = t.domain_base_counts()
                    self._full_refresh(cd_sg, cd_asg)
                G, N, R = len(prios), self.caps.n_cap, self.caps.r
                gid_of = {p: g for g, p in enumerate(prios)}
                node_ord = np.full(N, 2**31 - 1, np.int32)
                for name, pos in node_ord_of.items():
                    row = t.row_of.get(name)
                    if row is not None and t.valid[row]:
                        node_ord[row] = pos
                row_names = list(t.row_names)
                vict_keys = [list(ks) if ks else [] for ks in t.vict_keys]
                # host copies for the post-claim feasibility bound; on
                # the in-process backend these are the arrays the kernel
                # reads, on the remote seam (_state is a sentinel, the
                # worker holds the arrays) the snapshot mirror is a
                # LOWER bound on device `used` — the bound then only
                # over-defers (extra round), never wrongly excludes
                st = self._state
                import jax
                alloc_h = np.asarray(t.alloc, np.float32)
                # sync-point: preempt planning pulls the resident device
                # aggregates (host mirror stands in on the remote seam)
                used_h, npods_h = jax.device_get(
                    (st["used"], st["npods"]) if isinstance(st, dict)
                    else (t.used, t.npods))
                maxpods_h = np.asarray(t.maxpods, np.float32)
                taint_h = np.asarray(t.taint_mask, np.float32)
                vict_prio_h = np.asarray(t.vict_prio, np.int32)
                vict_req_h = np.asarray(t.vict_req, np.float32)
                I32M = 2**31 - 1

                def _pick(mask, kviol, khigh, kpsum, knvic):
                    # pickOneNodeForPreemption: lexicographic min over
                    # (violations, highest victim priority, priority sum,
                    # victim count, snapshot order); node_ord is unique,
                    # so exactly one row survives — bit-identical to the
                    # host Evaluator's min() over find_candidates order
                    m = mask.copy()
                    for key, sent in ((kviol, np.inf), (khigh, I32M),
                                      (kpsum, np.inf), (knvic, np.inf),
                                      (node_ord, I32M)):
                        kmin = np.min(np.where(m, key, sent))
                        m &= key == kmin
                    return int(np.argmax(m))

                P = self.PREEMPT_P_CAP
                nom_used = np.zeros((G, N, R), np.float32)
                nom_np = np.zeros((G, N), np.float32)
                for npi, nnode in nominated:
                    row = t.row_of.get(nnode)
                    if row is None or not t.valid[row]:
                        continue
                    rv = self._req_vec(npi.request)
                    for g, p in enumerate(prios):
                        if npi.priority >= p:
                            nom_used[g, row] += rv
                            nom_np[g, row] += 1.0
                # THIS wave's winners: row -> [(claimant priority,
                # request vector)].  Claims are NOT re-sent to the
                # device — a nomination only changes its own node's
                # columns, so every unclaimed node's plane stays exact
                # and a claimed candidate is re-proved host-side by
                # _host_dry_run below.
                claimed_rows = np.zeros(N, bool)
                claims_by_row: dict[int, list] = {}
                vict_pdb_h = np.asarray(t.vict_pdb, np.float32)
                V = vict_prio_h.shape[1]
                # per-node reprieve order, identical to the kernel's
                slot = np.broadcast_to(np.arange(V), vict_prio_h.shape)
                ordv_h = np.lexsort(
                    (slot, -vict_prio_h, -vict_pdb_h), axis=-1)
                eps32 = np.float32(1e-6)

                def _host_dry_run(rc, prio_j, req_j, g_j):
                    """The kernel's dry run for ONE (pod, claimed node)
                    pair with the wave's claims on that node folded in
                    as >=-priority nominations — f32 end-to-end and the
                    same reprieve order, so the key it returns is what
                    the device WOULD have emitted had the claims been
                    resident.  Returns (key, victim_mask, violations)
                    or None when the node no longer yields a candidate."""
                    elig = vict_prio_h[rc] < prio_j
                    nelig = float(elig.sum())
                    if nelig == 0.0:
                        return None
                    freed = (elig[:, None].astype(np.float32)
                             * vict_req_h[rc]).sum(axis=0,
                                                   dtype=np.float32)
                    cl_used = np.zeros(R, np.float32)
                    cl_np = np.float32(0.0)
                    for cp, crv in claims_by_row[rc]:
                        if cp >= prio_j:
                            cl_used = cl_used + crv
                            cl_np += np.float32(1.0)
                    free = (alloc_h[rc] - (used_h[rc]
                                           + nom_used[g_j, rc] + cl_used)
                            + freed).astype(np.float32)
                    slack = np.float32(
                        maxpods_h[rc] - (npods_h[rc] + nom_np[g_j, rc]
                                         + cl_np - nelig))
                    if not (np.all(req_j <= free + eps32)
                            and slack >= 1.0):
                        return None
                    reprieved = np.zeros(V, bool)
                    for s in ordv_h[rc]:
                        if not elig[s]:
                            continue
                        ftry = free - vict_req_h[rc, s]
                        if (np.all(req_j <= ftry + eps32)
                                and (slack - 1.0) >= 1.0):
                            free = ftry
                            slack = np.float32(slack - 1.0)
                            reprieved[s] = True
                    vict = elig & ~reprieved
                    nv = float(vict.sum())
                    if nv == 0.0:
                        return None
                    viol = float((vict_pdb_h[rc] * vict).sum(
                        dtype=np.float32))
                    high = int(vict_prio_h[rc][vict].max())
                    ps = float((vict_prio_h[rc].astype(np.float32)
                                * vict).sum(dtype=np.float32))
                    return ((viol, high, ps, nv, int(node_ord[rc])),
                            vict, int(viol))

                for at in range(0, len(live), P):
                    chunk = live[at:at + P]
                    req = np.zeros((P, R), np.float32)
                    prio = np.zeros(P, np.int32)
                    untol = np.zeros((P, self.caps.t_cap), np.float32)
                    gidx = np.zeros(P, np.int32)
                    active = np.zeros(P, bool)
                    for j, i in enumerate(chunk):
                        pi = pod_infos[i]
                        req[j] = self._req_vec(pi.request)
                        prio[j] = min(max(pi.priority, -(2**31) + 2),
                                      2**31 - 2)
                        untol[j] = untolerated_hard(t, pi)
                        gidx[j] = gid_of[pi.priority]
                        active[j] = True
                    (cand, kviol, khigh, kpsum, knvic, victs,
                     overflow) = self._preempt_step({
                        "req": req, "prio": prio, "untol_hard": untol,
                        "group_idx": gidx, "nom_used": nom_used,
                        "nom_np": nom_np, "active": active})
                    for j, i in enumerate(chunk):
                        if overflow[j]:
                            # a reachable node carries a truncated
                            # victim set — the device answer may
                            # differ from the oracle's, so this pod
                            # re-proves host-side
                            escapes[i] = "victim_overflow"
                            continue
                        cj = np.asarray(cand[j], bool)
                        # best OPEN node straight from the kernel planes
                        best = None
                        open_m = cj & ~claimed_rows
                        if open_m.any():
                            r = _pick(open_m, kviol[j], khigh[j],
                                      kpsum[j], knvic[j])
                            best = ((float(kviol[j, r]),
                                     int(khigh[j, r]),
                                     float(kpsum[j, r]),
                                     float(knvic[j, r]),
                                     int(node_ord[r])),
                                    r, None, int(kviol[j, r]))
                        # A node claimed by an earlier winner may still
                        # be this pod's true minimum (capacity sharing —
                        # PreemptionDense stacks 4 preemptors per node):
                        # re-prove it host-side with the claims folded.
                        # The kernel's cand bit is claim-blind in BOTH
                        # directions here — a claimed node the pod fit
                        # WITHOUT victims (cand false, nvic 0) can need
                        # victims once the claim is charged — so every
                        # claimed row is re-gated from scratch: taints,
                        # then a cheap closure bound (every eligible
                        # victim evicted, claims charged; on saturating
                        # workloads it prunes every claimed row and no
                        # replay runs), then the exact replay.
                        for rc in np.nonzero(claimed_rows)[0]:
                            if float(untol[j] @ taint_h[rc]) != 0.0:
                                continue
                            elig = vict_prio_h[rc] < prio[j]
                            freed = (vict_req_h[rc][elig].sum(axis=0)
                                     if elig.any() else 0.0)
                            free_ub = (alloc_h[rc] - used_h[rc]
                                       - nom_used[gidx[j], rc] + freed)
                            slack_ub = (maxpods_h[rc]
                                        - (npods_h[rc]
                                           + nom_np[gidx[j], rc]
                                           - float(elig.sum())))
                            for cp, crv in claims_by_row[rc]:
                                if cp >= prio[j]:
                                    free_ub = free_ub - crv
                                    slack_ub -= 1.0
                            if not (np.all(req[j] <= free_ub + 1e-6)
                                    and slack_ub >= 1.0):
                                continue  # provably closed post-claim
                            res = _host_dry_run(rc, int(prio[j]),
                                                req[j], int(gidx[j]))
                            if res is None:
                                continue
                            ckey, cvict, cviol = res
                            if best is None or ckey < best[0]:
                                best = (ckey, int(rc), cvict, cviol)
                        if best is None:
                            # no open candidate and every claimed row
                            # proved closed or victimless post-claim:
                            # the sequential Evaluator would find no
                            # candidate either
                            self.last_wave_order.append(i)
                            continue
                        _key, r, cvict, viol_out = best
                        keys = vict_keys[r]
                        if cvict is None:
                            vs = [keys[s] for s in range(len(keys))
                                  if victs[j, r, s]]
                        else:
                            vs = [keys[s] for s in range(len(keys))
                                  if cvict[s]]
                        out[i] = (row_names[r], vs, viol_out)
                        self.last_wave_order.append(i)
                        claimed_rows[r] = True
                        claims_by_row.setdefault(r, []).append(
                            (int(prio[j]), req[j].copy()))
        if escapes:
            tl: dict = {}
            for reason in escapes.values():
                key = ("DefaultPreemption", reason)
                tl[key] = tl.get(key, 0) + 1
            self._tally_escape_pairs(tl)
        return out, escapes


def make_batch_backend(kind: str = "tpu", caps: Caps | None = None,
                       batch_size: int = 256,
                       weights: dict[str, float] | None = None,
                       k_cap: int = 1024, **kw):
    """Construct a BatchBackend by kind — the one seam the `backend:`
    config stanza (scheduler/config.BackendPolicy) and `bench.py
    --backend` both resolve through, so the selectable kinds stay in one
    place:

      tpu      single-chip resident kernel (TPUBatchBackend)
      sharded  mesh-partitioned shard_map path (parallel/backend.py);
               node tensors live sharded per NODE_PARTITION_RULES and
               the wave solver's conflict matrices resolve per pod slab
               via reduce-scatter
      null     host pipeline with the device step nulled (host-tail
               measurement)

    Remote seams (ops/remote.py) stay separate: they need a worker URL
    and a transport policy, not just a kind string.  The worker itself
    rejects kind != "tpu" — sharded is mesh-local by design (the device
    mesh lives in THIS process; tunneling per-shard buffers through the
    row-patch wire protocol would re-replicate them)."""
    if kind == "tpu":
        return TPUBatchBackend(caps, batch_size=batch_size,
                               weights=weights, k_cap=k_cap, **kw)
    if kind == "sharded":
        from ..parallel.backend import ShardedTPUBatchBackend
        return ShardedTPUBatchBackend(caps, batch_size=batch_size,
                                      weights=weights, k_cap=k_cap, **kw)
    if kind == "null":
        from .nullbackend import NullBatchBackend
        return NullBatchBackend(caps or Caps(), batch_size=batch_size)
    raise ValueError(f"unknown batch backend kind {kind!r} "
                     "(expected tpu, sharded or null)")
