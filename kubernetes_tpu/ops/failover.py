"""Circuit-breaker failover ladder for TPU batch backends.

Reference: pkg/scheduler/extender.go's `ignorable` extenders — the
in-tree precedent that an out-of-process scheduling helper may be marked
non-fatal, with scheduling continuing without it when it fails — plus
SURVEY §5: a TPU-resident scheduler must degrade to the host path when
the device seam is unhealthy, because a scheduler that stops binding is
a cluster outage, while a scheduler that schedules more slowly is a
latency regression.

`FailoverBatchBackend` stacks rungs of decreasing performance and
decreasing dependency surface:

    remote RemoteTPUBatchBackend   (network + worker process + device)
      -> in-process TPUBatchBackend (local jax device only)
        -> per-pod oracle           (pure Python, always available)

Each rung carries a circuit breaker (Nygard, "Release It!" — the
canonical pattern; gRPC/Envoy outlier detection is the same shape):

  * CLOSED — the rung serves dispatches.  A dispatch or resolve that
    raises BackendUnavailableError counts one consecutive failure; at
    `failure_threshold` the breaker OPENS and the ladder falls to the
    next rung.  Any success resets the count.
  * OPEN — the rung is skipped.  After `probe_interval` seconds the
    next dispatch half-opens it: one `health()` round trip (backends
    without a health probe are trusted).  A good probe RE-CLOSES the
    breaker (fail-back, not just fail-over); a bad one re-arms the
    window.
  * all rungs open — the "oracle rung": dispatch returns every pod as
    SKIP, which the scheduler routes to its per-pod Python path
    (scheduler.py `_deferred`).  Nothing is dropped and no binding is
    ever wrong, it is merely slow — and the breakers keep probing, so
    the fleet climbs back up the ladder as rungs recover.

The ladder itself NEVER absorbs a failed batch: the failing dispatch or
resolve re-raises BackendUnavailableError and the scheduler requeues the
batch into the queue's backoff tier (queue.requeue_backoff), so the same
pods re-dispatch on whatever rung the breakers then select.  State
consistency on fail-back is the normal dispatch contract: a re-closed
remote rung diffs the authoritative tensors against its stale mirror and
refreshes itself (ops/backend.py), so serving batches in-process while
the remote rung was open needs no extra bookkeeping.
"""

from __future__ import annotations

import logging
import threading
import time

from ..scheduler.scheduler import BackendUnavailableError, BatchBackend
from ..scheduler.types import SKIP, Status

logger = logging.getLogger(__name__)


class _Breaker:
    """Consecutive-failure circuit breaker for one rung."""

    def __init__(self, threshold: int, probe_interval: float, now_fn):
        self.threshold = max(1, threshold)
        self.probe_interval = probe_interval
        self._now = now_fn
        self.consecutive = 0
        self.opened_at: float | None = None

    @property
    def is_open(self) -> bool:
        return self.opened_at is not None

    def record_failure(self) -> bool:
        """Returns True when this failure OPENS the breaker."""
        if self.opened_at is not None:
            # failed while open (a bad probe): re-arm the probe window
            self.opened_at = self._now()
            return False
        self.consecutive += 1
        if self.consecutive >= self.threshold:
            self.opened_at = self._now()
            return True
        return False

    def record_success(self) -> bool:
        """Returns True when this success RE-CLOSES an open breaker."""
        self.consecutive = 0
        if self.opened_at is not None:
            self.opened_at = None
            return True
        return False

    def probe_due(self) -> bool:
        return (self.opened_at is not None
                and self._now() - self.opened_at >= self.probe_interval)


class _Rung:
    __slots__ = ("name", "backend", "breaker")

    def __init__(self, name: str, backend, breaker: _Breaker):
        self.name = name
        self.backend = backend
        self.breaker = breaker


class FailoverBatchBackend(BatchBackend):
    """BatchBackend that serves each dispatch from the healthiest rung.

    `backends` is an ordered [(name, backend), ...], best first — e.g.
    [("remote", RemoteTPUBatchBackend(...)), ("inproc", TPUBatchBackend
    (...))].  The oracle rung is implicit and last."""

    def __init__(self, backends, failure_threshold: int = 3,
                 probe_interval: float = 5.0, now_fn=time.monotonic):
        if not backends:
            raise ValueError("FailoverBatchBackend needs at least one rung")
        self._rungs = [
            _Rung(name, backend,
                  _Breaker(failure_threshold, probe_interval, now_fn))
            for name, backend in backends]
        self._lock = threading.Lock()
        self.seam_stats = {"failovers": 0, "recloses": 0, "probes": 0,
                           "failed_probes": 0, "oracle_batches": 0,
                           "rung_failures": 0}

    # -- rung selection --------------------------------------------------

    def _probe(self, rung: _Rung) -> bool:
        self.seam_stats["probes"] += 1
        health = getattr(rung.backend, "health", None)
        if health is None:
            return True  # no probe surface: trust the half-open attempt
        try:
            got = health()
            return bool(got.get("ok", True))
        except Exception:  # noqa: BLE001 — any probe failure keeps it open
            return False

    def _active(self) -> _Rung | None:
        """First healthy rung, half-open-probing open rungs whose window
        elapsed.  None = every rung is open -> oracle."""
        for rung in self._rungs:
            if not rung.breaker.is_open:
                return rung
            if rung.breaker.probe_due():
                if self._probe(rung):
                    rung.breaker.record_success()
                    self.seam_stats["recloses"] += 1
                    logger.warning("failover: rung %r healthy again; "
                                   "re-closing breaker", rung.name)
                    return rung
                self.seam_stats["failed_probes"] += 1
                rung.breaker.record_failure()  # re-arm the window
        return None

    def _on_failure(self, rung: _Rung, err: BaseException) -> None:
        self.seam_stats["rung_failures"] += 1
        if rung.breaker.record_failure():
            self.seam_stats["failovers"] += 1
            logger.warning(
                "failover: rung %r opened after %d consecutive failures "
                "(%s); falling to next rung", rung.name,
                rung.breaker.threshold, err)

    # -- BatchBackend ----------------------------------------------------

    @property
    def supports_pipelining(self) -> bool:
        with self._lock:
            rung = next((r for r in self._rungs if not r.breaker.is_open),
                        None)
        if rung is None:
            return False  # oracle rung: nothing in flight, ever
        return getattr(rung.backend, "supports_pipelining", True)

    def dispatch(self, pod_infos, snapshot):
        with self._lock:
            rung = self._active()
        if rung is None:
            self.seam_stats["oracle_batches"] += 1
            n = len(pod_infos)
            results = [(None, Status(
                SKIP, "all TPU rungs unavailable; per-pod oracle path"))
            ] * n
            return lambda: results
        try:
            resolve = rung.backend.dispatch(pod_infos, snapshot)
        except BackendUnavailableError as e:
            with self._lock:
                self._on_failure(rung, e)
            raise
        if not callable(resolve):
            return resolve  # FLUSH_FIRST passes through by identity

        def _resolve():
            try:
                results = resolve()
            except BackendUnavailableError as e:
                with self._lock:
                    self._on_failure(rung, e)
                raise
            with self._lock:
                if rung.breaker.record_success():
                    self.seam_stats["recloses"] += 1
            return results

        return _resolve

    def assign(self, pod_infos, snapshot):
        resolve = self.dispatch(pod_infos, snapshot)
        if not callable(resolve):  # pragma: no cover — FLUSH_FIRST
            raise RuntimeError("assign() cannot honor FLUSH_FIRST; "
                               "use dispatch/resolve")
        return resolve()

    # -- delegation ------------------------------------------------------

    def device_census(self, *args, **kwargs) -> dict:
        """Census the currently-active rung (the program waves actually
        run through); rungs without a device path contribute nothing."""
        with self._lock:
            rung = next((r for r in self._rungs if not r.breaker.is_open),
                        None)
        if rung is None:
            return {}
        fn = getattr(rung.backend, "device_census", None)
        return fn(*args, **kwargs) if fn is not None else {}

    @property
    def census_kind(self) -> str:
        with self._lock:
            rung = next((r for r in self._rungs if not r.breaker.is_open),
                        None)
        if rung is None:
            return "failover"
        inner = getattr(rung.backend, "census_kind", rung.name)
        return f"failover-{inner}"

    def warmup(self) -> None:
        """Warm EVERY rung: a failover target that still has kernels to
        compile would turn the first degraded batch into a compile storm."""
        for rung in self._rungs:
            warm = getattr(rung.backend, "warmup", None)
            if warm is None:
                continue
            try:
                warm()
            except BackendUnavailableError as e:
                with self._lock:
                    self._on_failure(rung, e)

    def prefetch(self, snapshot) -> None:
        for rung in self._rungs:
            if not rung.breaker.is_open:
                fn = getattr(rung.backend, "prefetch", None)
                if fn is not None:
                    fn(snapshot)
                return

    def note_namespace_event(self, event_type: str, obj, old=None) -> None:
        """Fan namespace-label events to EVERY rung (not just the active
        one): a cold standby must resolve namespaceSelector terms from a
        current cache the moment failover promotes it."""
        for rung in self._rungs:
            fn = getattr(rung.backend, "note_namespace_event", None)
            if fn is not None:
                fn(event_type, obj, old)

    def note_pdb_event(self, event_type: str, obj, old=None) -> None:
        """Fan PDB events to EVERY rung (same reason as namespace events:
        a standby's victim-tensor PDB bits must be current at promotion)."""
        for rung in self._rungs:
            fn = getattr(rung.backend, "note_pdb_event", None)
            if fn is not None:
                fn(event_type, obj, old)

    def note_node_event(self, event_type: str, name: str, view) -> None:
        """Fan node events to EVERY rung (incremental flatten): each rung
        keeps its own resident ClusterTensors, and a cold standby's rows
        must be generation-current the moment failover promotes it."""
        for rung in self._rungs:
            fn = getattr(rung.backend, "note_node_event", None)
            if fn is not None:
                fn(event_type, name, view)

    def preempt_candidates(self, pod_infos, k: int = 16):
        for rung in self._rungs:
            if not rung.breaker.is_open:
                fn = getattr(rung.backend, "preempt_candidates", None)
                if fn is not None:
                    return fn(pod_infos, k)
        return None

    def preempt_batch(self, pod_infos, node_ord_of, nominated=()):
        """Serve the batched dry run from the healthiest rung; a rung
        failure opens its breaker and the NEXT rung answers — the last
        resort escapes the whole wave to the per-pod Evaluator, one rung
        at a time down the same ladder dispatch rides."""
        for rung in self._rungs:
            with self._lock:
                open_ = rung.breaker.is_open
            if open_:
                continue
            fn = getattr(rung.backend, "preempt_batch", None)
            if fn is None:
                continue
            try:
                return fn(pod_infos, node_ord_of, nominated)
            except BackendUnavailableError as e:
                with self._lock:
                    self._on_failure(rung, e)
        # no healthy rung implements it: the caller's legacy tier takes
        # the wave (per-pod Evaluator / full host PostFilter)
        return ([None] * len(pod_infos),
                {i: "backend_unavailable" for i in range(len(pod_infos))})

    # -- observability ---------------------------------------------------

    def victim_occupancy(self) -> float:
        for rung in self._rungs:
            fn = getattr(rung.backend, "victim_occupancy", None)
            if fn is not None and not rung.breaker.is_open:
                return fn()
        return 0.0

    def maintenance_snapshot(self) -> dict:
        """The ACTIVE rung's tensor-maintenance readout (occupancy and
        tombstones are per-tensor-copy state, not summable; the wave
        counters follow the rung that actually dispatched)."""
        for rung in self._rungs:
            fn = getattr(rung.backend, "maintenance_snapshot", None)
            if fn is not None and not rung.breaker.is_open:
                return fn()
        return {}

    @property
    def stats(self) -> dict:
        """Summed per-rung batch stats (the scheduler reads e.g.
        stats['batches'] for its bench counters)."""
        total: dict = {}
        for rung in self._rungs:
            for key, val in getattr(rung.backend, "stats", {}).items():
                if isinstance(val, (int, float)):
                    total[key] = total.get(key, 0) + val
        return total

    def drain_escape_reasons(self) -> dict:
        """Summed per-(plugin, reason) escape tallies across rungs (the
        scheduler applies them as scheduler_tpu_escape_total deltas)."""
        out: dict = {}
        for rung in self._rungs:
            fn = getattr(rung.backend, "drain_escape_reasons", None)
            if fn is not None:
                for key, cnt in fn().items():
                    out[key] = out.get(key, 0) + cnt
        return out

    def drain_batch_telemetry(self) -> list:
        out: list = []
        for rung in self._rungs:
            fn = getattr(rung.backend, "drain_batch_telemetry", None)
            if fn is not None:
                out.extend(fn())
        return out

    def breaker_state(self) -> dict[str, float]:
        with self._lock:
            return {r.name: 1.0 if r.breaker.is_open else 0.0
                    for r in self._rungs}

    def seam_snapshot(self) -> dict[str, float]:
        """Own ladder counters + the primary rung's transport counters
        (retries/resyncs/...), prefixed, for scheduler.expose_metrics."""
        snap = dict(self.seam_stats)
        primary = self._rungs[0].backend
        for key, val in getattr(primary, "seam_stats", {}).items():
            snap[f"remote_{key}"] = val
        return snap

    def close(self) -> None:
        for rung in self._rungs:
            fn = getattr(rung.backend, "close", None)
            if fn is not None:
                fn()
