"""Deterministic fault injection for the remote TPU seam.

Reference: test/e2e's disruptive "chaosmonkey" pattern (test/e2e/chaosmonkey
— register disruptions, run them against live components, assert the system
converges) and SURVEY §5's resilience claims: a control plane is only as
fault-tolerant as the faults it has demonstrably survived.  This module
makes the seam's fault model EXECUTABLE: every failure mode the error
ladder in ops/remote.py claims to handle (lost requests, slow requests,
corrupted responses, a worker crash+restart) can be injected on a seeded,
reproducible schedule and asserted on in tests/test_chaos_seam.py and the
RemoteSeamFaulty bench config.

Design: `FaultyTransport` wraps a real client transport (the _HttpTransport
/ _GrpcTransport `post()` interface) and consults a `FaultSchedule` before
forwarding each call.  The schedule is deterministic two ways:

  * `script` — {call_index: action} pins an exact fault to an exact call
    (e.g. "kill the worker right before call 17").  Scripted entries win.
  * rates — drop/delay/corrupt probabilities drawn from a seeded
    random.Random.  Exactly ONE draw happens per call, before the script
    lookup, so adding a scripted entry never shifts the random stream of
    the calls around it.

Faults map to the seam's own vocabulary, so injected and organic failures
exercise identical client paths:

  DROP    -> raise TransientSeamError (request never reaches the worker);
             the client's bounded backoff retry absorbs it.
  DELAY   -> sleep, then forward (tail-latency; deadlines still apply).
  CORRUPT -> forward, then flip bytes in the response frame; the CRC
             framing detects it and the seq dedup makes the retry serve
             the original bytes without re-applying the step.
  KILL    -> call on_kill() (DeviceWorker.simulate_restart) BEFORE
             forwarding: the call lands on a state-lost worker and the
             client must run its checkpoint+journal resync.
"""

from __future__ import annotations

import random
import threading
import time

from .remote import TransientSeamError

DROP = "drop"
DELAY = "delay"
CORRUPT = "corrupt"
KILL = "kill"
NONE = "none"


class FaultSchedule:
    """Seeded, reproducible fault decisions, one per transport call.

    `action(i)` is consulted with a global call index; subclass it for
    stateful schedules (e.g. KillOnNthStep in the chaos tests keys on
    the Nth /step rather than an absolute call index)."""

    def __init__(self, seed: int = 0, drop_rate: float = 0.0,
                 delay_rate: float = 0.0, corrupt_rate: float = 0.0,
                 delay_s: float = 0.01,
                 script: dict[int, str] | None = None):
        self.rng = random.Random(seed)
        self.drop_rate = drop_rate
        self.delay_rate = delay_rate
        self.corrupt_rate = corrupt_rate
        self.delay_s = delay_s
        self.script = dict(script or {})

    def action(self, call_index: int, verb: str) -> str:
        # one draw per call REGARDLESS of the script, so scripted entries
        # don't shift the stream for later calls
        u = self.rng.random()
        scripted = self.script.get(call_index)
        if scripted is not None:
            return scripted
        if u < self.drop_rate:
            return DROP
        if u < self.drop_rate + self.delay_rate:
            return DELAY
        if u < self.drop_rate + self.delay_rate + self.corrupt_rate:
            return CORRUPT
        return NONE


def _corrupt(blob: bytes) -> bytes:
    """Flip a spray of bytes across the frame header and early payload —
    guaranteed to break either the magic or the CRC check."""
    out = bytearray(blob)
    for i in range(0, min(len(out), 33), 8):
        out[i] ^= 0xFF
    return bytes(out)


class FaultyTransport:
    """A client transport wrapper that injects schedule-driven faults.

    Drop-in for the inner transport (same `post` signature), handed to
    RemoteTPUBatchBackend via its `transport=` parameter.  `injected`
    counts what actually fired, keyed by action, for test/bench
    assertions; `calls` is the number of posts seen."""

    def __init__(self, inner, schedule: FaultSchedule,
                 on_kill=None):
        self.inner = inner
        self.kind = getattr(inner, "kind", "?")
        self.schedule = schedule
        self.on_kill = on_kill
        self.calls = 0
        self.injected = {DROP: 0, DELAY: 0, CORRUPT: 0, KILL: 0}
        self._lock = threading.Lock()

    def post(self, verb: str, body: bytes, *, timeout: float,
             epoch: int | None = None, seq: int | None = None,
             traceparent: str | None = None) -> bytes:
        with self._lock:
            i = self.calls
            self.calls += 1
            act = self.schedule.action(i, verb)
        if act == DROP:
            self.injected[DROP] += 1
            raise TransientSeamError(verb, f"injected drop (call {i})")
        if act == KILL and self.on_kill is not None:
            # restart BEFORE forwarding: this very call arrives at a
            # state-lost worker
            self.injected[KILL] += 1
            self.on_kill()
        if act == DELAY:
            self.injected[DELAY] += 1
            time.sleep(self.schedule.delay_s)
        out = self.inner.post(verb, body, timeout=timeout, epoch=epoch,
                              seq=seq, traceparent=traceparent)
        if act == CORRUPT:
            self.injected[CORRUPT] += 1
            return _corrupt(out)
        return out

    def close(self) -> None:
        self.inner.close()


# -- overload chaos (overload-resilience PR) -----------------------------
#
# Same philosophy, one seam up: where FaultyTransport injects TRANSPORT
# faults under the remote seam, ChaosBatchBackend injects LOAD faults at
# the BatchBackend contract itself — slow waves (a device that still
# answers, but late: the stuck-wave watchdog's prey) and adversarial
# all-escape waves (every pod SKIPs toward the per-pod oracle: the
# escape-storm breaker's prey).  Seeded + scriptable exactly like
# FaultSchedule so tests/test_overload.py and bench.py --overload replay
# identical storms.

SLOW = "slow"
ALL_ESCAPE = "all_escape"


class OverloadSchedule:
    """Seeded, reproducible per-WAVE overload decisions.

    One rng draw per wave regardless of the script (same stream-stability
    rule as FaultSchedule): scripted waves never shift the random stream
    of the waves around them."""

    def __init__(self, seed: int = 0, slow_rate: float = 0.0,
                 slow_s: float = 0.25, all_escape_rate: float = 0.0,
                 script: dict[int, str] | None = None):
        self.rng = random.Random(seed)
        self.slow_rate = slow_rate
        self.slow_s = slow_s
        self.all_escape_rate = all_escape_rate
        self.script = dict(script or {})

    def action(self, wave_index: int) -> str:
        u = self.rng.random()
        scripted = self.script.get(wave_index)
        if scripted is not None:
            return scripted
        if u < self.slow_rate:
            return SLOW
        if u < self.slow_rate + self.all_escape_rate:
            return ALL_ESCAPE
        return NONE


class ChaosBatchBackend:
    """A BatchBackend wrapper that injects schedule-driven overload faults.

    SLOW        -> forward the dispatch; the returned resolve() sleeps
                   slow_s before yielding the real results (a live but
                   late device — deadline/watchdog territory).
    ALL_ESCAPE  -> do NOT touch the inner backend: every pod in the wave
                   comes back (None, SKIP) as if its constraints were not
                   tensor-encodable, and the wave tallies a
                   ("chaos", "injected_all_escape") escape reason.  No
                   device state is claimed, so abandoning or retrying the
                   wave needs no repair.

    `injected` counts fired faults; `waves` is the dispatch count."""

    def __init__(self, inner, schedule: OverloadSchedule):
        self.inner = inner
        self.supports_pipelining = getattr(inner, "supports_pipelining", True)
        self.schedule = schedule
        self.waves = 0
        self.injected = {SLOW: 0, ALL_ESCAPE: 0}
        self._lock = threading.Lock()
        self._esc_pending: dict[tuple[str, str], int] = {}

    @property
    def stats(self):
        return getattr(self.inner, "stats", {})

    def dispatch(self, pod_infos, snapshot):
        from ..scheduler.types import SKIP, Status
        with self._lock:
            i = self.waves
            self.waves += 1
            act = self.schedule.action(i)
        if act == ALL_ESCAPE:
            self.injected[ALL_ESCAPE] += 1
            n = len(pod_infos)
            with self._lock:
                key = ("chaos", "injected_all_escape")
                self._esc_pending[key] = self._esc_pending.get(key, 0) + n
            results = [(None, Status(SKIP, "injected escape storm"))
                       for _ in range(n)]
            return lambda: results
        resolve = self.inner.dispatch(pod_infos, snapshot)
        if not callable(resolve):
            return resolve  # FLUSH_FIRST sentinel passes through
        if act == SLOW:
            self.injected[SLOW] += 1

            def slow_resolve():
                time.sleep(self.schedule.slow_s)
                return resolve()
            return slow_resolve
        return resolve

    def assign(self, pod_infos, snapshot):
        return self.dispatch(pod_infos, snapshot)()

    # -- forwarded backend surface (all optional on the contract) --------

    def warmup(self) -> None:
        fn = getattr(self.inner, "warmup", None)
        if fn is not None:
            fn()

    def health(self):
        fn = getattr(self.inner, "health", None)
        return fn() if fn is not None else True

    def prefetch(self, view) -> None:
        fn = getattr(self.inner, "prefetch", None)
        if fn is not None:
            fn(view)

    def note_node_event(self, event_type: str, name: str, view) -> None:
        fn = getattr(self.inner, "note_node_event", None)
        if fn is not None:
            fn(event_type, name, view)

    def maintenance_snapshot(self) -> dict:
        fn = getattr(self.inner, "maintenance_snapshot", None)
        return fn() if fn is not None else {}

    def abandon_wave(self) -> None:
        fn = getattr(self.inner, "abandon_wave", None)
        if fn is not None:
            fn()

    def drain_escape_reasons(self) -> dict:
        with self._lock:
            out, self._esc_pending = self._esc_pending, {}
        fn = getattr(self.inner, "drain_escape_reasons", None)
        if fn is not None:
            for key, cnt in fn().items():
                out[key] = out.get(key, 0) + cnt
        return out

    def drain_batch_telemetry(self) -> list:
        fn = getattr(self.inner, "drain_batch_telemetry", None)
        return fn() if fn is not None else []

    def device_census(self, *args, **kwargs) -> dict:
        fn = getattr(self.inner, "device_census", None)
        return fn(*args, **kwargs) if fn is not None else {}

    @property
    def census_kind(self) -> str:
        return getattr(self.inner, "census_kind", "chaos")


# -- scale-out chaos (horizontal scale-out PR) ---------------------------
#
# One seam further out again: where ChaosBatchBackend stresses a single
# scheduler's device path, the scale-out harness stresses the MEMBERSHIP
# of N cooperating scheduler instances — killing and reviving whole
# instances mid-wave so the survivors must absorb the dead instance's
# ring slices while its in-flight batch lands in backoff, never on a
# node a peer now owns.  Seeded + scriptable like the schedules above so
# tests/test_scaleout.py replays identical churn.

KILL_INSTANCE = "kill_instance"
REVIVE_INSTANCE = "revive_instance"


class ScaleOutSchedule:
    """Seeded, reproducible per-wave instance-churn decisions.

    One rng draw per wave regardless of the script (the stream-stability
    rule shared with FaultSchedule/OverloadSchedule).  The single draw
    decides BOTH the action and the victim: the draw's position inside
    its action band is re-scaled to an instance index, so adding a
    scripted wave never shifts the stream of the waves around it.
    Scripted entries are (action, instance_index) pairs and win."""

    def __init__(self, seed: int = 0, instance_count: int = 2,
                 kill_rate: float = 0.0, revive_rate: float = 0.0,
                 script: dict[int, tuple[str, int]] | None = None):
        self.rng = random.Random(seed)
        self.instance_count = instance_count
        self.kill_rate = kill_rate
        self.revive_rate = revive_rate
        self.script = dict(script or {})

    def action(self, wave_index: int) -> tuple[str, int]:
        u = self.rng.random()
        scripted = self.script.get(wave_index)
        if scripted is not None:
            return scripted
        if self.kill_rate and u < self.kill_rate:
            victim = int(u / self.kill_rate * self.instance_count)
            return (KILL_INSTANCE, min(victim, self.instance_count - 1))
        if self.revive_rate and u < self.kill_rate + self.revive_rate:
            frac = (u - self.kill_rate) / self.revive_rate
            victim = int(frac * self.instance_count)
            return (REVIVE_INSTANCE, min(victim, self.instance_count - 1))
        return (NONE, -1)


class InstanceChurner:
    """Applies ScaleOutSchedule actions to live ScaleOutCoordinators.

    The in-process kill switch is coordinator.retire(): the instance
    stops renewing its lease AND flips self_live to False, so its next
    bind wave takes the fenced path — exactly what lease expiry or a
    store fence does to a real deployment, minus the process exit.
    A min_live floor refuses kills that would leave the cluster with no
    scheduler at all (chaos must not deadlock the test).  `injected`
    counts actions that actually changed state, for assertions."""

    def __init__(self, coordinators, schedule: ScaleOutSchedule,
                 min_live: int = 1):
        self.coordinators = list(coordinators)
        self.schedule = schedule
        self.min_live = min_live
        self.waves = 0
        self.injected = {KILL_INSTANCE: 0, REVIVE_INSTANCE: 0}
        self.log: list[tuple[int, str, int]] = []
        self._lock = threading.Lock()

    def step(self) -> tuple[str, int] | None:
        """Consult the schedule for the next wave; returns the applied
        (action, instance) or None when nothing changed."""
        with self._lock:
            i = self.waves
            self.waves += 1
            act, idx = self.schedule.action(i)
            if act == NONE or not (0 <= idx < len(self.coordinators)):
                return None
            co = self.coordinators[idx]
            retired = getattr(co, "_retired", False)
            if act == KILL_INSTANCE:
                alive = sum(1 for c in self.coordinators
                            if not getattr(c, "_retired", False))
                if retired or alive <= self.min_live:
                    return None
                co.retire()
            else:
                if not retired:
                    return None
                co.revive()
            self.injected[act] += 1
            self.log.append((i, act, idx))
            return (act, idx)


class ProcessChurner:
    """InstanceChurner's process-true sibling: applies the SAME seeded
    ScaleOutSchedule to a procrun.ProcCluster, so the chaos an instance
    sees is identical whether it lives in this interpreter or in its own
    OS process.  KILL_INSTANCE becomes SIGKILL (no drain — the victim's
    lease lapses and survivors absorb its ring slices); REVIVE_INSTANCE
    becomes a respawn with the old instance identity.  Same min_live
    floor and `injected` accounting as InstanceChurner."""

    def __init__(self, cluster, schedule: ScaleOutSchedule,
                 min_live: int = 1):
        self.cluster = cluster
        self.schedule = schedule
        self.min_live = min_live
        self.waves = 0
        self.injected = {KILL_INSTANCE: 0, REVIVE_INSTANCE: 0}
        self.log: list[tuple[int, str, int]] = []
        self._lock = threading.Lock()

    def step(self) -> tuple[str, int] | None:
        with self._lock:
            i = self.waves
            self.waves += 1
            act, idx = self.schedule.action(i)
            if act == NONE or not (0 <= idx < self.cluster.n):
                return None
            if act == KILL_INSTANCE:
                if not self.cluster.alive(idx) \
                        or len(self.cluster.live_indices()) <= self.min_live:
                    return None
                self.cluster.kill(idx)
            else:
                if self.cluster.alive(idx):
                    return None
                self.cluster.respawn(idx)
            self.injected[act] += 1
            self.log.append((i, act, idx))
            return (act, idx)


# -- rolling-upgrade chaos (zero-downtime operations PR) -------------------
#
# The churners above kill and revive instances at random; the upgrade
# driver below cycles them DELIBERATELY — drain -> respawn -> readiness,
# one at a time, the way an operator rolls a new build through the
# topology — while the seeded schedule decides which rolls get sabotaged
# with a mid-drain SIGKILL (the child that ignores SIGTERM: the drain
# escalation must fire and the upgrade must still complete).

ROLL_INSTANCE = "roll_instance"
HANDOFF_APISERVER = "handoff_apiserver"


class UpgradeSchedule:
    """Seeded, reproducible rolling-upgrade decisions.

    One rng draw per step (the stream-stability rule shared with the
    other schedules): step k rolls instance k mod instance_count, and
    the draw only decides whether that roll is sabotaged with a
    mid-drain SIGKILL.  Scripted entries are (action, instance,
    sabotage) triples and win without consuming extra draws, so adding
    a scripted step never shifts the decisions around it."""

    def __init__(self, seed: int = 0, instance_count: int = 2,
                 sabotage_rate: float = 0.0,
                 script: dict[int, tuple[str, int, bool]] | None = None):
        self.rng = random.Random(seed)
        self.instance_count = instance_count
        self.sabotage_rate = sabotage_rate
        self.script = dict(script or {})

    def action(self, step_index: int) -> tuple[str, int, bool]:
        u = self.rng.random()
        scripted = self.script.get(step_index)
        if scripted is not None:
            return scripted
        idx = step_index % self.instance_count
        sabotage = bool(self.sabotage_rate and u < self.sabotage_rate)
        return (ROLL_INSTANCE, idx, sabotage)


class UpgradeDriver:
    """Applies an UpgradeSchedule to a procrun.ProcCluster.

    One step = one rolled child (drain -> respawn -> stdout READY ->
    /readyz 200), so the never-more-than-one-out invariant holds by
    construction.  A sabotaged step shrinks the drain window to zero,
    forcing ProcCluster.drain's SIGTERM->SIGKILL escalation mid-roll;
    the roll proceeds anyway — a hung child cannot stall the upgrade.
    HANDOFF_APISERVER steps replace the apiserver over its WAL
    (requires the cluster's data_dir)."""

    def __init__(self, cluster, schedule: UpgradeSchedule,
                 drain_timeout: float = 20.0, ready_timeout: float = 60.0):
        self.cluster = cluster
        self.schedule = schedule
        self.drain_timeout = drain_timeout
        self.ready_timeout = ready_timeout
        self.steps = 0
        self.injected = {ROLL_INSTANCE: 0, HANDOFF_APISERVER: 0,
                         "sabotaged": 0}
        self.log: list[tuple[int, str, int, bool]] = []
        self._lock = threading.Lock()

    def step(self) -> tuple[str, int] | None:
        with self._lock:
            i = self.steps
            self.steps += 1
            act, idx, sabotage = self.schedule.action(i)
            if act == HANDOFF_APISERVER:
                self.cluster.handoff_apiserver()
                self.injected[HANDOFF_APISERVER] += 1
                self.log.append((i, act, idx, False))
                return (act, idx)
            if act != ROLL_INSTANCE or not self.cluster.alive(idx):
                return None
            self.cluster.drain(idx,
                               timeout=0.0 if sabotage
                               else self.drain_timeout)
            self.cluster.respawn(idx, wait_ready=True)
            self.cluster.wait_child_ready(idx, timeout=self.ready_timeout)
            self.injected[ROLL_INSTANCE] += 1
            if sabotage:
                self.injected["sabotaged"] += 1
            self.log.append((i, act, idx, sabotage))
            return (act, idx)

    def roll_all(self) -> list[tuple[str, int]]:
        """One full rolling upgrade: every instance cycled once."""
        out = []
        for _ in range(self.cluster.n):
            applied = self.step()
            if applied is not None:
                out.append(applied)
        return out


# -- churn-storm chaos (signal-driven engagement PR) -----------------------
#
# The chaos above stresses the transport, the device path and the
# MEMBERSHIP; the storm below stresses the CLUSTER TOPOLOGY itself —
# flooding node adds, drains and relabels through the informer while pod
# floods are in flight, so the backend's row patches, between-wave
# compaction and pipelined generation fences absorb real event pressure
# with SLOs asserted on top.  Seeded + scriptable under the same
# one-draw-per-step stream-stability rule as every schedule above, so
# tests/test_churn_storm.py and bench.py replay identical storms.

NODE_ADD = "node_add"
NODE_DRAIN = "node_drain"
NODE_RELABEL = "node_relabel"


class ChurnStormSchedule:
    """Seeded, reproducible per-step node-churn decisions.

    One rng draw per step regardless of the script; the single draw
    decides BOTH the action and the victim — its position inside the
    action's probability band re-scales to a victim fraction (the
    ScaleOutSchedule idiom), so adding a scripted step never shifts the
    stream of the steps around it.  Scripted entries are
    (action, victim_fraction) pairs and win."""

    def __init__(self, seed: int = 0, add_rate: float = 0.0,
                 drain_rate: float = 0.0, relabel_rate: float = 0.0,
                 script: dict[int, tuple[str, float]] | None = None):
        self.rng = random.Random(seed)
        self.add_rate = add_rate
        self.drain_rate = drain_rate
        self.relabel_rate = relabel_rate
        self.script = dict(script or {})

    def action(self, step_index: int) -> tuple[str, float]:
        u = self.rng.random()
        scripted = self.script.get(step_index)
        if scripted is not None:
            return scripted
        if self.add_rate and u < self.add_rate:
            return (NODE_ADD, u / self.add_rate)
        lo = self.add_rate
        if self.drain_rate and u < lo + self.drain_rate:
            return (NODE_DRAIN, (u - lo) / self.drain_rate)
        lo += self.drain_rate
        if self.relabel_rate and u < lo + self.relabel_rate:
            return (NODE_RELABEL, (u - lo) / self.relabel_rate)
        return (NONE, 0.0)


class NodeStormDriver:
    """Applies ChurnStormSchedule actions to a live cluster store.

    NODE_ADD      -> create a fresh schedulable node (storm-N); lands as
                     an informer add -> backend row patch / gen bump.
    NODE_DRAIN    -> delete the victim node outright (the storm models
                     abrupt capacity loss, not cordon+wait): its row is
                     tombstoned, bound pods' accounting unwinds, and any
                     in-flight wave dispatched against the old topology
                     must gen-fence.  A min_nodes floor refuses drains
                     that would leave the flood nowhere to land (chaos
                     must not deadlock the run).
    NODE_RELABEL  -> bump a storm epoch label on the victim via
                     guaranteed_update; an update event that changes
                     labels invalidates selector caches without touching
                     capacity — the cheap-patch path under pressure.

    Victims are picked from the driver's live-name view (base nodes +
    storm adds - drains); `injected` counts applied actions and `log`
    records (step, action, node) for deterministic assertions."""

    def __init__(self, client, schedule: ChurnStormSchedule,
                 base_nodes, min_nodes: int = 1, max_nodes: int = 0,
                 cpu: str = "32", mem: str = "256Gi", pods: int = 110,
                 rack_labels: int = 0, name_prefix: str = "storm-"):
        self.client = client
        self.schedule = schedule
        self.min_nodes = max(1, min_nodes)
        # ceiling symmetrical to the floor: unbounded adds would grow the
        # cluster past the backend's tensor caps (n_cap) and stall every
        # wave; 0 = no ceiling (unit tests), harness default is 2x base
        self.max_nodes = max_nodes
        self.cpu, self.mem, self.pods = cpu, mem, pods
        self.rack_labels = rack_labels
        self.name_prefix = name_prefix
        self._names = list(base_nodes)
        self._next_id = 0
        self.steps = 0
        self.injected = {NODE_ADD: 0, NODE_DRAIN: 0, NODE_RELABEL: 0}
        self.log: list[tuple[int, str, str]] = []
        self._lock = threading.Lock()

    def _build_node(self, name: str, epoch: int):
        from ..testing import make_node
        w = make_node(name).capacity(cpu=self.cpu, mem=self.mem,
                                     pods=self.pods)
        labels = {"kubernetes.io/hostname": name,
                  "ktpu.io/storm-epoch": str(epoch)}
        if self.rack_labels:
            labels["ktpu.io/rack"] = str(epoch % self.rack_labels)
        w.labels(**labels)
        return w.build()

    def step(self) -> tuple[str, str] | None:
        """Consult the schedule once; returns the applied (action, node)
        or None when the step was a no-op (NONE draw or floor refusal)."""
        from ..client.clientset import NODES
        from ..store import kv
        with self._lock:
            i = self.steps
            self.steps += 1
            act, frac = self.schedule.action(i)
            if act == NODE_ADD:
                if self.max_nodes and len(self._names) >= self.max_nodes:
                    return None
                name = f"{self.name_prefix}{self._next_id}"
                node = self._build_node(name, self._next_id)
                self._next_id += 1
                try:
                    self.client.create(NODES, node)
                except kv.StoreError:
                    return None
                self._names.append(name)
            elif act == NODE_DRAIN:
                if len(self._names) <= self.min_nodes:
                    return None
                name = self._names.pop(
                    min(int(frac * len(self._names)),
                        len(self._names) - 1))
                try:
                    self.client.delete(NODES, "", name)
                except kv.StoreError:
                    return None
            elif act == NODE_RELABEL:
                if not self._names:
                    return None
                name = self._names[min(int(frac * len(self._names)),
                                       len(self._names) - 1)]

                def bump(cur, i=i):
                    cur["metadata"].setdefault("labels", {})[
                        "ktpu.io/storm-epoch"] = str(i)
                    return cur
                try:
                    self.client.guaranteed_update(NODES, "", name, bump)
                except kv.StoreError:
                    return None
            else:
                return None
            self.injected[act] += 1
            self.log.append((i, act, name))
            return (act, name)
