"""Snapshot -> tensor flattener (the tensorization source for the TPU path).

Reference semantics being tensorized:
  NodeInfo/Resource aggregates   pkg/scheduler/framework/types.go:375,426
  incremental snapshotting       internal/cache/cache.go:197 (generation diff)
  NodeResourcesFit               plugins/noderesources/fit.go:253
  TaintToleration / NodeAffinity / NodePorts / NodeUnschedulable
  PodTopologySpread match counts filtering.go:40-51 (per-(key,value) counts)
  InterPodAffinity count maps    filtering.go:90-230

Scheme (see SURVEY.md §7 step 1):
  * All categorical data (label key=value pairs, label keys, taints, host
    ports, scalar resource names) goes through capped vocabularies ->
    integer ids -> dense 0/1 masks.  Vocab caps are static so jitted shapes
    never change; overflow routes the affected pod to the per-pod oracle
    path (the escape hatch) rather than producing wrong answers.
  * Node rows re-encode ONLY when their NodeInfo generation advanced
    (mirrors UpdateSnapshot's delta copy).  Rows are reused via a free list,
    so the node axis is stable across batches and padded to n_cap.
  * Topology-sensitive constraints (spread / pod (anti-)affinity) compile
    to "selector groups": a (topology_key, selector, namespaces) triple.
    Per node we maintain cnt[sg, row] = matching pods on that node; per
    batch the per-domain base counts are one bincount away.  The greedy
    assignment scan (models/assign.py) then updates these counts on device
    as it places pods, which is what gives the batch the same semantics as
    the reference's one-pod-at-a-time loop with assume() in between
    (SURVEY.md §7 hard part #1).

Everything here is host-side numpy; device arrays are built/updated by
ops/backend.py from these buffers.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field

import numpy as np

from ..api import meta
from ..api.labels import (
    EXISTS, GT, IN, LT, NOT_IN, DOES_NOT_EXIST, Selector, selector_from_dict,
)
from ..api.meta import Obj
from ..scheduler.cache import Snapshot
from ..scheduler.plugins.nodebasic import toleration_tolerates_taint
from ..scheduler.types import NodeInfo, PodInfo
from ..utils.fasthost import req_columns

logger = logging.getLogger(__name__)

# resource slot layout: [cpu_milli, memory, ephemeral] + scalar slots
CORE_R = 3

# constraint kinds (c_kind)
C_NONE = 0
C_SPREAD_HARD = 1      # DoNotSchedule topology spread
C_AFFINITY = 2         # required pod affinity term
C_ANTI_AFFINITY = 3    # required pod anti-affinity term
C_SPREAD_SCORE = 4     # ScheduleAnyway topology spread
C_PREF_AFFINITY = 5    # preferred pod (anti-)affinity, weight signed

UNSCHEDULABLE_TAINT = ("node.kubernetes.io/unschedulable", "", "NoSchedule")

# victim-tensor priority padding: empty slots sort AFTER every real pod
# (priorities are int32; kept as i32 on device — f32 loses exactness
# above 2^24 and the reprieve/tie-break ordering must be bit-faithful)
VICT_PAD = np.int32(2**31 - 1)


class VocabFullError(Exception):
    pass


class Vocab:
    """String-ish -> dense id with a hard cap (static shapes for jit)."""

    def __init__(self, cap: int):
        self.cap = cap
        self.ids: dict = {}
        self.items: list = []

    def get(self, key, create: bool = True) -> int | None:
        idx = self.ids.get(key)
        if idx is None and create:
            if len(self.items) >= self.cap:
                raise VocabFullError(f"vocab cap {self.cap} exceeded by {key!r}")
            idx = len(self.items)
            self.ids[key] = idx
            self.items.append(key)
        return idx

    def lookup(self, key) -> int | None:
        return self.ids.get(key)

    def __len__(self) -> int:
        return len(self.items)


@dataclass
class SelectorGroup:
    """(topology_key, selector, namespaces) — the unit of count bookkeeping.

    namespaceSelector terms resolve to a CONCRETE namespace set at
    registration (ClusterTensors.group_for_term); such groups keep the
    selector and base namespaces around so a namespace relabel can
    re-resolve the set in place (_refresh_ns_groups)."""

    topology_key: str
    selector: Selector
    namespaces: frozenset[str]
    ns_selector: Selector | None = None      # resolved-from selector, if any
    base_namespaces: frozenset[str] | None = None  # term's explicit namespaces

    def key(self):
        return (self.topology_key, self.selector, self.namespaces)

    def matches_pod(self, pi: PodInfo) -> bool:
        return (meta.namespace(pi.pod) in self.namespaces
                and self.selector.matches(pi.labels))


class GroupBucket:
    """One sg/asg tensor slot, possibly shared by several DISTINCT
    selector groups (hash-bucketed once the cap is full — the
    high-label-cardinality regime: thousands of per-service
    anti-affinity selectors vs a few dozen tensor slots).

    A shared bucket's counts are the UNION over its member groups —
    an UPPER BOUND on any single member's true count.  Sharing is only
    sound for constraints that treat counts as BLOCKERS (required
    anti-affinity; preferred terms, where inflation merely distorts a
    score): over-counts then only over-block, so a device-allowed
    placement is always truly legal, and a no-fit verdict for a pod
    riding a collided bucket escapes to the per-pod oracle.  Required
    AFFINITY and DoNotSchedule spread treat counts as ENABLERS — a
    union count could falsely satisfy them — so those constraints only
    ever use EXCLUSIVE (single-group) slots and keep the old
    full-registry escape behavior (allow_share gating in
    register_sg).  Wrong answers stay structurally impossible; only
    the escape rate varies with cardinality (backend stats)."""

    __slots__ = ("topology_key", "groups", "allow_share")

    def __init__(self, group: SelectorGroup, allow_share: bool = False):
        self.topology_key = group.topology_key
        self.groups = [group]
        self.allow_share = allow_share

    @property
    def collided(self) -> bool:
        return len(self.groups) > 1

    def matches_pod(self, pi: PodInfo) -> bool:
        return any(g.matches_pod(pi) for g in self.groups)


def _stable_group_hash(group: SelectorGroup) -> int:
    """Deterministic bucket seed (hash() is per-process randomized,
    which would make escape sets differ run to run)."""
    import zlib
    reqs = tuple(sorted(
        (r.key, r.operator, tuple(sorted(r.values or ())))
        for r in group.selector.requirements))
    return zlib.crc32(repr((group.topology_key, reqs,
                            tuple(sorted(group.namespaces)))).encode())


def _exact_kv(group: SelectorGroup) -> tuple[str, str] | None:
    """(key, value) when the group's selector is a single exact match —
    the dominant shape (per-service matchLabels) — else None."""
    reqs = group.selector.requirements
    if (len(reqs) == 1 and reqs[0].operator == IN
            and len(reqs[0].values or ()) == 1):
        return (reqs[0].key, reqs[0].values[0])
    return None


@dataclass
class Caps:
    """Static tensor capacities. All jitted shapes derive from these."""

    # caps marked (packed) are bounded by the bitmask wire format
    # (models/assign.PackSpec): <=31 for one-word masks, kl_cap <= 62.
    n_cap: int = 1024          # node rows
    l_cap: int = 512           # label (key,value) vocab
    kl_cap: int = 62           # label key vocab (packed)
    t_cap: int = 31            # taint vocab (packed)
    pt_cap: int = 31           # host-port vocab (packed)
    s_cap: int = 5             # scalar resource slots
    sg_cap: int = 16           # selector groups (spread/affinity counts)
    asg_cap: int = 16          # anti-affinity groups of existing pods
    g_cap: int = 4             # any-of label groups per pod (node selector)
    kg_cap: int = 2            # any-of key groups per pod (Exists)
    c_cap: int = 6             # constraints per pod
    ns_cap: int = 256          # namespace vocab (namespaceSelector masks)
    v_cap: int = 16            # victim slots per node (batched preemption)

    @property
    def r(self) -> int:
        return CORE_R + self.s_cap


class ClusterTensors:
    """Host mirror of the snapshot as SoA numpy arrays, incrementally updated."""

    def __init__(self, caps: Caps | None = None):
        self.caps = caps or Caps()
        c = self.caps
        self.alloc = np.zeros((c.n_cap, c.r), np.float32)
        self.used = np.zeros((c.n_cap, c.r), np.float32)
        self.used_nz = np.zeros((c.n_cap, c.r), np.float32)
        self.npods = np.zeros(c.n_cap, np.float32)
        self.maxpods = np.zeros(c.n_cap, np.float32)
        self.valid = np.zeros(c.n_cap, bool)
        self.taint_mask = np.zeros((c.n_cap, c.t_cap), np.float32)
        self.label_mask = np.zeros((c.n_cap, c.l_cap), np.float32)
        self.key_mask = np.zeros((c.n_cap, c.kl_cap), np.float32)
        self.port_mask = np.zeros((c.n_cap, c.pt_cap), np.float32)
        # selector-group machinery
        self.dom_sg = np.full((c.sg_cap, c.n_cap), -1, np.int32)
        self.cnt_sg = np.zeros((c.sg_cap, c.n_cap), np.float32)
        self.dom_asg = np.full((c.asg_cap, c.n_cap), -1, np.int32)
        self.cnt_asg = np.zeros((c.asg_cap, c.n_cap), np.float32)

        self.scalar_vocab = Vocab(c.s_cap)
        self.label_vocab = Vocab(c.l_cap)
        self.key_vocab = Vocab(c.kl_cap)
        self.taint_vocab = Vocab(c.t_cap)   # entries: (key, value, effect)
        self.port_vocab = Vocab(c.pt_cap)   # entries: (protocol, port)
        self.domain_vocabs: dict[str, Vocab] = {}  # topo key -> value vocab

        # sg/asg slots are BUCKETS: one group each until the cap fills,
        # then distinct groups hash-share slots (see GroupBucket)
        self.sgs: list[GroupBucket] = []
        self._sg_ids: dict = {}
        self.asgs: list[GroupBucket] = []
        self._asg_ids: dict = {}
        # (key, value) -> [(idx, group)] for single-exact-kv selectors
        # (cross-pod matching in O(pod labels), not O(groups));
        # non-exact selectors go to the short linear-scan lists
        self._sg_kv_index: dict = {}
        self._sg_complex: list = []
        self._asg_kv_index: dict = {}
        self._asg_complex: list = []

        # namespaceSelector resolution: Namespace-object labels cached
        # from the informer feed (note_namespace/set_namespace_labels);
        # terms resolve to concrete namespace sets against it at encode
        # time, memoized until the cache changes (ns_version).  The
        # namespace vocab + per-slot masks are the DEVICE side: column =
        # namespace id, last column = namespaces outside the vocab.
        # All-ones rows (the init state, kept for every plain-namespace
        # group) make the kernel's namespace AND a no-op, so batches
        # without namespaceSelector terms pay nothing.
        self.ns_labels: dict[str, dict] = {}
        self.ns_version = 0
        self._ns_memo: dict = {}           # (base, ns_selector) -> frozenset
        self.ns_vocab = Vocab(c.ns_cap)
        self.sg_ns_mask = np.ones((c.sg_cap, c.ns_cap + 1), np.float32)
        self.asg_ns_mask = np.ones((c.asg_cap, c.ns_cap + 1), np.float32)

        # ns-anti guard: the conservative FALLBACK for namespaceSelector
        # anti-affinity terms whose group could NOT be registered (asg
        # bucket overflow) — any later pod whose labels could match one
        # of the unregistered selectors escapes to the oracle, so a
        # device placement can never violate them.  Armed at escape
        # time, never disarmed; zero cost while unarmed.  (Terms whose
        # group DID register need no guard: their counts/masks cover
        # them on the device path.)
        self.ns_anti_kv: set[tuple[str, str]] = set()
        self.ns_anti_complex = False

        self.row_of: dict[str, int] = {}
        self.node_infos: list[NodeInfo | None] = [None] * c.n_cap
        # registration-time name per row.  Dispatch snapshots THIS list to
        # resolve assignments, never NodeInfo.name: the zero-copy cache
        # view (CacheFlattenView) shares LIVE NodeInfos, and the cache
        # nulls .node in place when a drained node still holds pods — a
        # wave resolving across that mutation would read name "" and bind
        # pods to an empty nodeName (silently lost; nothing requeues them)
        self.row_names: list[str | None] = [None] * c.n_cap
        self.gen = np.zeros(c.n_cap, np.int64)
        self.node_gen = np.full(c.n_cap, -1, np.int64)  # last static encode
        self._free = list(range(c.n_cap - 1, -1, -1))
        # released rows park here instead of going straight back to _free:
        # a row freed mid-wave must not be re-assigned to a new node while
        # an in-flight wave still references it by index.  compact()
        # (called by the backend between waves, or forcibly by _sync_rows
        # when _free empties) scrubs the group columns and recycles them.
        self._tombstones: set[int] = set()
        # patch_gen counts patch/compaction API applications; every
        # mutation through patch_node/patch_remove/compact bumps it (the
        # tensor-patch-discipline lint keys off this counter)
        self.patch_gen = 0
        # per-row dynamic-aggregate digest for the bulk re-encode skip:
        # bind-shaped churn (assume→confirm cycles) advances NodeInfo
        # generations without changing the encoded aggregates; a matching
        # digest means the row's dynamic columns are already current
        self._dyn_digest: list = [None] * c.n_cap
        # rows that have EVER held data: a pristine row's arrays are still
        # their init zeros, so the fresh-flood encode can skip the ~360
        # floats/row of zero-fills (at 100k nodes those writes alone cost
        # ~0.3s inside the first scheduling window)
        self._ever_used = np.zeros(c.n_cap, bool)
        # static_version tracks arrays that rarely change (labels, taints,
        # alloc, domains); the device cache keys off it so binding a pod —
        # which dirties used/npods only — doesn't trigger a multi-MB
        # re-upload of the label/key masks every batch.
        self.version = 0         # any host-array mutation
        self.static_version = 0  # label/key/taint/alloc/dom/valid mutations
        # row-incremental static upload support: rows whose static fields
        # changed since the backend's last upload; static_full forces a
        # whole-array re-upload (column backfills touch every row)
        self.static_dirty_rows: set[int] = set()
        self.static_full = True

        # victim tensors (batched preemption / DryRunPreemption): per-node
        # resident pods sorted ascending by priority.  PAD slots carry
        # VICT_PAD so `vict_prio < preemptor_prio` masks them out for any
        # real priority.  Maintained LAZILY: hot paths (binds) only mark
        # rows dirty here; refresh_victims() re-encodes at preempt time so
        # the per-bind cost is one set.add.  vict_version is a SEPARATE
        # upload channel from static_version — victim rebuilds must not
        # invalidate the static cache (that would force a multi-MB label
        # re-upload per preemption wave).
        self.vict_prio = np.full((c.n_cap, c.v_cap), VICT_PAD, np.int32)
        self.vict_req = np.zeros((c.n_cap, c.v_cap, c.r), np.float32)
        self.vict_pdb = np.zeros((c.n_cap, c.v_cap), np.float32)
        self.vict_over = np.zeros(c.n_cap, bool)
        # host-side victim identities per row (slot-aligned with
        # vict_prio); None for rows never victim-encoded
        self.vict_keys: list[list | None] = [None] * c.n_cap
        self.vict_version = 0
        self.vict_dirty_rows: set[int] = set()
        self.vict_full = True
        # PDB cache from the informer: (namespace, name) -> (namespace,
        # Selector, disruptionsAllowed).  The device bit marks victims
        # covered by a BLOCKING pdb (allowed <= 0); pdb_version feeds the
        # victim-refresh staleness check.
        self.pdbs: dict[tuple, tuple] = {}
        self.pdb_version = 0
        self._vict_pdb_version = -1

    # -- vocab helpers ---------------------------------------------------

    def ensure_label_id(self, pair: tuple[str, str]) -> int:
        """Get-or-create a (key,value) label id, backfilling the node column
        for all live rows on creation."""
        lid = self.label_vocab.lookup(pair)
        if lid is not None:
            return lid
        lid = self.label_vocab.get(pair)
        k, v = pair
        for row, ni in enumerate(self.node_infos):
            if ni is not None and self.valid[row] and ni.node is not None:
                if meta.labels(ni.node).get(k) == v:
                    self.label_mask[row, lid] = 1.0
        self.version += 1
        self.static_version += 1
        self.static_full = True  # column fill: every row changed
        return lid

    def ensure_key_id(self, key: str) -> int:
        kid = self.key_vocab.lookup(key)
        if kid is not None:
            return kid
        kid = self.key_vocab.get(key)
        for row, ni in enumerate(self.node_infos):
            if ni is not None and self.valid[row] and ni.node is not None:
                if key in meta.labels(ni.node):
                    self.key_mask[row, kid] = 1.0
        self.version += 1
        self.static_version += 1
        self.static_full = True  # column fill: every row changed
        return kid

    # -- namespace resolution (namespaceSelector terms) ------------------

    def resolve_namespaces(self, base: frozenset,
                           ns_selector: Selector) -> frozenset:
        """base ∪ {namespaces whose labels match ns_selector}, mirroring
        the oracle's AffinityTerm.matches exactly: a namespace must have
        a cached OBJECT to match (an empty match-all selector matches
        only known namespaces), and empty labels {} DO match a match-all
        selector.  Memoized until the namespace cache changes."""
        memo_key = (base, ns_selector)
        got = self._ns_memo.get(memo_key)
        if got is None:
            got = frozenset(base) | {
                ns for ns, lbl in self.ns_labels.items()
                if ns_selector.matches(lbl)}
            self._ns_memo[memo_key] = got
        return got

    def term_group_key(self, term):
        """The sg/asg id-map key for an affinity term: plain terms keep
        the raw (topology_key, selector, namespaces) triple; terms with
        a namespaceSelector key on the RESOLVED namespace set."""
        if term.ns_selector is None:
            return (term.topology_key, term.selector, term.namespaces)
        return (term.topology_key, term.selector,
                self.resolve_namespaces(term.namespaces, term.ns_selector))

    def group_for_term(self, term) -> SelectorGroup:
        """SelectorGroup for an affinity term, with any namespaceSelector
        resolved against the namespace-label cache."""
        if term.ns_selector is None:
            return SelectorGroup(term.topology_key, term.selector,
                                 term.namespaces)
        return SelectorGroup(
            term.topology_key, term.selector,
            self.resolve_namespaces(term.namespaces, term.ns_selector),
            ns_selector=term.ns_selector, base_namespaces=term.namespaces)

    def intern_namespaces(self, namespaces) -> bool:
        """Intern namespaces into the device vocab; False when the vocab
        cannot hold them all (the registering pod then escapes with
        reason namespace_vocab_overflow — the group itself still
        registers with exact host-side counts and an all-ones mask)."""
        ok = True
        for ns in namespaces:
            try:
                self.ns_vocab.get(ns)
            except VocabFullError:
                ok = False
        return ok

    def set_namespace_labels(self, name: str, labels: dict | None) -> None:
        """Update the namespace-label cache (labels=None: namespace
        deleted) and re-resolve every registered namespaceSelector group
        against it.  Deterministic invalidation: the NEXT batch encodes
        against the new resolution — no TTL, no staleness window beyond
        informer delivery."""
        if labels is None:
            if name not in self.ns_labels:
                return
            del self.ns_labels[name]
        else:
            labels = dict(labels)
            if self.ns_labels.get(name) == labels:
                return
            self.ns_labels[name] = labels
        self.ns_version += 1
        self._ns_memo.clear()
        self._refresh_ns_groups()

    def note_namespace(self, obj: Obj, deleted: bool = False) -> None:
        """Feed one Namespace informer event into the cache."""
        self.set_namespace_labels(
            meta.name(obj), None if deleted else meta.labels(obj))

    # -- PDB cache (batched preemption victim bits) -----------------------

    def note_pdb(self, obj: Obj, deleted: bool = False) -> None:
        """Feed one PodDisruptionBudget informer event into the cache.
        Mirrors the Evaluator's _list_pdbs shape: (selector, allowed)
        pairs, allowed defaulting to 0 when status is absent."""
        key = (meta.namespace(obj), meta.name(obj))
        if deleted:
            if self.pdbs.pop(key, None) is None:
                return
        else:
            spec = obj.get("spec") or {}
            status = obj.get("status") or {}
            entry = (key[0], selector_from_dict(spec.get("selector") or {}),
                     int(status.get("disruptionsAllowed", 0)))
            if self.pdbs.get(key) == entry:
                return
            self.pdbs[key] = entry
        self.pdb_version += 1

    def pdb_blocking(self) -> list[tuple]:
        """(namespace, selector) pairs of BLOCKING pdbs (allowed <= 0) —
        the only ones whose coverage counts as a violation in the
        Evaluator's _violates_pdb."""
        return [(ns, sel) for ns, sel, allowed in self.pdbs.values()
                if allowed <= 0]

    def _refresh_ns_groups(self) -> None:
        """Re-resolve registered namespaceSelector groups after a
        namespace-label change: group membership sets, id-map keys,
        per-node counts and the device namespace masks all follow the
        new resolution in one pass."""
        changed = False
        for is_sg in (True, False):
            buckets = self.sgs if is_sg else self.asgs
            ids = self._sg_ids if is_sg else self._asg_ids
            for idx, bucket in enumerate(buckets):
                touched = False
                for g in bucket.groups:
                    if g.ns_selector is None:
                        continue
                    new = self.resolve_namespaces(g.base_namespaces,
                                                  g.ns_selector)
                    if new == g.namespaces:
                        continue
                    old_key = g.key()
                    if ids.get(old_key) == idx:
                        del ids[old_key]
                    g.namespaces = new
                    ids[g.key()] = idx
                    touched = True
                if not touched:
                    continue
                changed = True
                for row, ni in enumerate(self.node_infos):
                    if ni is None or not self.valid[row]:
                        continue
                    if is_sg:
                        self._encode_sg_row(idx, row, ni)
                    else:
                        self._encode_asg_row(idx, row, ni)
                self.intern_namespaces(
                    ns for g in bucket.groups for ns in g.namespaces)
                self._ns_mask_row_update(idx, bucket, is_sg)
        if changed:
            self.version += 1
            self.static_version += 1
            self.static_full = True

    def _ns_mask_row_update(self, idx: int, bucket: GroupBucket,
                            is_sg: bool) -> bool:
        """Device namespace mask for one sg/asg slot (column = namespace
        vocab id; last column = outside-vocab namespaces).  The host
        fold is authoritative — it sets inc/match bits from the same
        resolved sets — so the mask is enforcement, not semantics: a
        stale or fallback row can only over-block, never admit a
        placement the resolution forbids.  Plain members and
        outside-vocab namespaces therefore fall back to all-ones (the
        kernel AND becomes a no-op)."""
        mask = self.sg_ns_mask if is_sg else self.asg_ns_mask
        row = np.zeros(self.caps.ns_cap + 1, np.float32)
        exact = True
        for g in bucket.groups:
            if g.ns_selector is None:
                exact = False   # plain member: its namespaces aren't interned
                break
            for ns in g.namespaces:
                nid = self.ns_vocab.lookup(ns)
                if nid is None:
                    exact = False
                    break
                row[nid] = 1.0
            if not exact:
                break
        if not exact:
            row[:] = 1.0
        # report row-value changes: mask mutations are NOT row-patchable
        # (no node axis), so callers must force a full static re-upload
        changed = not np.array_equal(mask[idx], row)
        mask[idx] = row
        return changed

    def domain_id(self, topo_key: str, value: str) -> int:
        vocab = self.domain_vocabs.get(topo_key)
        if vocab is None:
            vocab = self.domain_vocabs[topo_key] = Vocab(self.caps.n_cap)
        return vocab.get(value)

    def _dom_row_for_key(self, key: str,
                         exclude: GroupBucket | None = None) -> np.ndarray:
        """[n_cap] domain-id row for a topology key.

        The row depends only on (key, node labels) — never on the group —
        so any existing bucket with the same key already holds it; copy
        instead of touching every node again (a high-cardinality flood
        registers thousands of same-key groups in one encode pass).
        `exclude` is the bucket being registered (its row is not yet
        encoded)."""
        for arr, buckets in ((self.dom_sg, self.sgs),
                             (self.dom_asg, self.asgs)):
            for j, b in enumerate(buckets):
                if b.topology_key == key and b is not exclude:
                    return arr[j].copy()
        row = np.full(self.caps.n_cap, -1, np.int32)
        for r, ni in enumerate(self.node_infos):
            if ni is None or not self.valid[r] or ni.node is None:
                continue
            val = meta.labels(ni.node).get(key)
            if val is not None:
                row[r] = self.domain_id(key, val)
        return row

    @staticmethod
    def _probe_bucket(buckets: list[GroupBucket],
                      group: SelectorGroup) -> int | None:
        """Slot for a group once the cap is full: hash start + linear
        probe to a SHAREABLE bucket with the SAME topology key (dom
        rows are per-topology-key, so cross-key sharing would corrupt
        domain ids; exclusive slots serve count-as-enabler constraints
        and must never be joined).  None when no compatible bucket
        exists."""
        cap = len(buckets)
        start = _stable_group_hash(group) % cap
        for probe in range(cap):
            b = buckets[(start + probe) % cap]
            if b.allow_share and b.topology_key == group.topology_key:
                return (start + probe) % cap
        return None

    def _index_group(self, kv_index: dict, complex_list: list,
                     idx: int, group: SelectorGroup) -> None:
        kv = _exact_kv(group)
        if kv is not None:
            kv_index.setdefault(kv, []).append((idx, group))
        else:
            complex_list.append((idx, group))

    def register_sg(self, group: SelectorGroup,
                    shareable: bool = False) -> int | None:
        """Returns sg index, backfilling counts for all live rows.

        shareable=True (count-as-BLOCKER constraints: required
        anti-affinity, preferred/score terms): beyond the cap the group
        hash-shares a bucket (GroupBucket upper-bound semantics).
        shareable=False (count-as-ENABLER: required affinity,
        DoNotSchedule spread): the group needs an exclusive slot —
        None when the registry is full OR the group already lives in a
        shared bucket (escape hatch, exactly the pre-bucketing
        behavior)."""
        idx = self._sg_ids.get(group.key())
        if idx is not None:
            if not shareable:
                if self.sgs[idx].collided:
                    return None  # exact counts required; slot is shared
                # pin the slot: an enabler-constraint user means no
                # later overflow group may join it
                self.sgs[idx].allow_share = False
            return idx
        if len(self.sgs) < self.caps.sg_cap:
            idx = len(self.sgs)
            self.sgs.append(GroupBucket(group, allow_share=shareable))
            is_new_bucket = True
        else:
            if not shareable:
                return None
            idx = self._probe_bucket(self.sgs, group)
            if idx is None:
                return None
            self.sgs[idx].groups.append(group)
            is_new_bucket = False
        self._sg_ids[group.key()] = idx
        self._index_group(self._sg_kv_index, self._sg_complex, idx, group)
        mask_changed = False
        if group.ns_selector is not None or self.sgs[idx].collided:
            # a namespaceSelector member (or a join that may widen a
            # selective row) re-derives the slot's namespace mask
            mask_changed = self._ns_mask_row_update(idx, self.sgs[idx], True)
        # Registration cost discipline (a 2000-service flood registers
        # its whole vocabulary inside ONE batch encode): a new bucket
        # copies/derives its dom row in one vectorized step; a JOIN can
        # only change counts on nodes that hold pods matching the new
        # member, so empty nodes are skipped and nothing is bumped when
        # nothing changed (the bump would force a static re-upload and a
        # pipeline flush PER REGISTRATION — measured 26s of a 26s
        # high-cardinality run before this).
        bucket = self.sgs[idx]
        if is_new_bucket:
            self.dom_sg[idx] = self._dom_row_for_key(bucket.topology_key,
                                                     exclude=bucket)
        changed = is_new_bucket or mask_changed
        for row, ni in enumerate(self.node_infos):
            if ni is None or not self.valid[row] or not ni.pods:
                continue
            new = sum(1 for pi in ni.pods
                      if not meta.deletion_timestamp(pi.pod)
                      and bucket.matches_pod(pi))
            if new != self.cnt_sg[idx, row]:
                self.cnt_sg[idx, row] = new
                changed = True
        if changed:
            self.version += 1
            self.static_version += 1  # dom_sg/cnt_sg rows changed
            self.static_full = True
        return idx

    def register_asg(self, group: SelectorGroup) -> int | None:
        idx = self._asg_ids.get(group.key())
        if idx is not None:
            return idx
        if len(self.asgs) < self.caps.asg_cap:
            idx = len(self.asgs)
            # asg counts only ever BLOCK (existing-pod anti-affinity),
            # so every asg slot is shareable
            self.asgs.append(GroupBucket(group, allow_share=True))
            is_new_bucket = True
        else:
            idx = self._probe_bucket(self.asgs, group)
            if idx is None:
                return None
            self.asgs[idx].groups.append(group)
            is_new_bucket = False
        self._asg_ids[group.key()] = idx
        self._index_group(self._asg_kv_index, self._asg_complex, idx,
                          group)
        mask_changed = False
        if group.ns_selector is not None or self.asgs[idx].collided:
            mask_changed = self._ns_mask_row_update(idx, self.asgs[idx],
                                                    False)
        # same registration cost discipline as register_sg: vectorized
        # dom row for new buckets, count deltas only on nodes that hold
        # anti-affinity pods, version bumps only when something changed
        if is_new_bucket:
            self.dom_asg[idx] = self._dom_row_for_key(
                group.topology_key, exclude=self.asgs[idx])
        ids = self._asg_ids
        term_key = self.term_group_key
        changed = is_new_bucket or mask_changed
        for row, ni in enumerate(self.node_infos):
            if (ni is None or not self.valid[row]
                    or not ni.pods_with_required_anti_affinity):
                continue
            n = 0
            for pi in ni.pods_with_required_anti_affinity:
                for term in pi.required_anti_affinity_terms:
                    if ids.get(term_key(term)) == idx:
                        n += 1
            if n != self.cnt_asg[idx, row]:
                self.cnt_asg[idx, row] = n
                changed = True
        if changed:
            self.version += 1
            self.static_version += 1  # dom_asg/cnt_asg rows changed
            self.static_full = True
        return idx

    # -- node encoding ---------------------------------------------------

    def update_from_snapshot(self, snapshot: Snapshot) -> bool:
        """Incremental refresh; returns True if anything changed."""
        return bool(self.update_from_snapshot_tracked(snapshot))

    def update_from_snapshot_tracked(self, snapshot) -> list[int]:
        """Incremental refresh; returns the rows re-encoded this call.

        Accepts either an immutable scheduler Snapshot or a zero-copy
        cache view (scheduler/cache.py CacheFlattenView): views run the
        whole re-encode under the cache lock so rows are never encoded
        from a NodeInfo mid-mutation, and skip the per-dirty-node clone
        the Snapshot path pays.  Views that can feed the changed-node
        delta (run_locked_dirty) skip the O(nodes) membership scan too."""
        if not os.environ.get("KTPU_FORCE_REFLATTEN"):
            # A/B baseline knob: when set, skip the changed-node delta so
            # every sync pays the O(nodes) full scan (the pre-incremental
            # world bench measures the maintenance win against)
            run_dirty = getattr(snapshot, "run_locked_dirty", None)
            if run_dirty is not None:
                return run_dirty(self._update_from_dirty)
        run_locked = getattr(snapshot, "run_locked", None)
        if run_locked is not None:
            return run_locked(self._update_from_nodes_tracked)
        return self._update_from_nodes_tracked(snapshot.node_info_list)

    def _sync_rows(self, named_infos) -> list[int]:
        """Re-encode every (name, NodeInfo) whose generation advanced;
        returns the touched rows.  Bind-only dirt (node_generation
        unchanged, no ports/scalars/selector groups) takes a BULK columnar
        re-encode: at bench shapes every batch dirties one row per bound
        pod, and the per-row _encode_node costs ~30µs x 16k rows."""
        dirty: list[int] = []
        bulk: list = []  # (row, ni) pairs eligible for the columnar path
        fresh_bulk: list = []  # brand-new podless rows (creation floods)
        bulk_ok = not self.sgs and not self.asgs
        row_of, gen, valid = self.row_of, self.gen, self.valid
        digests = self._dyn_digest
        for name, ni in named_infos:
            row = row_of.get(name)
            if row is None:
                if not self._free and self._tombstones:
                    self.compact()
                if not self._free:
                    raise VocabFullError(
                        f"node capacity {self.caps.n_cap} exceeded")
                row = self._free.pop()
                row_of[name] = row
                self.row_names[row] = name
                gen[row] = -1
            if gen[row] != ni.generation:
                if (bulk_ok and valid[row]
                        and self.node_gen[row] == ni.node_generation
                        and not ni.used_ports
                        and not ni.requested.scalar):
                    req, nz = ni.requested, ni.non_zero_requested
                    dg = (req.milli_cpu, req.memory, req.ephemeral_storage,
                          nz.milli_cpu, nz.memory, nz.ephemeral_storage,
                          len(ni.pods))
                    if digests[row] == dg:
                        # identical aggregates (snapshot paths clone
                        # NodeInfos per update): record the generation and
                        # NodeInfo identity, skip the rewrite + upload
                        self.node_infos[row] = ni
                        gen[row] = ni.generation
                        self.vict_dirty_rows.add(row)
                        continue
                    digests[row] = dg
                    bulk.append((row, ni))
                elif (bulk_ok and not valid[row] and ni.node is not None
                        and not ni.pods and not ni.used_ports
                        and not ni.allocatable.scalar):
                    fresh_bulk.append((row, ni))
                else:
                    self._encode_node(row, ni)
                gen[row] = ni.generation
                dirty.append(row)
        if bulk:
            self._encode_dynamic_bulk(bulk)
        if fresh_bulk:
            self._encode_fresh_bulk(fresh_bulk)
        if dirty:
            # resident-pod set may have changed on these rows; victim
            # tensors re-encode lazily at preempt time
            self.vict_dirty_rows.update(dirty)
        return dirty

    def _encode_fresh_bulk(self, pairs: list) -> None:
        """Columnar encode for brand-new podless rows — the node-creation
        flood shape (100k registrations before any pod exists).  The
        per-row _encode_node costs ~30µs; this path is ~4µs/row: dynamic
        fields are zero-filled column-wise, alloc/maxpods come from list
        comprehensions, and only taints/labels stay per-row (short dict
        loops, vocab lookups only)."""
        rows = np.fromiter((r for r, _ in pairs), np.int64, len(pairs))
        infos = [ni for _, ni in pairs]
        node_infos = self.node_infos
        for row, ni in pairs:
            node_infos[row] = ni
        # zero-fill only rows that have ever held data; pristine rows are
        # still their init zeros (the 100k-registration flood writes none)
        stale = rows[self._ever_used[rows]]
        if len(stale):
            for arr in (self.used, self.used_nz, self.port_mask, self.alloc,
                        self.taint_mask, self.label_mask, self.key_mask):
                arr[stale] = 0.0
            self.npods[stale] = 0.0
        self._ever_used[rows] = True
        self.alloc[rows, 0] = [ni.allocatable.milli_cpu for ni in infos]
        self.alloc[rows, 1] = [ni.allocatable.memory for ni in infos]
        self.alloc[rows, 2] = [ni.allocatable.ephemeral_storage
                               for ni in infos]
        self.maxpods[rows] = [ni.allocatable.allowed_pod_number
                              for ni in infos]
        self.node_gen[rows] = [ni.node_generation for ni in infos]
        self.valid[rows] = True
        tm, lm, km = self.taint_mask, self.label_mask, self.key_mask
        lv, kv = self.label_vocab.lookup, self.key_vocab.lookup
        tv = self.taint_vocab.get
        for row, ni in pairs:
            node = ni.node
            spec = node.get("spec") or {}
            taints = spec.get("taints")
            if taints or spec.get("unschedulable"):
                taints = list(taints or ())
                if spec.get("unschedulable"):
                    taints.append({"key": UNSCHEDULABLE_TAINT[0],
                                   "value": UNSCHEDULABLE_TAINT[1],
                                   "effect": UNSCHEDULABLE_TAINT[2]})
                for t in taints:
                    tm[row, tv((t.get("key", ""), t.get("value", ""),
                                t.get("effect", "")))] = 1.0
            for k, v in meta.labels(node).items():
                lid = lv((k, v))
                if lid is not None:
                    lm[row, lid] = 1.0
                kid = kv(k)
                if kid is not None:
                    km[row, kid] = 1.0
        self.static_version += 1
        self.static_dirty_rows.update(rows.tolist())

    def _release_row(self, name: str) -> int | None:
        row = self.row_of.pop(name, None)
        if row is None:
            return None
        self.valid[row] = False
        self.node_infos[row] = None
        self.row_names[row] = None
        self.node_gen[row] = -1
        self._dyn_digest[row] = None
        self._tombstones.add(row)
        self.static_version += 1
        self.static_dirty_rows.add(row)
        self.vict_dirty_rows.add(row)
        return row

    def compact(self) -> int:
        """Reclaim tombstoned row slots: scrub the selector-group columns
        a dead row may still carry (valid=False masks it on device, but a
        recycled slot must start clean) and return the slots to the free
        list.  Called by the backend between waves (never while a wave is
        in flight — an in-flight wave references rows by index) and
        forcibly by _sync_rows when the free list empties.  Selector-group
        SLOTS are buckets and stay permanent; only node rows recycle."""
        if not self._tombstones:
            return 0
        rows = sorted(self._tombstones, reverse=True)
        arr = np.asarray(rows, np.int64)
        self.cnt_sg[:, arr] = 0.0
        self.dom_sg[:, arr] = -1
        self.cnt_asg[:, arr] = 0.0
        self.dom_asg[:, arr] = -1
        self._tombstones.clear()
        self._free.extend(rows)
        self.static_dirty_rows.update(rows)
        self.version += 1
        self.static_version += 1
        self.patch_gen += 1
        return len(rows)

    def tombstone_count(self) -> int:
        return len(self._tombstones)

    def row_occupancy(self) -> float:
        """Fraction of node-row capacity holding a live node."""
        return len(self.row_of) / self.caps.n_cap

    # -- event-driven patch API (incremental flatten) --------------------
    # Informer deltas land here between waves so the resident tensors stay
    # current without a per-wave snapshot re-encode; the wave-time drain
    # (update_from_snapshot_tracked) remains the authoritative backstop —
    # a row patched here is generation-current and skips re-encode there.

    def patch_node(self, name: str, ni: NodeInfo) -> int | None:
        """Apply one node add/update event as a targeted row patch.
        Returns the touched row (for the backend's dirty-row upload), or
        None when the row is already generation-current.  Raises
        VocabFullError only if compaction cannot free a slot."""
        row = self.row_of.get(name)
        if row is None:
            if not self._free and self._tombstones:
                self.compact()
            if not self._free:
                raise VocabFullError(
                    f"node capacity {self.caps.n_cap} exceeded")
            row = self._free.pop()
            self.row_of[name] = row
            self.row_names[row] = name
            self.gen[row] = -1
        elif self.gen[row] == ni.generation:
            return None
        self._encode_node(row, ni)
        self.gen[row] = ni.generation
        self.vict_dirty_rows.add(row)
        self.version += 1
        self.patch_gen += 1
        return row

    def patch_remove(self, name: str) -> int | None:
        """Apply one node delete event: tombstone the row (reclaimed by a
        later compact()).  Returns the released row or None."""
        row = self._release_row(name)
        if row is not None:
            self.version += 1
            self.patch_gen += 1
        return row

    def _update_from_dirty(self, pairs, removed_names) -> list[int]:
        """Incremental sync from a changed-node delta (CacheFlattenView.
        run_locked_dirty): O(changed) instead of O(nodes)."""
        dirty = self._sync_rows(pairs)
        for name in removed_names:
            row = self._release_row(name)
            if row is not None:
                dirty.append(row)
        if dirty:
            self.version += 1
        return dirty

    def _update_from_nodes_tracked(self, node_info_list) -> list[int]:
        dirty = self._sync_rows((ni.name, ni) for ni in node_info_list)
        live = {ni.name for ni in node_info_list}
        for name in list(self.row_of):
            if name not in live:
                row = self._release_row(name)
                if row is not None:
                    dirty.append(row)
        if dirty:
            self.version += 1
        return dirty

    def _encode_dynamic_bulk(self, pairs: list) -> None:
        """Columnar dynamic re-encode for rows whose static side is
        untouched and whose aggregates carry no scalars/ports — five
        column fills instead of ~10 numpy ops per row."""
        rows = np.fromiter((r for r, _ in pairs), np.int64, len(pairs))
        infos = [ni for _, ni in pairs]
        node_infos = self.node_infos
        for (row, ni) in pairs:  # snapshot paths clone NodeInfos per update
            node_infos[row] = ni
        self._ever_used[rows] = True
        self.used[rows, 0] = [ni.requested.milli_cpu for ni in infos]
        self.used[rows, 1] = [ni.requested.memory for ni in infos]
        self.used[rows, 2] = [ni.requested.ephemeral_storage for ni in infos]
        self.used[rows[:, None], np.arange(CORE_R, self.caps.r)[None, :]] = 0.0
        nz = [ni.non_zero_requested for ni in infos]
        self.used_nz[rows, 0] = [r.milli_cpu for r in nz]
        self.used_nz[rows, 1] = [r.memory for r in nz]
        self.used_nz[rows, 2] = [r.ephemeral_storage for r in nz]
        self.used_nz[rows[:, None],
                     np.arange(CORE_R, self.caps.r)[None, :]] = 0.0
        self.npods[rows] = [len(ni.pods) for ni in infos]
        self.port_mask[rows] = 0.0

    def _encode_resource(self, out: np.ndarray, res) -> None:
        out[0] = res.milli_cpu
        out[1] = res.memory
        out[2] = res.ephemeral_storage
        out[CORE_R:] = 0.0
        for name, v in res.scalar.items():
            try:
                out[CORE_R + self.scalar_vocab.get(name)] = v
            except VocabFullError:
                raise

    def _encode_node(self, row: int, ni: NodeInfo) -> None:
        c = self.caps
        node = ni.node
        self.node_infos[row] = ni
        self._ever_used[row] = True
        self._dyn_digest[row] = None  # full encode: bulk digest is stale

        # ---- dynamic fields (change on every bind; cheap to upload) ----
        self._encode_resource(self.used[row], ni.requested)
        self._encode_resource(self.used_nz[row], ni.non_zero_requested)
        self.npods[row] = len(ni.pods)
        self.port_mask[row] = 0.0
        for proto, _ip, port in ni.used_ports:
            self.port_mask[row, self.port_vocab.get((proto, port))] = 1.0
        for sg_idx in range(len(self.sgs)):
            self._encode_sg_row(sg_idx, row, ni)
        for asg_idx in range(len(self.asgs)):
            self._encode_asg_row(asg_idx, row, ni)

        # restart window: a RESIDENT pod can carry a namespaceSelector
        # anti term whose group was never registered in this process —
        # registration happens on the ENCODE path of incoming pods, and
        # after a scheduler restart a bound pod never re-encodes.  Arm
        # the conservative guard for any such term during the first
        # snapshot sync so an incoming pod that could match it defers to
        # the oracle instead of silently violating the unencoded
        # constraint.  (Groups that DO register later keep exact device
        # counts; the guard stays armed — conservative, never wrong.)
        ids, term_key = self._asg_ids, self.term_group_key
        for pi in ni.pods_with_required_anti_affinity:
            for term in pi.required_anti_affinity_terms:
                if term.ns_selector is not None \
                        and term_key(term) not in ids:
                    self.arm_ns_anti_guard(term)

        # ---- static fields (labels/taints/alloc) ----
        # Binds dirty only dynamic fields; NodeInfo.node_generation advances
        # only when the node OBJECT changed, so rows dirtied by pod traffic
        # skip the static rebuild entirely (the dominant case: every batch
        # dirties one row per bound pod).
        if self.valid[row] and self.node_gen[row] == ni.node_generation:
            return
        # compare before write so routine no-op refreshes never bump
        # static_version (a bump forces a multi-MB device re-upload);
        # node_gen is recorded only after every fallible encode below
        # succeeds, so a VocabFullError mid-encode retries next dispatch
        fresh = not self.valid[row]
        if fresh:
            # creation flood fast path (100k nodes register before any pod
            # exists): encode straight into the target rows (zero-filled
            # first — a recycled row holds stale values) instead of
            # building temporaries and diffing them against a row that is
            # invalid anyway
            alloc_new = self.alloc[row]
            alloc_new[:] = 0.0
            taint_new = self.taint_mask[row]
            taint_new[:] = 0.0
            label_new = self.label_mask[row]
            label_new[:] = 0.0
            key_new = self.key_mask[row]
            key_new[:] = 0.0
        else:
            alloc_new = np.zeros(c.r, np.float32)
            taint_new = np.zeros(c.t_cap, np.float32)
            label_new = np.zeros(c.l_cap, np.float32)
            key_new = np.zeros(c.kl_cap, np.float32)
        self._encode_resource(alloc_new, ni.allocatable)
        taints = list((node.get("spec") or {}).get("taints") or ())
        if (node.get("spec") or {}).get("unschedulable"):
            taints.append({"key": UNSCHEDULABLE_TAINT[0],
                           "value": UNSCHEDULABLE_TAINT[1],
                           "effect": UNSCHEDULABLE_TAINT[2]})
        for t in taints:
            tid = self.taint_vocab.get(
                (t.get("key", ""), t.get("value", ""), t.get("effect", "")))
            taint_new[tid] = 1.0
        # labels — vocab ids are created by POD-side references only (a
        # per-node-unique label like kubernetes.io/hostname would otherwise
        # grow the vocab O(N)); node rows just set bits for known ids, and
        # ensure_label_id/ensure_key_id backfill columns when a pod first
        # references a label.
        labels = meta.labels(node)
        for k, v in labels.items():
            lid = self.label_vocab.lookup((k, v))
            if lid is not None:
                label_new[lid] = 1.0
            kid = self.key_vocab.lookup(k)
            if kid is not None:
                key_new[kid] = 1.0

        if fresh:
            self.valid[row] = True
            self.maxpods[row] = ni.allocatable.allowed_pod_number
            self.static_version += 1
            self.static_dirty_rows.add(row)
            self.node_gen[row] = ni.node_generation
            return
        static_changed = (
            self.maxpods[row] != ni.allocatable.allowed_pod_number
            or not np.array_equal(self.alloc[row], alloc_new)
            or not np.array_equal(self.taint_mask[row], taint_new)
            or not np.array_equal(self.label_mask[row], label_new)
            or not np.array_equal(self.key_mask[row], key_new))
        if static_changed:
            self.alloc[row] = alloc_new
            self.maxpods[row] = ni.allocatable.allowed_pod_number
            self.taint_mask[row] = taint_new
            self.label_mask[row] = label_new
            self.key_mask[row] = key_new
            self.static_version += 1
            self.static_dirty_rows.add(row)
        elif self.sgs or self.asgs:
            # a node-object change can move the row's topology domain
            # (dom_sg/dom_asg) without touching any compared array; mark
            # the row so an incremental static upload carries the doms
            self.static_dirty_rows.add(row)
        self.node_gen[row] = ni.node_generation

    def _encode_sg_row(self, sg_idx: int, row: int, ni: NodeInfo) -> None:
        bucket = self.sgs[sg_idx]
        labels = meta.labels(ni.node) if ni.node else {}
        val = labels.get(bucket.topology_key)
        self.dom_sg[sg_idx, row] = (self.domain_id(bucket.topology_key, val)
                                    if val is not None else -1)
        # each pod counts ONCE if it matches ANY member (the same
        # per-pod semantics as encode()'s inc_sg and the mirror replay —
        # per-(pod,group) counting would diverge between full refresh
        # and replay on shared buckets)
        self.cnt_sg[sg_idx, row] = sum(
            1 for pi in ni.pods
            if not meta.deletion_timestamp(pi.pod)
            and bucket.matches_pod(pi))

    def _encode_asg_row(self, asg_idx: int, row: int, ni: NodeInfo) -> None:
        bucket = self.asgs[asg_idx]
        labels = meta.labels(ni.node) if ni.node else {}
        val = labels.get(bucket.topology_key)
        self.dom_asg[asg_idx, row] = (self.domain_id(bucket.topology_key,
                                                     val)
                                      if val is not None else -1)
        # pods on this node carrying an anti-affinity term == any member
        # (namespaceSelector terms compare by their RESOLVED group key)
        ids = self._asg_ids
        term_key = self.term_group_key
        n = 0
        for pi in ni.pods_with_required_anti_affinity:
            for term in pi.required_anti_affinity_terms:
                if ids.get(term_key(term)) == asg_idx:
                    n += 1
        self.cnt_asg[asg_idx, row] = n

    def arm_ns_anti_guard(self, term) -> None:
        """Record one namespaceSelector ANTI term in the conservative
        guard (ns_anti_kv/ns_anti_complex, see __init__): later pods
        whose labels could match the selector escape to the oracle, so
        a device placement can never violate the unencoded term."""
        kv = _exact_kv(SelectorGroup("", term.selector, frozenset()))
        if kv is not None:
            self.ns_anti_kv.add(kv)
        else:
            self.ns_anti_complex = True

    # -- per-batch domain base counts ------------------------------------

    def domain_base_counts(self) -> tuple[np.ndarray, np.ndarray]:
        """cd0_sg[SG, n_cap], cd0_asg[ASG, n_cap]: per-domain counts
        (domain ids index into the n_cap-sized axis; counts of matching pods
        aggregated from per-node counts via bincount)."""
        c = self.caps
        cd_sg = np.zeros((c.sg_cap, c.n_cap), np.float32)
        for i in range(len(self.sgs)):
            m = self.valid & (self.dom_sg[i] >= 0)
            if m.any():
                bc = np.bincount(self.dom_sg[i][m], weights=self.cnt_sg[i][m],
                                 minlength=c.n_cap)
                cd_sg[i] = bc[:c.n_cap]
        cd_asg = np.zeros((c.asg_cap, c.n_cap), np.float32)
        for i in range(len(self.asgs)):
            m = self.valid & (self.dom_asg[i] >= 0)
            if m.any():
                bc = np.bincount(self.dom_asg[i][m], weights=self.cnt_asg[i][m],
                                 minlength=c.n_cap)
            else:
                bc = np.zeros(c.n_cap, np.float32)
            cd_asg[i] = bc[:c.n_cap]
        return cd_sg, cd_asg

    def node_name(self, row: int) -> str | None:
        ni = self.node_infos[row]
        return ni.name if ni is not None else None

    # -- victim tensors (batched preemption) ------------------------------

    def refresh_victims(self) -> list[int] | None:
        """Re-encode victim rows marked dirty since the last refresh (plus
        ALL rows when the PDB cache changed — coverage bits are global).
        Called at preempt time, not on the bind hot path.  Returns the
        re-encoded rows (patch candidates), None when nothing changed."""
        if self._vict_pdb_version != self.pdb_version:
            # PDB change flips coverage bits on any row; re-encode all
            # live rows and force a full upload
            self.vict_dirty_rows.update(
                row for row, ni in enumerate(self.node_infos)
                if ni is not None)
            self._vict_pdb_version = self.pdb_version
            self.vict_full = True
        if not self.vict_dirty_rows:
            return None
        blocking = self.pdb_blocking()
        rows = sorted(self.vict_dirty_rows)
        for row in rows:
            self._encode_vict_row(row, blocking)
        self.vict_dirty_rows.clear()
        self.vict_version += 1
        return rows

    def _encode_vict_row(self, row: int, blocking: list) -> None:
        """Victim slots for one node row: ALL resident pods (terminating
        included — the Evaluator's `potential` list keeps them; eligibility
        gating happens host-side), stable-sorted ascending by priority to
        mirror `sorted(ni.pods)` order under the reprieve lexsort.  Rows
        holding more than v_cap lower-priority candidates overflow: the
        device answer would be built from a truncated victim set, so the
        row sets vict_over and any preemptor that can reach it escapes
        with reason victim_overflow."""
        c = self.caps
        ni = self.node_infos[row]
        self.vict_prio[row] = VICT_PAD
        self.vict_req[row] = 0.0
        self.vict_pdb[row] = 0.0
        self.vict_over[row] = False
        if ni is None or not self.valid[row]:
            self.vict_keys[row] = None
            return
        pods = ni.pods
        order = sorted(range(len(pods)), key=lambda j: pods[j].priority)
        if len(order) > c.v_cap:
            self.vict_over[row] = True
            order = order[:c.v_cap]
        keys = []
        for slot, j in enumerate(order):
            pi = pods[j]
            # clamp keeps negation safe in the kernel's lexsort (int32
            # -(-2^31) wraps) and PAD strictly above any real priority
            self.vict_prio[row, slot] = np.int32(
                min(max(pi.priority, -(2**31) + 2), 2**31 - 2))
            try:
                self._encode_resource(self.vict_req[row, slot], pi.request)
            except VocabFullError:
                # a victim whose scalars can't be represented would free
                # resources the kernel can't see; conservative overflow
                self.vict_over[row] = True
            if blocking:
                labels = meta.labels(pi.pod)
                if any(sel.matches(labels) for _, sel in blocking):
                    self.vict_pdb[row, slot] = 1.0
            keys.append(pi.key)
        self.vict_keys[row] = keys

    def victim_occupancy(self) -> float:
        """Fraction of victim slots in use across live rows (gauge feed)."""
        live = self.valid
        if not live.any():
            return 0.0
        used = (self.vict_prio[live] != VICT_PAD).sum()
        return float(used) / float(live.sum() * self.caps.v_cap)


def untolerated_hard(t: ClusterTensors, pi: PodInfo) -> np.ndarray:
    """[t_cap] hard-untolerated taint vector for one pod — the standalone
    twin of BatchEncoder._encode_taints' untol_hard section, for callers
    without a PodBatch (the batched preemption path)."""
    out = np.zeros(t.caps.t_cap, np.float32)
    for tid, (key, value, effect) in enumerate(t.taint_vocab.items):
        if effect not in ("NoSchedule", "NoExecute"):
            continue
        taint = {"key": key, "value": value, "effect": effect}
        if not any(toleration_tolerates_taint(tol, taint)
                   for tol in pi.tolerations):
            out[tid] = 1.0
    return out


@dataclass
class PodBatch:
    """Encoded pod-side tensors for one batch (P = p_cap, padded).

    Constraint-side fields are LAZY: None means "all zeros / -1" (the
    field was never touched by any pod in the batch).  A 16k-pod plain
    batch otherwise allocates ~100 MB of dense zeros per dispatch —
    sel_any alone is [P, G, L] f32 — which measured as the single
    biggest host cost of the batch path.  `ensure()` materializes a
    field on first write; consumers treat None as zeros (pack: plain
    spec never reads them; full spec materializes; _needs_full /
    _replay: None-aware)."""

    p_cap: int
    req: np.ndarray            # f32[P, R]
    req_nz: np.ndarray         # f32[P, R]  (non-zero defaults, for scoring)
    p_valid: np.ndarray        # bool[P]
    untol_hard: np.ndarray     # f32[P, T]  1 = taint t blocks this pod
    untol_prefer: np.ndarray = None   # f32[P, T]  PreferNoSchedule not tolerated
    sel_any: np.ndarray = None        # f32[P, G, L] any-of label groups
    sel_any_active: np.ndarray = None  # f32[P, G]
    sel_forb: np.ndarray = None       # f32[P, L]  forbidden label ids (NotIn)
    key_any: np.ndarray = None        # f32[P, KG, KL] Exists groups
    key_any_active: np.ndarray = None  # f32[P, KG]
    key_forb: np.ndarray = None       # f32[P, KL] DoesNotExist
    ports: np.ndarray = None          # f32[P, PT] requested host ports
    node_row: np.ndarray = None       # i32[P] pinned node row or -1 (None = all -1)
    c_kind: np.ndarray = None         # i32[P, C]
    c_sg: np.ndarray = None           # i32[P, C] selector-group index
    c_maxskew: np.ndarray = None      # f32[P, C]
    c_selfmatch: np.ndarray = None    # f32[P, C]
    c_weight: np.ndarray = None       # f32[P, C] (preferred terms; signed)
    inc_sg: np.ndarray = None         # f32[P, SG]  assigning pod bumps sg counts
    inc_asg: np.ndarray = None        # f32[P, ASG] pod carries this anti group
    match_asg: np.ndarray = None      # f32[P, ASG] pod labels match anti group
    pod_ns: np.ndarray = None         # i32[P] namespace vocab id (ns_cap=unknown)
    # id-based duals of the dense selector arrays (for packed transport;
    # -1 padded; see models/assign.PackSpec)
    sel_ids: np.ndarray = None        # i32[P, G, 8]
    sel_forb_ids: np.ndarray = None   # i32[P, 8]
    key_ids: np.ndarray = None        # i32[P, KG, 4]
    escape: list[int] = field(default_factory=list)  # batch positions for oracle path
    # position -> (plugin, reason) for every escape above: WHICH
    # constraint term forced the pod off the device path (feeds
    # scheduler_tpu_escape_total and the batch span attributes)
    escape_reasons: dict = field(default_factory=dict)
    # positions whose constraints touch a COLLIDED bucket (shared sg/asg
    # slot): a no-fit verdict for these is an upper-bound artifact, not
    # proof — the scheduler re-proves them on the per-pod oracle instead
    # of declaring unschedulable
    nofit_oracle: list[int] = field(default_factory=list)

    _SHAPES = None  # caps-dependent; filled by shapes()

    def shapes(self, caps: "Caps") -> dict:
        c, P = caps, self.p_cap
        return {
            "untol_prefer": ((P, c.t_cap), np.float32, 0.0),
            "sel_any": ((P, c.g_cap, c.l_cap), np.float32, 0.0),
            "sel_any_active": ((P, c.g_cap), np.float32, 0.0),
            "sel_forb": ((P, c.l_cap), np.float32, 0.0),
            "key_any": ((P, c.kg_cap, c.kl_cap), np.float32, 0.0),
            "key_any_active": ((P, c.kg_cap), np.float32, 0.0),
            "key_forb": ((P, c.kl_cap), np.float32, 0.0),
            "ports": ((P, c.pt_cap), np.float32, 0.0),
            "node_row": ((P,), np.int32, -1),
            "c_kind": ((P, c.c_cap), np.int32, 0),
            "c_sg": ((P, c.c_cap), np.int32, -1),
            "c_maxskew": ((P, c.c_cap), np.float32, 0.0),
            "c_selfmatch": ((P, c.c_cap), np.float32, 0.0),
            "c_weight": ((P, c.c_cap), np.float32, 0.0),
            "inc_sg": ((P, c.sg_cap), np.float32, 0.0),
            "inc_asg": ((P, c.asg_cap), np.float32, 0.0),
            "match_asg": ((P, c.asg_cap), np.float32, 0.0),
            "pod_ns": ((P,), np.int32, c.ns_cap),
            "sel_ids": ((P, c.g_cap, 8), np.int32, -1),
            "sel_forb_ids": ((P, 8), np.int32, -1),
            "key_ids": ((P, c.kg_cap, 4), np.int32, -1),
        }

    def ensure(self, caps: "Caps", name: str) -> np.ndarray:
        """Materialize a lazy field (None -> its zero/-1-filled array)."""
        arr = getattr(self, name)
        if arr is None:
            shape, dtype, fill = self.shapes(caps)[name]
            arr = (np.zeros(shape, dtype) if fill == 0.0
                   else np.full(shape, fill, dtype))
            setattr(self, name, arr)
        return arr

    def materialized(self, caps: "Caps", keys) -> dict:
        """Dense arrays for `keys` — the ONE place consumers that need
        every field (mesh upload, dryrun, tests) densify a batch."""
        return {k: self.ensure(caps, k) for k in keys}


def slice_pod_batch(batch: "PodBatch", lo: int, hi: int,
                    p_cap: int) -> "PodBatch":
    """Rows [lo, hi) of a PodBatch re-padded to p_cap — the chunking
    primitive for running an oversized batch through a kernel compiled at
    a smaller P (the constraint-carrying variant's HBM cap at large
    n_cap).  The contiguous special case of gather_pod_batch."""
    return gather_pod_batch(batch, range(lo, hi), p_cap)


def gather_pod_batch(batch: "PodBatch", idx, p_cap: int) -> "PodBatch":
    """Rows `idx` of a PodBatch re-padded to p_cap.  Two callers: the
    chunking path (contiguous `range`, one view-copy per field — the
    hot path for oversized constraint batches) and the straggler retry
    (scattered positions, fancy indexing)."""
    import dataclasses
    n = len(idx)
    contiguous = isinstance(idx, range) and idx.step == 1
    ix = None if contiguous else np.asarray(idx, np.int64)
    fields = {}
    for f in dataclasses.fields(PodBatch):
        if f.name in ("p_cap", "escape", "escape_reasons", "nofit_oracle"):
            continue
        arr = getattr(batch, f.name)
        if arr is None:
            fields[f.name] = None
            continue
        out = np.zeros((p_cap,) + arr.shape[1:], arr.dtype)
        if contiguous:
            out[:n] = arr[idx.start:idx.stop]
        else:
            out[:n] = arr[ix]
        fields[f.name] = out
    if fields.get("node_row") is not None:
        fields["node_row"][n:] = -1
    if contiguous:
        lo, hi = idx.start, idx.stop
        fields["escape"] = [e - lo for e in batch.escape if lo <= e < hi]
        fields["escape_reasons"] = {e - lo: r for e, r
                                    in batch.escape_reasons.items()
                                    if lo <= e < hi}
        fields["nofit_oracle"] = [e - lo for e in batch.nofit_oracle
                                  if lo <= e < hi]
    else:
        pos = {orig: j for j, orig in enumerate(idx)}
        fields["escape"] = [pos[e] for e in batch.escape if e in pos]
        fields["escape_reasons"] = {pos[e]: r for e, r
                                    in batch.escape_reasons.items()
                                    if e in pos}
        fields["nofit_oracle"] = [pos[e] for e in batch.nofit_oracle
                                  if e in pos]
    return PodBatch(p_cap=p_cap, **fields)


class BatchEncoder:
    """Encodes a list of PodInfos against a ClusterTensors instance."""

    def __init__(self, tensors: ClusterTensors, p_cap: int):
        self.t = tensors
        self.p_cap = p_cap
        # (plugin, reason) for the pod currently failing _encode_pod —
        # read by encode() when it routes the pod to the escape list
        self._escape_reason: tuple | None = None

    def _esc(self, plugin: str, reason: str) -> bool:
        """Record why the in-flight pod can't be tensor-encoded and
        return False (the _encode_pod escape convention)."""
        self._escape_reason = (plugin, reason)
        return False

    def encode(self, pod_infos: list[PodInfo]) -> PodBatch:
        t, c = self.t, self.t.caps
        P = self.p_cap
        b = PodBatch(
            p_cap=P,
            req=np.zeros((P, c.r), np.float32),
            req_nz=np.zeros((P, c.r), np.float32),
            p_valid=np.zeros(P, bool),
            untol_hard=np.zeros((P, c.t_cap), np.float32),
        )
        pods = pod_infos[:P]
        n = len(pods)
        if n:
            # request vectors column-wise in bulk (the rows are fresh
            # zeros, so only the core columns + rare scalars need writes;
            # a per-pod _encode_resource pair cost ~3µs/pod); one native
            # pass when built (utils/fasthost), list-comp columns otherwise
            req_columns(pods if isinstance(pods, list) else list(pods),
                        b.req, b.req_nz)
        # plain fast path: a pod with no selectors/affinity/constraints/
        # ports/pins/scalars needs NO per-field writes beyond the bulk
        # request columns above — p_valid plus (when the taint vocab is
        # non-empty and the pod carries no tolerations) one precomputed
        # untolerated row.  This skips _encode_pod entirely for the
        # dominant workload shape (~10µs/pod at bench scale).
        taint_items = t.taint_vocab.items
        if taint_items:
            base_hard = np.zeros(c.t_cap, np.float32)
            base_prefer = np.zeros(c.t_cap, np.float32)
            for tid, (_k, _v, effect) in enumerate(taint_items):
                if effect in ("NoSchedule", "NoExecute"):
                    base_hard[tid] = 1.0
                elif effect == "PreferNoSchedule":
                    base_prefer[tid] = 1.0
            any_prefer = bool(base_prefer.any())
        is_plain = self._is_plain
        # ns-anti guard: once armed (a namespaceSelector anti-affinity
        # term could not REGISTER — asg bucket overflow), any pod whose
        # labels could match one of those selectors must take the
        # oracle too — zero cost while unarmed, which is now the normal
        # state (registered ns terms are covered by resolved groups +
        # namespace masks, not the guard).  Arming can happen MID-batch
        # (the arming pod's _encode_pod runs inside this loop): the
        # post-loop re-scan below retroactively escapes earlier
        # same-batch pods the live guard missed.
        guard_n0 = len(t.ns_anti_kv) + int(t.ns_anti_complex)
        guard_kv = t.ns_anti_kv if guard_n0 else None
        guard_all = t.ns_anti_complex
        for i, pi in enumerate(pods):
            if guard_kv is not None and (
                    guard_all
                    or any(kv in guard_kv for kv in pi.labels.items())):
                b.escape.append(i)
                b.escape_reasons[i] = ("InterPodAffinity",
                                       "ns_anti_guard")
                continue
            if is_plain(pi):
                b.p_valid[i] = True
                if taint_items and not pi.tolerations:
                    b.untol_hard[i] = base_hard
                    if any_prefer:
                        b.ensure(c, "untol_prefer")[i] = base_prefer
                    continue
                elif not taint_items:
                    continue
                # plain pod WITH tolerations vs a live taint vocab:
                # only the taint section of the slow path applies
                self._encode_taints(b, i, pi)
                continue
            try:
                self._escape_reason = None
                ok = self._encode_pod(b, i, pi)
            except VocabFullError as e:
                ok = False
                self._escape_reason = (
                    "BatchEncoder",
                    "constraint_capacity" if "constraint" in str(e)
                    else "vocab_full")
            if ok:
                b.p_valid[i] = True
            else:
                b.escape.append(i)
                b.escape_reasons[i] = (self._escape_reason
                                       or ("BatchEncoder", "unencodable"))
        if len(t.ns_anti_kv) + int(t.ns_anti_complex) != guard_n0:
            # the guard armed during THIS encode: retroactively escape
            # earlier pods in the batch that the live check missed
            esc = set(b.escape)
            for i, pi in enumerate(pods):
                if i in esc or not b.p_valid[i]:
                    continue
                if t.ns_anti_complex or any(
                        kv in t.ns_anti_kv for kv in pi.labels.items()):
                    b.p_valid[i] = False
                    b.escape.append(i)
                    b.escape_reasons[i] = ("InterPodAffinity",
                                           "ns_anti_guard")
        # cross-pod: inc/match rows vs the registered groups — via the
        # exact-kv index (O(pod labels)) + the short complex-selector
        # scan, so 2000 per-service groups don't cost 2000 matches/pod
        if t.sgs or t.asgs:
            inc_sg = b.ensure(c, "inc_sg") if t.sgs else None
            match_asg = b.ensure(c, "match_asg") if t.asgs else None
            inc_asg = b.ensure(c, "inc_asg") if t.asgs else None
            kvi_sg, cx_sg = t._sg_kv_index, t._sg_complex
            kvi_asg, cx_asg = t._asg_kv_index, t._asg_complex
            asg_ids = t._asg_ids
            term_key = t.term_group_key
            # per-pod namespace ids for the device masks — only when a
            # namespaceSelector group has interned namespaces (plain
            # workloads leave the vocab empty and pod_ns lazy, so
            # batches without such terms pay nothing)
            pod_ns = b.ensure(c, "pod_ns") if len(t.ns_vocab) else None
            ns_lookup = t.ns_vocab.lookup
            for i, pi in enumerate(pods):
                if pod_ns is not None:
                    nid = ns_lookup(meta.namespace(pi.pod))
                    if nid is not None:
                        pod_ns[i] = nid
                if not b.p_valid[i]:
                    continue
                if inc_sg is not None:
                    for kv in pi.labels.items():
                        for idx, g in kvi_sg.get(kv, ()):
                            if g.matches_pod(pi):
                                inc_sg[i, idx] = 1.0
                    for idx, g in cx_sg:
                        if g.matches_pod(pi):
                            inc_sg[i, idx] = 1.0
                if match_asg is not None:
                    for kv in pi.labels.items():
                        for idx, g in kvi_asg.get(kv, ()):
                            if g.matches_pod(pi):
                                match_asg[i, idx] = 1.0
                    for idx, g in cx_asg:
                        if g.matches_pod(pi):
                            match_asg[i, idx] = 1.0
                    for term in pi.required_anti_affinity_terms:
                        idx = asg_ids.get(term_key(term))
                        if idx is not None:
                            inc_asg[i, idx] += 1.0
        # collided-bucket post-pass (AFTER all registrations, so buckets
        # that became shared mid-batch are seen): any pod whose
        # constraints reference a shared slot — or that matches a shared
        # anti-affinity bucket — gets the no-fit-means-oracle marker,
        # because its device verdict rides upper-bound counts
        col_sg = [i for i, bk in enumerate(t.sgs) if bk.collided]
        col_asg = [i for i, bk in enumerate(t.asgs) if bk.collided]
        if col_sg or col_asg:
            flagged = np.zeros(P, bool)
            if col_sg and b.c_sg is not None:
                # only HARD blocker constraints can turn an inflated
                # count into a false no-fit; preferred/score slots on a
                # shared bucket distort a score, never feasibility
                hard = b.c_kind == C_ANTI_AFFINITY
                flagged |= (np.isin(b.c_sg, col_sg) & hard).any(axis=1)
            if col_asg and b.match_asg is not None:
                flagged |= (b.match_asg[:, col_asg] > 0).any(axis=1)
            flagged &= b.p_valid
            b.nofit_oracle.extend(np.nonzero(flagged)[0].tolist())
        return b

    @staticmethod
    def _is_plain(pi: PodInfo) -> bool:
        """True when the pod touches none of the constraint-side fields.
        Precomputed by PodInfo.update (types.py) where every input is
        already in hand — PodInfo.plain's checks mirror _encode_pod's
        write sites exactly, so a plain=True pod can never diverge from
        what the fast path assumes."""
        return pi.plain

    def _encode_taints(self, b: PodBatch, i: int, pi: PodInfo) -> None:
        """Taint section of the pod encode (shared by slow path and the
        plain-with-tolerations case): mark every vocab taint this pod
        does NOT tolerate."""
        t, c = self.t, self.t.caps
        for tid, (key, value, effect) in enumerate(t.taint_vocab.items):
            taint = {"key": key, "value": value, "effect": effect}
            tolerated = any(toleration_tolerates_taint(tol, taint)
                            for tol in pi.tolerations)
            if not tolerated:
                if effect in ("NoSchedule", "NoExecute"):
                    b.untol_hard[i, tid] = 1.0
                elif effect == "PreferNoSchedule":
                    b.ensure(c, "untol_prefer")[i, tid] = 1.0

    @staticmethod
    def _push_id(arr: np.ndarray, i: int, lid: int) -> bool:
        """Append lid into the -1-padded id row arr[i]; False if full."""
        row = arr[i]
        for v in range(row.shape[0]):
            if row[v] < 0:
                row[v] = lid
                return True
        return False

    def _arm_ns_anti_guard(self, term) -> None:
        """Record one namespaceSelector ANTI term in the conservative
        guard — the fallback for terms whose group could NOT register
        (asg bucket overflow).  Delegates to the tensors' own arming
        path (also used by the restart-window resident scan)."""
        self.t.arm_ns_anti_guard(term)

    def _cover_ns_anti_terms(self, pi: PodInfo) -> None:
        """Pre-register the resolved ANTI groups of a namespaceSelector
        pod — called before any escape path, so even if the pod escapes
        (nominated node, volumes, overflow), its anti constraint is
        still enforced on the device path once the oracle binds it (the
        bound pod's terms count into cnt_asg via the resolved term
        key).  Only registration failure arms the conservative guard."""
        t = self.t
        for term in pi.required_anti_affinity_terms:
            if term.ns_selector is None:
                continue
            sg = t.group_for_term(term)
            t.intern_namespaces(sg.namespaces)  # mask falls back all-ones
            if t.register_asg(sg) is None:
                self._arm_ns_anti_guard(term)

    # returns False -> escape to oracle path
    def _encode_pod(self, b: PodBatch, i: int, pi: PodInfo) -> bool:
        t, c = self.t, self.t.caps
        if pi.has_ns_selector_terms:
            # namespaceSelector terms resolve to concrete namespace sets
            # against the cached Namespace labels and encode like any
            # other term; the pre-pass keeps anti terms enforced on every
            # escape route out of this function
            self._cover_ns_anti_terms(pi)
        if pi.nominated_node_name:
            # nominated-first fast path (the reference tries the nominated
            # node before the full list): pin the pod to its nominated row
            # and let the device prove the fit.  No-fit is NOT proof of
            # unschedulability — victims may still be terminating — so the
            # position also rides nofit_oracle: a no-fit verdict yields
            # SKIP and the per-pod oracle re-evaluates against the full
            # node list, exactly today's semantics.  Only a nomination
            # whose node left the cluster escapes outright (a genuine
            # re-evaluation, distinct reason in scheduler_tpu_escape_total).
            row = t.row_of.get(pi.nominated_node_name)
            if row is None or not t.valid[row]:
                return self._esc("DefaultPreemption", "nominated_node_stale")
            b.ensure(c, "node_row")[i] = row
            b.nofit_oracle.append(i)
            b.escape_reasons[i] = ("DefaultPreemption", "nominated_node_stale")
        for v in (pi.pod.get("spec") or {}).get("volumes") or ():
            if (v.get("persistentVolumeClaim") or v.get("gcePersistentDisk")
                    or v.get("awsElasticBlockStore") or v.get("azureDisk")
                    or v.get("iscsi") or v.get("csi")):
                # volume binding/zones/limits are deeply stateful (PVC/PV/
                # StorageClass lookups + API writes at PreBind): oracle path
                return self._esc("VolumeBinding", "stateful_volume")
        # (core request columns were filled column-wise in encode();
        # scalar resources are rare enough to stay per-pod — and their
        # VocabFullError must route this pod to the escape path)
        if pi.request.scalar:
            for name, v in pi.request.scalar.items():
                b.req[i, CORE_R + t.scalar_vocab.get(name)] = v
        if pi.request_nonzero.scalar:
            for name, v in pi.request_nonzero.scalar.items():
                b.req_nz[i, CORE_R + t.scalar_vocab.get(name)] = v

        # taints: mark every vocab taint this pod does NOT tolerate
        self._encode_taints(b, i, pi)

        # spec.nodeName pin
        want = (pi.pod.get("spec") or {}).get("nodeName")
        if want:
            row = t.row_of.get(want)
            if row is None:
                return self._esc("NodeName", "unknown_node")
            b.ensure(c, "node_row")[i] = row

        # node selector + required node affinity -> any-of groups / forbidden
        groups: list[list[int]] = []
        key_groups: list[list[int]] = []
        for k, v in pi.node_selector.items():
            groups.append([t.ensure_label_id((k, v))])
        if pi.node_affinity_required:
            enc = self._encode_affinity_terms(pi.node_affinity_required,
                                              groups, key_groups, b, i)
            if not enc:
                return False
        if len(groups) > c.g_cap or len(key_groups) > c.kg_cap:
            return self._esc("NodeAffinity", "group_overflow")
        if groups:
            sel_ids = b.ensure(c, "sel_ids")
            sel_any_active = b.ensure(c, "sel_any_active")
            sel_any = b.ensure(c, "sel_any")
            for g, ids in enumerate(groups):
                if len(ids) > sel_ids.shape[2]:
                    # any-of group too wide for packed transport
                    return self._esc("NodeAffinity", "group_overflow")
                sel_any_active[i, g] = 1.0
                for v, lid in enumerate(ids):
                    sel_any[i, g, lid] = 1.0
                    sel_ids[i, g, v] = lid
        if key_groups:
            key_ids = b.ensure(c, "key_ids")
            key_any_active = b.ensure(c, "key_any_active")
            key_any = b.ensure(c, "key_any")
            for g, ids in enumerate(key_groups):
                if len(ids) > key_ids.shape[2]:
                    return self._esc("NodeAffinity", "group_overflow")
                key_any_active[i, g] = 1.0
                for v, kid in enumerate(ids):
                    key_any[i, g, kid] = 1.0
                    key_ids[i, g, v] = kid
        if pi.node_affinity_preferred:
            # node-affinity scoring: oracle path (rare)
            return self._esc("NodeAffinity", "preferred_terms")

        # host ports
        if pi.host_ports:
            ports = b.ensure(c, "ports")
            for proto, ip, port in pi.host_ports:
                if ip not in ("0.0.0.0", "", None):
                    # per-IP port semantics: oracle path
                    return self._esc("NodePorts", "host_port_ip")
                ports[i, t.port_vocab.get((proto, port))] = 1.0

        # constraints
        ci = 0

        def add_constraint(kind, sg_idx, maxskew=0.0, selfmatch=0.0, weight=0.0):
            nonlocal ci
            if ci >= c.c_cap or sg_idx is None:
                raise VocabFullError("constraint capacity")
            b.ensure(c, "c_kind")[i, ci] = kind
            b.ensure(c, "c_sg")[i, ci] = sg_idx
            b.ensure(c, "c_maxskew")[i, ci] = maxskew
            b.ensure(c, "c_selfmatch")[i, ci] = selfmatch
            b.ensure(c, "c_weight")[i, ci] = weight
            ci += 1

        ns = meta.namespace(pi.pod)
        for tsc in pi.topology_spread_constraints:
            sel = selector_from_dict(tsc.get("labelSelector"))
            sg = SelectorGroup(tsc["topologyKey"], sel, frozenset([ns]))
            kind = (C_SPREAD_HARD
                    if tsc.get("whenUnsatisfiable", "DoNotSchedule") == "DoNotSchedule"
                    else C_SPREAD_SCORE)
            # DoNotSchedule treats counts as enablers of admission
            # (skew vs min) -> exclusive slot; ScheduleAnyway is
            # scoring-only -> shareable
            add_constraint(kind, t.register_sg(
                sg, shareable=kind == C_SPREAD_SCORE),
                maxskew=tsc.get("maxSkew", 1),
                selfmatch=1.0 if sel.matches(pi.labels) else 0.0)
        for term in pi.required_affinity_terms:
            sg = t.group_for_term(term)
            if (term.ns_selector is not None
                    and not t.intern_namespaces(sg.namespaces)):
                return self._esc("InterPodAffinity",
                                 "namespace_vocab_overflow")
            # counts ENABLE here (gathered>0 satisfies): exclusive only
            add_constraint(C_AFFINITY, t.register_sg(sg),
                           selfmatch=1.0 if sg.matches_pod(pi) else 0.0)
        for term in pi.required_anti_affinity_terms:
            sg = t.group_for_term(term)
            if (term.ns_selector is not None
                    and not t.intern_namespaces(sg.namespaces)):
                # the group itself registered in _cover_ns_anti_terms
                # (exact host counts, all-ones mask); only the encoding
                # pod takes the oracle
                return self._esc("InterPodAffinity",
                                 "namespace_vocab_overflow")
            # counts BLOCK here: sharing is sound (upper bounds)
            add_constraint(C_ANTI_AFFINITY, t.register_sg(sg,
                                                          shareable=True))
            if t.register_asg(sg) is None:
                if term.ns_selector is not None:
                    self._arm_ns_anti_guard(term)
                return self._esc("InterPodAffinity", "anti_group_overflow")
        for term in pi.preferred_affinity_terms:
            sg = t.group_for_term(term)
            if (term.ns_selector is not None
                    and not t.intern_namespaces(sg.namespaces)):
                return self._esc("InterPodAffinity",
                                 "namespace_vocab_overflow")
            # scoring only: inflation distorts a score, never legality
            add_constraint(C_PREF_AFFINITY,
                           t.register_sg(sg, shareable=True),
                           weight=float(term.weight))
        for term in pi.preferred_anti_affinity_terms:
            sg = t.group_for_term(term)
            if (term.ns_selector is not None
                    and not t.intern_namespaces(sg.namespaces)):
                return self._esc("InterPodAffinity",
                                 "namespace_vocab_overflow")
            add_constraint(C_PREF_AFFINITY,
                           t.register_sg(sg, shareable=True),
                           weight=-float(term.weight))
        return True

    def _encode_affinity_terms(self, terms, groups, key_groups, b, i) -> bool:
        """Required node-affinity terms (OR over terms, AND within).

        Encodable cases:
          - single term: each requirement becomes its own group
          - multiple terms, each a single positive requirement: union group
        """
        t = self.t
        if any(fields.requirements for _, fields in terms):
            # matchFields (metadata.name): oracle path
            return self._esc("NodeAffinity", "match_fields")
        if len(terms) == 1:
            lab, fields = terms[0]
            for req in lab.requirements:
                if req.operator == IN:
                    groups.append([t.ensure_label_id((req.key, v))
                                   for v in req.values])
                elif req.operator == EXISTS:
                    key_groups.append([t.ensure_key_id(req.key)])
                elif req.operator == NOT_IN:
                    for v in req.values:
                        lid = t.ensure_label_id((req.key, v))
                        b.ensure(t.caps, "sel_forb")[i, lid] = 1.0
                        if not self._push_id(b.ensure(t.caps, "sel_forb_ids"),
                                             i, lid):
                            return self._esc("NodeAffinity",
                                             "not_in_overflow")
                elif req.operator == DOES_NOT_EXIST:
                    # key_forb travels as a dense bitmask; no id list needed
                    b.ensure(t.caps, "key_forb")[
                        i, t.ensure_key_id(req.key)] = 1.0
                else:  # Gt/Lt
                    return self._esc("NodeAffinity", "gt_lt_operator")
            return True
        union: list[int] = []
        for lab, fields in terms:
            reqs = lab.requirements
            if len(reqs) != 1 or reqs[0].operator != IN:
                return self._esc("NodeAffinity", "multi_term")
            for v in reqs[0].values:
                union.append(t.ensure_label_id((reqs[0].key, v)))
        groups.append(union)
        return True
