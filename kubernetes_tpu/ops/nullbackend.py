"""NullBatchBackend — the host pipeline with the device step nulled.

Measurement tool (NOT a production backend): runs the ENTIRE host side
of the batch path — store -> watch -> informer -> queue -> encode ->
assume -> bulk bind — with the device kernel replaced by an instant
vectorized capacity fill.  Every millisecond on the clock is host work,
which makes this the reproducible source for:

  * LATENCY.md's host-only latency rows (the direct-attached projection
    subtracts the tunnel by measuring exactly this configuration);
  * the host-throughput ceiling (the single-interpreter wall the
    100k-tier numbers hit; VERDICT r4 item #1) and any improvement to
    it (native helpers, multi-process host) in isolation from tunnel
    weather;
  * cProfile runs locating where host µs/pod goes.

Scope: PLAIN pods only (no selectors/affinity/constraints/ports/pins).
Anything else escapes to the per-pod oracle with SKIP — the null
"device" has no constraint solver, and silently placing constraint
pods by capacity alone would produce placements the real kernel would
never emit.  supports_pipelining=False: with an instant device there is
no flight to overlap, and the flush-before-dispatch ordering means each
dispatch's re-encode sees the previous batch's assumed claims (the
sync-path contract in scheduler.BatchBackend).

Reference analog: scheduler_perf's null-kubelet shape (hollow nodes,
test/integration/scheduler_perf/util.go:79) — the harness isolates the
control loop from the execution substrate the same way.
"""

from __future__ import annotations

import threading
import time
from typing import Sequence

import numpy as np

from ..scheduler.scheduler import BatchBackend
from ..scheduler.types import PodInfo, Status
from .backend import decode_results, record_batch_stats
from .flatten import BatchEncoder, Caps, ClusterTensors, VocabFullError

SKIP_MSG = "null backend: constraint pod -> per-pod oracle"


class FlightDelayBackend:
    """Measurement wrapper (NOT a production backend): pins the device
    flight of every wave to a minimum wall duration.

    ``dispatch`` starts the flight clock; the returned resolve blocks
    (GIL released) until ``flight_s`` has elapsed since dispatch, then
    resolves the inner wave.  This models what a real accelerator is
    from the host's perspective — a step that takes wall time but ~zero
    host CPU — which a CPU-simulated device on a single-core box cannot
    exhibit (host and "device" compete for the same core, so pipeline
    overlap is physically impossible no matter what the scheduler
    does).  `bench.py --pipeline-ab` uses this arm
    (``BENCH_PIPELINE_FLIGHT_MS``) to measure the wave pipeline's
    overlap in isolation from that box artifact: at depth 2 the flight
    of wave N+1 runs concurrently with wave N's resolve wait and the
    host leg forming wave N+2, so steady-state wall per wave drops from
    ``host + flight`` toward ``max(host, flight)``.

    The wait happens INSIDE the wrapped resolve, before the inner
    resolve's device pull, so the backend's own timeline record
    (``device-step``: launch -> results landed) attributes the flight
    to device time — idle-share and overlap metrics read the same as
    they would with a genuinely slow device."""

    def __init__(self, inner, flight_s: float):
        self.inner = inner
        self.flight_s = float(flight_s)

    def dispatch(self, pod_infos, snapshot):
        inner_resolve = self.inner.dispatch(pod_infos, snapshot)
        if not callable(inner_resolve):  # pass-through sentinel / results
            return inner_resolve
        t_dispatch = time.monotonic()

        def resolve():
            remaining = self.flight_s - (time.monotonic() - t_dispatch)
            if remaining > 0:
                time.sleep(remaining)
            return inner_resolve()

        return resolve

    def __getattr__(self, name):
        # warmup/assign/health/prefetch/abandon_wave/stats/tensors/
        # supports_pipelining all forward untouched
        return getattr(self.inner, name)


class NullBatchBackend(BatchBackend):
    supports_pipelining = False

    def __init__(self, caps: Caps | None = None, batch_size: int = 256,
                 weights: dict | None = None, k_cap: int = 1024):
        self.caps = caps or Caps()
        self.batch_size = batch_size
        self.tensors = ClusterTensors(self.caps)
        self.encoder = BatchEncoder(self.tensors, batch_size)
        self._lock = threading.Lock()
        # incremental per-row slot counts (see _assign_vectorized): the
        # null device must cost O(dirty + pods), not O(n_cap), per
        # dispatch — at 100k nodes a full-array capacity recompute per
        # batch was ~30% of the sched-loop and polluted the host
        # measurement this backend exists to take
        self._cap = np.zeros(self.caps.n_cap, np.int64)
        self._cap_maxreq: np.ndarray | None = None
        self._carry_dirty: set[int] = set()
        # epoch fast path (same contract as TPUBatchBackend): when every
        # cache change since the last sync was this backend's own bulk
        # assume/confirm lifecycle, the O(dirty-rows) re-encode is
        # skipped and this backend replays its own placements into the
        # tensors directly (_replay_claims) — the dominant steady-state
        # shape, and the biggest single host cost the null measurement
        # was still paying (~7µs/pod of re-encode at the 100k tier)
        self._last_epoch: int | None = None
        self._synced = False
        self.stats = {"batches": 0}

    def warmup(self) -> None:
        self.encoder.encode([])

    def prefetch(self, view) -> None:
        """Idle-time tensor sync (same contract as TPUBatchBackend); rows
        synced here must still reach the next dispatch's capacity
        recount, so they carry."""
        with self._lock:
            try:
                self._carry_dirty |= set(
                    self.tensors.update_from_snapshot_tracked(view))
            except VocabFullError:
                pass

    def _recount_rows(self, rows: np.ndarray, maxreq: np.ndarray) -> None:
        """Recompute remaining slot counts for `rows` given the reference
        per-pod claim `maxreq`."""
        t = self.tensors
        remaining = t.alloc[rows] - t.used[rows]
        with np.errstate(divide="ignore", invalid="ignore"):
            per_res = np.where(maxreq > 0, remaining / maxreq, np.inf)
        cap = np.floor(per_res.min(axis=1))
        cap = np.minimum(cap, t.maxpods[rows] - t.npods[rows])
        self._cap[rows] = np.clip(cap, 0, 1 << 40).astype(np.int64)
        self._cap[rows[~t.valid[rows]]] = 0

    def _assign_vectorized(self, batch, n: int,
                           dirty: np.ndarray) -> np.ndarray:
        """Capacity-aware fill, O(dirty rows + pods) per dispatch.

        Per-pod claims use the batch's MAX request per resource (bench
        batches are uniform, where this is exact; mixed batches
        under-pack, never over-pack).  Slot counts live in self._cap,
        recomputed only for rows whose encode changed this dispatch (or
        everywhere when the reference claim changes) and decremented in
        place for this batch's own placements — rows fill lowest-index
        first; placement ORDER is not what this backend measures."""
        t = self.tensors
        assignments = np.full(self.batch_size, -1, np.int64)
        if n == 0:
            return assignments
        maxreq = batch.req[:n].max(axis=0)
        if (self._cap_maxreq is None
                or not np.array_equal(maxreq, self._cap_maxreq)):
            self._cap_maxreq = maxreq
            self._recount_rows(np.nonzero(t.valid)[0], maxreq)
        elif len(dirty):
            self._recount_rows(dirty, maxreq)
        rows = np.nonzero(self._cap > 0)[0]
        if not len(rows):
            return assignments
        cap = np.minimum(self._cap[rows], n)
        # materialize only the first n slots: at 100k nodes the full
        # repeat would build a ~30M-element array per dispatch (~110ms —
        # measured as 70% of the whole dispatch) for 16k placements
        cum = np.cumsum(cap)
        stop = int(np.searchsorted(cum, n))
        if stop < len(rows):
            cap = cap[:stop + 1].copy()
            cap[stop] -= int(cum[stop]) - n  # partial last row
            rows = rows[:stop + 1]
        slots = np.repeat(rows, cap)
        k = min(len(slots), n)
        assignments[:k] = slots[:k]
        self._cap[rows] -= cap
        return assignments

    def _replay_claims(self, batch, assignments: np.ndarray, n: int) -> None:
        """Apply this batch's placements to the host tensors so the next
        dispatch's epoch skip sees current used/npods without a cache
        re-encode (the cache's authoritative re-encode overwrites these
        rows with identical values whenever an external epoch bump forces
        a real sync)."""
        t = self.tensors
        rows = assignments[:min(n, self.batch_size)]
        placed = np.nonzero(rows >= 0)[0]
        if placed.size == 0:
            return
        prow = rows[placed]
        np.add.at(t.used, prow, batch.req[placed])
        np.add.at(t.used_nz, prow, batch.req_nz[placed])
        np.add.at(t.npods, prow, 1.0)

    def dispatch(self, pod_infos: Sequence[PodInfo], snapshot):
        with self._lock:
            epoch_fn = getattr(snapshot, "epoch", None)
            epoch = epoch_fn() if epoch_fn is not None else None
            skip_sync = (epoch is not None and self._synced
                         and epoch == self._last_epoch
                         and not self._carry_dirty)
            try:
                if skip_sync:
                    dirty = set()
                else:
                    dirty = set(self.tensors.update_from_snapshot_tracked(
                        snapshot))
                    dirty |= self._carry_dirty
                    self._carry_dirty = set()
                    self._last_epoch = epoch
                    self._synced = True
                batch = self.encoder.encode(list(pod_infos))
            except VocabFullError as e:
                from ..scheduler.types import SKIP
                self._synced = False  # partial sync: force a real one next
                results = [(None, Status(SKIP, str(e)))] * len(pod_infos)
                return lambda: results
            n = len(pod_infos)
            # constraint pods escape: the null device has no solver
            is_plain = self.encoder._is_plain
            extra_escapes = {i for i, pi in enumerate(pod_infos[:self.batch_size])
                             if not is_plain(pi)}
            assignments = self._assign_vectorized(
                batch, n, np.fromiter(dirty, np.int64, len(dirty)))
            if extra_escapes:
                assignments[list(extra_escapes)] = -1
            self._replay_claims(batch, assignments, n)
            row_names = list(self.tensors.row_names)
            self.stats["batches"] += 1
            self.stats["epoch_skips"] = self.stats.get(
                "epoch_skips", 0) + (1 if skip_sync else 0)
        escapes = set(batch.escape) | extra_escapes

        def resolve():
            out = decode_results(assignments, n, self.batch_size, escapes,
                                 row_names, "no feasible node (null backend)",
                                 nofit_escapes=set(batch.nofit_oracle))
            record_batch_stats(self.stats, self._lock, out, n)
            return out

        return resolve
