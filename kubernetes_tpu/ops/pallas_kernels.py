"""Pallas TPU kernels for the assignment hot tile.

The wave solver (models/assign.py) spends its device time in the per-wave
[P, N] pass — resource-fit masking, LeastAllocated + BalancedAllocation
scoring, tie-break noise, and the per-pod masked argmax (the reference's
HOT LOOPS 1-2, pkg/scheduler/schedule_one.go:512 findNodesThatPassFilters
+ framework/runtime/framework.go:903 RunScorePlugins, fused with
selectHost :777).  XLA emits several [P, N] intermediates for it (one per
resource compare, two score planes, the masked select); at bench shapes
(P=2048, N=5632) each plane is ~46 MB of HBM traffic.

`claims` fuses the whole pass into one VMEM-resident tile program: a
(pods x nodes) grid where each step loads transposed [R, TP] request and
[R, TN] node tiles (lane dimension = the large axis, so Mosaic never
relayouts the tiny R axis), computes mask+score+noise in registers, and
folds a running (best score, best index) pair per pod in VMEM scratch —
one HBM read per input tile, one [1, TP] write per pod tile, and no
[P, N] materialization at all.

Used by the PLAIN kernel variant (no selectors/ports/constraints — the
common case the backend already specializes, ops/backend.py _pick_variant)
on single-device meshes.  Non-TPU backends run the same kernel in
interpret mode (tests) — `claims` is numerically identical to the
assign.py oracle path either way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG = -1e9
TIE_NOISE = 1e-3

# Tile sizes (lane-dim multiples of 128).  Env-overridable for A/B
# tuning (KTPU_PALLAS_TP/TN) — tunnel weather swamps single-run
# comparisons, so tile experiments must interleave runs in one window.
# Recorded negative result: 256x1024 was interleave-A/B'd at the 100k
# tier in round 5 (12.6/15.6k default vs 13.8/11.8k big tiles) — no
# winner, weather dominates; don't re-run that experiment on a tunnel.


def _tile_from_env(var: str, default: int) -> int:
    raw = os.environ.get(var)
    if raw is None:
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"{var}={raw!r}: must be an integer") from None
    if v <= 0 or v % 128 != 0:
        # Mosaic lane-dim contract; a bad value would pass interpret-mode
        # CPU tests and only fail lowering on real TPU
        raise ValueError(f"{var}={v}: must be a positive multiple of 128")
    return v


TP = _tile_from_env("KTPU_PALLAS_TP", 128)   # pod-tile size
TN = _tile_from_env("KTPU_PALLAS_TN", 512)   # node-tile size


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def pallas_enabled() -> bool:
    """Kernel on by default; KTPU_PALLAS=0 opts out (oracle fallback)."""
    return os.environ.get("KTPU_PALLAS", "1") != "0"


def _claims_kernel(r_dim: int, n_tiles: int,
                   req_ref, req_nz_ref, active_ref,
                   alloc_ref, dyn_ref, caps_ref, smask_ref,
                   idx_out_ref, score_out_ref,
                   best_ref, bidx_ref):
    """One (pi, ni) grid step: fold node tile ni into pod tile pi's best.

    Layouts (lane dim last, always TP or TN):
      req_ref/req_nz_ref [R, TP]   active_ref [1, TP]   (static per batch)
      alloc_ref [R, TN]                                  (static per batch)
      dyn_ref [2R, TN] = used rows, then used_nz rows    (changes per wave)
      caps_ref [2, TN] = npods row, maxpods row
      smask_ref [TP, TN]                                 (static per batch)
      outputs/scratch [1, TP]
    """
    pi = pl.program_id(0)
    ni = pl.program_id(1)

    @pl.when(ni == 0)
    def _init():
        best_ref[:] = jnp.full_like(best_ref, NEG)
        bidx_ref[:] = jnp.full_like(bidx_ref, -1)

    npods = caps_ref[0, :]
    maxpods = caps_ref[1, :]
    fit = (npods + 1.0 <= maxpods)[None, :]               # [1, TN]
    for r in range(r_dim):
        avail_r = alloc_ref[r, :] - dyn_ref[r, :]          # alloc - used
        fit = fit & (req_ref[r, :][:, None] <= avail_r[None, :])
    mask = (smask_ref[:] > 0.0) & fit & (active_ref[0, :][:, None] > 0.0)

    # LeastAllocated + BalancedAllocation over cpu/mem
    # (assign._fit_scores_vec semantics: util clipped to [0, 1])
    utils = []
    for r in range(2):
        a = alloc_ref[r, :][None, :]                       # [1, TN]
        u = (dyn_ref[r_dim + r, :][None, :]                # used_nz
             + req_nz_ref[r, :][:, None])                  # [TP, TN]
        utils.append(jnp.where(a > 0.0,
                               jnp.minimum(u / jnp.maximum(a, 1.0), 1.0),
                               1.0))
    ucpu, umem = utils
    score = (2.0 - ucpu - umem) * 50.0 \
        + (1.0 - jnp.abs(ucpu - umem) * 0.5) * 100.0

    # deterministic tie-break noise keyed on GLOBAL (pod, node) ids —
    # identical to the assign.py formula so results match the oracle
    gp = (pi * TP + jax.lax.broadcasted_iota(jnp.int32, (TP, TN), 0)
          ).astype(jnp.float32)
    gn = (ni * TN + jax.lax.broadcasted_iota(jnp.int32, (TP, TN), 1)
          ).astype(jnp.float32)
    h = jnp.sin(gp * 12.9898 + gn * 78.233) * 43758.5453
    noise = (h - jnp.floor(h)) * TIE_NOISE

    masked = jnp.where(mask, score + noise, NEG)           # [TP, TN]
    tile_best = jnp.max(masked, axis=-1)[None, :]          # [1, TP]
    tile_idx = jnp.argmax(masked, axis=-1)[None, :]        # [1, TP]

    upd = tile_best > best_ref[:]
    best_ref[:] = jnp.where(upd, tile_best, best_ref[:])
    bidx_ref[:] = jnp.where(upd, ni * TN + tile_idx.astype(jnp.int32),
                            bidx_ref[:])

    @pl.when(ni == n_tiles - 1)
    def _flush():
        idx_out_ref[:] = jnp.where(best_ref[:] > NEG / 2, bidx_ref[:], -1)
        score_out_ref[:] = best_ref[:]


def _pad_last(x, want):
    d = want - x.shape[-1]
    if d == 0:
        return x
    return jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, d)])


def prepare_static(req, req_nz, alloc, maxpods, static_mask):
    """Batch-invariant tiles, computed ONCE outside the wave loop: the
    [P,N] mask astype/pad alone is ~46 MB at bench shapes and must not be
    re-materialized every wave."""
    P, N = static_mask.shape
    Pp = -(-P // TP) * TP
    Np = -(-N // TN) * TN
    smask_p = _pad_last(static_mask.astype(jnp.float32), Np)
    smask_p = jnp.pad(smask_p, [(0, Pp - P), (0, 0)])
    return {
        "req_t": _pad_last(req.T, Pp),
        "req_nz_t": _pad_last(req_nz.T, Pp),
        "alloc_t": _pad_last(alloc.T, Np),
        "maxpods": maxpods,
        "smask_p": smask_p,
        "shape": (P, N, req.shape[1]),
    }


def claims(static, active, used, used_nz, npods):
    """Fused mask+score+argmax: returns (claims int32[P], best f32[P]).
    claims[p] = -1 when no node is feasible for pod p.  `static` comes
    from prepare_static; only the small dynamic aggregates are transposed
    per call."""
    P, N, R = static["shape"]
    Pp = static["smask_p"].shape[0]
    Np = static["smask_p"].shape[1]
    active_t = _pad_last(active.astype(jnp.float32)[None, :], Pp)
    dyn_t = _pad_last(jnp.concatenate([used.T, used_nz.T]), Np)
    # padded node columns get maxpods=0 -> pod-count check fails ->
    # infeasible; padded pod rows have active=0 -> masked out
    caps_t = _pad_last(jnp.stack([npods, static["maxpods"]]), Np)

    p_tiles, n_tiles = Pp // TP, Np // TN
    kernel = functools.partial(_claims_kernel, R, n_tiles)
    idx, score = pl.pallas_call(
        kernel,
        grid=(p_tiles, n_tiles),
        in_specs=[
            pl.BlockSpec((R, TP), lambda i, j: (0, i)),      # req_t
            pl.BlockSpec((R, TP), lambda i, j: (0, i)),      # req_nz_t
            pl.BlockSpec((1, TP), lambda i, j: (0, i)),      # active_t
            pl.BlockSpec((R, TN), lambda i, j: (0, j)),      # alloc_t
            pl.BlockSpec((2 * R, TN), lambda i, j: (0, j)),  # dyn_t
            pl.BlockSpec((2, TN), lambda i, j: (0, j)),      # caps_t
            pl.BlockSpec((TP, TN), lambda i, j: (i, j)),     # smask
        ],
        out_specs=[
            pl.BlockSpec((1, TP), lambda i, j: (0, i)),
            pl.BlockSpec((1, TP), lambda i, j: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, Pp), jnp.int32),
            jax.ShapeDtypeStruct((1, Pp), jnp.float32),
        ],
        scratch_shapes=[
            # running (best score, best index) per pod tile
            pltpu.VMEM((1, TP), jnp.float32),
            pltpu.VMEM((1, TP), jnp.int32),
        ],
        interpret=_use_interpret(),
    )(static["req_t"], static["req_nz_t"], active_t,
      static["alloc_t"], dyn_t, caps_t, static["smask_p"])
    idx = idx[0, :P]
    best = score[0, :P]
    return jnp.where(idx >= N, -1, idx), best
