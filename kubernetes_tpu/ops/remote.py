"""Remote device worker — the scheduler<->JAX-worker shim made a process
boundary.

Reference/north-star lineage: BASELINE.json's design keeps the
apiserver-facing scheduler untouched and crosses a gRPC shim to a JAX
worker ("tensorized snapshot request -> assignment response"); the
in-tree precedent for an out-of-process scheduling hook is the HTTP
extender (pkg/scheduler/extender.go).  Round 1 collapsed the shim into
an in-process BatchBackend; this module restores the network seam
without giving up the resident-state transport:

  * `_WorkerCore` owns the jitted kernels and the resident device state
    (exactly TPUBatchBackend's device half) behind the verbs: /init
    (shape config), /static (full static upload), /refresh (dynamic
    state reset), /step (ONE packed pod+patch buffer in, assignments
    out), /health (liveness + incarnation probe).  `GrpcDeviceWorker`
    serves them over gRPC/HTTP-2 — the transport the north star names
    (reference precedent: staging/src/k8s.io/cri-api/.../api.proto),
    each packed buffer one gRPC message with identity serializers;
    `DeviceWorker` is the same core over plain HTTP/1.1.
  * `RemoteTPUBatchBackend` IS TPUBatchBackend with the three
    device-touching methods overridden to send the same byte payloads
    (grpc:// or http:// targets) — all host bookkeeping
    (ClusterTensors, encoder, mirror/diff/replay, chunking, preemption
    candidates fall back to local jax) is shared code, so wire format
    and semantics cannot drift.  bench.py's RemoteSeamGrpc config
    measures the seam cost vs in-process (~1.1x on a CPU mesh).

Fault model (ISSUE 1; "The Tail at Scale", Dean & Barroso 2013 — tail
latency is dominated by rare slow/failed RPCs; Borg, Verma 2015 —
control-plane components must survive each other's failures):

  * Worker errors are STRUCTURED: every failure carries an error class
    (`state_lost` / `invalid_request` / `internal`), mapped to HTTP
    409/400/500 and gRPC FAILED_PRECONDITION / INVALID_ARGUMENT /
    INTERNAL.  The client's ladder distinguishes retryable transport
    faults (TransientSeamError) from fatal protocol/shape bugs
    (WorkerProtocolError) from a restarted, state-lost worker
    (WorkerStateLostError).
  * Every successful response is CRC-framed (magic + crc32 header): a
    corrupt frame is detected, classified retryable, and the retry is
    safe because every state-mutating post carries a SEQUENCE NUMBER —
    the worker caches (last_seq, last_response) and serves a duplicate
    delivery from the cache without re-applying the step.
  * The worker holds an EPOCH (incarnation token, minted at process
    start / reset).  Clients pin the epoch learned at /init on every
    subsequent post; a restarted worker answers `state_lost` and the
    client transparently resyncs: re-/init, replay the checkpointed
    /static and /refresh bodies, then replay the journal of steps
    posted since the checkpoint — deterministic kernels rebuild the
    resident state bit-identical to an uninterrupted run.
  * Per-verb deadlines + bounded retries with exponential backoff and
    seeded jitter come from scheduler/config.RemoteSeamPolicy
    (`remoteSeam:` stanza).  Exhausted retries raise
    WorkerUnavailableError, a scheduler.BackendUnavailableError: the
    scheduler requeues the batch (queue.requeue_backoff) and the
    failover ladder (ops/failover.py) can trip its breaker.

Transport: raw little-endian float32/int32 bodies (the packed buffer is
already a single 1-D f32 array; np.save framing for the array dicts).
The worker is single-tenant and ordered: steps apply to the resident
state in arrival order, which the client guarantees by being the only
writer (same contract the in-process backend's lock provides).
supports_pipelining stays True: /step returns after the device round
trip, so the client's resolve() is a no-op wait — pipelining degrades
gracefully to synchronous, it never corrupts.
"""

from __future__ import annotations

import io
import json
import logging
import os
import random
import struct
import threading
import time
import urllib.error
import urllib.request
import zlib
from dataclasses import replace
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..component_base import metrics as cbm
from ..component_base import profiling
from ..component_base import timeline as cb_timeline
from ..component_base import tracing
from ..scheduler.config import RemoteSeamPolicy
from ..scheduler.scheduler import BackendUnavailableError
from .backend import TPUBatchBackend
from .flatten import Caps

logger = logging.getLogger(__name__)


def _dump_arrays(arrays: dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _load_arrays(blob: bytes) -> dict[str, np.ndarray]:
    return dict(np.load(io.BytesIO(blob)))


# -- response framing ----------------------------------------------------
# Every SUCCESS payload travels behind an 8-byte header: magic u32 +
# crc32(payload) u32, little-endian.  A flipped bit anywhere surfaces as
# CorruptFrameError (retryable; the worker's seq cache makes the retry
# exactly-once) instead of silently mis-decoding an assignment vector.

_FRAME_MAGIC = 0x5550_544B  # b"KTPU" little-endian
_FRAME_HEADER = struct.Struct("<II")


def _frame(payload: bytes) -> bytes:
    return _FRAME_HEADER.pack(_FRAME_MAGIC,
                              zlib.crc32(payload) & 0xFFFFFFFF) + payload


def _unframe(blob: bytes, verb: str = "?") -> bytes:
    if len(blob) < _FRAME_HEADER.size:
        raise CorruptFrameError(verb, f"short frame ({len(blob)} bytes)")
    magic, crc = _FRAME_HEADER.unpack_from(blob)
    payload = blob[_FRAME_HEADER.size:]
    if magic != _FRAME_MAGIC:
        raise CorruptFrameError(verb, f"bad magic 0x{magic:08x}")
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise CorruptFrameError(verb, "crc mismatch")
    return payload


# -- error ladder --------------------------------------------------------

class SeamError(BackendUnavailableError):
    """Base for remote-seam failures.  Subclasses scheduler's
    BackendUnavailableError so an error that escapes the backend makes
    the scheduler requeue the batch instead of killing the loop."""

    retryable = False
    error_class = "transport"

    def __init__(self, verb: str, msg: str):
        super().__init__(f"{verb}: {msg}")
        self.verb = verb


class TransientSeamError(SeamError):
    """Connection refused/reset, deadline exceeded, 5xx, UNAVAILABLE:
    worth retrying against the same worker."""

    retryable = True


class CorruptFrameError(TransientSeamError):
    error_class = "corrupt_frame"


class WorkerStateLostError(SeamError):
    """409 / FAILED_PRECONDITION: the worker answered but has no (or the
    wrong incarnation of) resident state — it restarted.  Triggers the
    client-side resync replay, not a plain retry."""

    error_class = "state_lost"


class WorkerProtocolError(SeamError):
    """400 / INVALID_ARGUMENT: the request itself is malformed (shape or
    framing bug).  Deterministic — retrying cannot help."""

    error_class = "protocol"


class WorkerUnavailableError(TransientSeamError):
    """The retry budget is exhausted (or a restarted worker cannot be
    resynced).  What dispatch/resolve raise upward to the scheduler and
    the failover ladder."""

    error_class = "unavailable"


# -- worker side ---------------------------------------------------------

# error classes on the wire (the `class` field of an error body / the
# prefix of a gRPC details string)
E_STATE_LOST = "state_lost"
E_INVALID = "invalid_request"
E_INTERNAL = "internal"


class WorkerError(Exception):
    """A classified handler failure; the serving layer maps error_class
    to the transport's status vocabulary."""

    def __init__(self, error_class: str, msg: str):
        super().__init__(msg)
        self.error_class = error_class


def _new_epoch() -> int:
    # incarnation token, not a counter: two workers (or one worker
    # restarted) must not collide, so draw it from the OS
    return int.from_bytes(os.urandom(4), "little") | 1


class _WorkerCore:
    """The device half of TPUBatchBackend, transport-agnostic: both the
    HTTP DeviceWorker and the gRPC GrpcDeviceWorker serve exactly these
    verbs over the same byte payloads.

    State beyond the backend itself: `_epoch` (incarnation token; a
    client pinning a stale epoch gets `state_lost`) and the one-deep
    dedup cache `(_last_seq, _last_resp)` — the client is a single
    ordered writer, so one slot makes every retried post exactly-once.

    Tracing: each verb served under a propagated W3C traceparent opens a
    `worker.<verb>` span in the worker's OWN TracerProvider, parented by
    ids into the client-side batch trace (the head-sampling decision
    travels in the traceparent flags, so the worker never re-samples).
    The worker's flight recorder is served at /debug/traces on the HTTP
    transport."""

    def __init__(self):
        self._lock = threading.Lock()
        self._backend: TPUBatchBackend | None = None
        self._epoch = _new_epoch()
        self._last_seq: int | None = None
        self._last_resp = None
        self.tracer_provider = tracing.TracerProvider()
        self._tracer = self.tracer_provider.tracer("tpu-worker")
        # always-on, like the flight recorder: the ring is bounded and
        # idle when nobody drains it, and the client can't reach across
        # the process boundary to arm it at config time
        self.timeline = cb_timeline.Timeline(enabled=True, proc="worker")

    def reset(self) -> None:
        """Simulate a crash+restart in place: resident state, kernels and
        the dedup cache are gone; a fresh epoch is minted.  The chaos
        harness (ops/faults.py kill action) and DeviceWorker
        .simulate_restart() use this — protocol-wise indistinguishable
        from a real process restart on the same port."""
        with self._lock:
            self._backend = None
            self._epoch = _new_epoch()
            self._last_seq = None
            self._last_resp = None

    def handle(self, path: str, body: bytes, epoch: int | None = None,
               seq: int | None = None, traceparent: str | None = None):
        """Returns (payload, worker_epoch); raises WorkerError with an
        error class on any failure.  A sampled `traceparent` wraps the
        verb in a worker-side span (malformed headers are ignored, per
        the W3C spec — never fail the request over telemetry)."""
        ctx = tracing.parse_traceparent(traceparent)
        if ctx is None or not ctx.sampled:
            return self._handle(path, body, epoch, seq)
        with self._tracer.start_span(
                "worker." + path.lstrip("/").split("?", 1)[0],
                context=ctx) as span:
            span.set_attribute("process", "worker")
            span.set_attribute("verb", path)
            span.set_attribute("bytes", len(body))
            try:
                return self._handle(path, body, epoch, seq)
            except WorkerError as e:
                span.add_event("worker_error", error_class=e.error_class,
                               error=str(e))
                raise

    def _handle(self, path: str, body: bytes, epoch: int | None = None,
                seq: int | None = None):
        with self._lock:
            if path == "/health":
                # liveness + incarnation, served before /init and without
                # consuming a seq: the breaker's half-open probe
                return ({"ok": True, "epoch": self._epoch,
                         "initialized": self._backend is not None},
                        self._epoch)
            if path == "/timeline":
                # observability drain, served like /health: before /init,
                # epoch-blind and without consuming a seq — a restarted
                # or uninitialized worker still answers (its ring is
                # simply empty).  Rows are wall-anchored by this
                # process's own clock, so the client ingests verbatim.
                return ({"intervals": self.timeline.intervals(drain=True)},
                        self._epoch)
            if seq is not None and seq == self._last_seq \
                    and self._last_resp is not None:
                # duplicate delivery (client retried after a lost or
                # corrupt response): serve the cached response WITHOUT
                # re-applying — re-running a /step would double-count
                # the resident-state commit
                return (self._last_resp, self._epoch)
            if path == "/init":
                out = self._init(body)
            else:
                if self._backend is None:
                    raise WorkerError(E_STATE_LOST,
                                      "worker not initialized (/init first)")
                if epoch is not None and epoch != self._epoch:
                    raise WorkerError(
                        E_STATE_LOST,
                        f"epoch mismatch (client {epoch}, worker "
                        f"{self._epoch}): worker restarted")
                out = self._apply(path, body)
            if seq is not None:
                self._last_seq, self._last_resp = seq, out
            return (out, self._epoch)

    def _init(self, body: bytes):
        try:
            cfg = json.loads(body)
            caps = Caps(**cfg["caps"])
        except (ValueError, TypeError, KeyError) as e:
            raise WorkerError(E_INVALID, f"bad /init body: {e!r}")
        kind = cfg.get("backend_kind", "tpu")
        if kind != "tpu":
            # the sharded backend is mesh-local by design: its node
            # tensors live partitioned across THIS process's device mesh
            # and the row-patch wire protocol would re-replicate them —
            # run `backend: sharded` in the scheduler process instead
            raise WorkerError(
                E_INVALID, f"worker backend kind {kind!r} unsupported "
                "(only 'tpu'; sharded is mesh-local)")
        try:
            # a plain TPUBatchBackend, used ONLY for its device half —
            # the remote client owns all host bookkeeping
            self._backend = TPUBatchBackend(
                caps, batch_size=cfg["batch_size"],
                weights=cfg.get("weights"), k_cap=cfg.get("k_cap", 1024),
                full_batch_cap=cfg.get("full_batch_cap"))
            # instance override: the CLIENT's setting wins (its resolve()
            # is the half that must retry what a capped kernel leaves)
            self._backend.FULL_MAIN_WAVES = cfg.get(
                "full_main_waves", self._backend.FULL_MAIN_WAVES)
            self._backend._ensure_full()
            if self._backend.FULL_MAIN_WAVES:
                self._backend._ensure_full_small()
            self._backend._ensure_plain()
        except Exception as e:  # noqa: BLE001 — classify, don't die
            self._backend = None
            raise WorkerError(E_INTERNAL, f"/init failed: {e!r}")
        return {"ok": True, "full_cap": self._backend.full_cap,
                "epoch": self._epoch}

    def _apply(self, path: str, body: bytes):
        b = self._backend
        if path == "/static":
            import jax.numpy as jnp

            from .backend import STATIC_CORE, STATIC_SEL, STATIC_VICT
            try:
                arrays = _load_arrays(body)
                static_node = {k: jnp.asarray(arrays[k])
                               for k in STATIC_CORE}
                static_sel = {k: jnp.asarray(arrays[k]) for k in STATIC_SEL}
            except (ValueError, KeyError, OSError) as e:
                raise WorkerError(E_INVALID, f"bad /static body: {e!r}")
            b._static_node = static_node
            # the worker holds BOTH halves resident (its tensors are empty,
            # so the base _ensure_sel must never try to rebuild from them)
            b._static_sel = static_sel
            b._sel_stale = False
            if all(k in arrays for k in STATIC_VICT):
                # victim tensors ride the same body once the client's
                # preemption path engages (older clients omit them)
                b._static_vict = {k: jnp.asarray(arrays[k])
                                  for k in STATIC_VICT}
            return {"ok": True}
        if path == "/refresh":
            import jax.numpy as jnp
            try:
                arrays = _load_arrays(body)
            except (ValueError, OSError) as e:
                raise WorkerError(E_INVALID, f"bad /refresh body: {e!r}")
            b._state = {k: jnp.asarray(v) for k, v in arrays.items()}
            return {"ok": True}
        if path.startswith("/step"):
            variant = path.rsplit("=", 1)[-1]
            if variant not in ("full", "full_small", "plain"):
                raise WorkerError(E_INVALID, f"unknown variant {variant!r}")
            try:
                import jax
                buf = np.frombuffer(body, np.float32)
                t0 = time.monotonic()
                rd = b._device_step(variant, buf)
                # sync-point: worker serializes the step result for the wire
                out = jax.device_get(rd).astype(np.int32).tobytes()
                # device-step measured at the sync point: the worker's lane
                # is the true device time the client's wire RT swallows
                self.timeline.record("device-step", t0, time.monotonic())
                return out
            except WorkerError:
                raise
            except (ValueError, TypeError, KeyError, IndexError) as e:
                # wrong byte count / unpackable layout: the request is
                # broken, not the worker
                raise WorkerError(E_INVALID, f"malformed /step body: {e!r}")
            except Exception as e:  # noqa: BLE001 — classify, don't die
                raise WorkerError(E_INTERNAL, f"/step failed: {e!r}")
        if path == "/preempt":
            # the dry-run kernel against the RESIDENT static + victim +
            # dynamic arrays; read-only (never journaled client-side), so
            # a retry or post-resync replay cannot double-apply anything
            if b._static_vict is None:
                raise WorkerError(E_STATE_LOST,
                                  "no resident victim tensors (/static "
                                  "with a victim section first)")
            if b._state is None:
                raise WorkerError(E_STATE_LOST,
                                  "no resident dynamic state (/refresh "
                                  "first)")
            try:
                arrays = _load_arrays(body)
                out = b._preempt_step(
                    {k: arrays[k]
                     for k in ("req", "prio", "untol_hard", "group_idx",
                               "nom_used", "nom_np", "active")})
                import jax
                # sync-point: worker serializes the dry-run planes
                cand, viol, highest, psum, nvic, victims, overflow = \
                    jax.device_get(out)
                return _dump_arrays({
                    "cand": cand, "viol": viol, "highest": highest,
                    "psum": psum, "nvic": nvic, "victims": victims,
                    "overflow": overflow})
            except WorkerError:
                raise
            except (ValueError, TypeError, KeyError, IndexError, OSError) as e:
                raise WorkerError(E_INVALID, f"malformed /preempt body: {e!r}")
            except Exception as e:  # noqa: BLE001 — classify, don't die
                raise WorkerError(E_INTERNAL, f"/preempt failed: {e!r}")
        raise WorkerError(E_INVALID, f"unknown verb {path!r}")


class DeviceWorker:
    """The device half of TPUBatchBackend behind HTTP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._core = _WorkerCore()
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("tpu-worker: " + fmt, *args)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def _reply(self, code: int, body: bytes = b"{}",
                       ctype: str = "application/json",
                       epoch: int | None = None) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                if epoch is not None:
                    self.send_header("X-KTPU-Epoch", str(epoch))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                # observability twins of the scheduler apiserver's
                # endpoints: the Prometheus page and the span flight
                # recorder (component_base/tracing debug_traces_json)
                if self.path == "/debug/traces":
                    self._reply(200, server._core.tracer_provider
                                .debug_traces_json().encode())
                elif self.path.startswith("/debug/timeline"):
                    tl = server._core.timeline
                    if "chrome" in self.path:
                        body = json.dumps(tl.to_chrome_trace()).encode()
                    else:
                        body = tl.debug_json().encode()
                    self._reply(200, body)
                elif self.path == "/debug/profile":
                    self._reply(200, profiling.default_host_profiler
                                .collapsed().encode(), "text/plain")
                elif self.path == "/metrics":
                    self._reply(200, cbm.default_registry.expose().encode(),
                                "text/plain; version=0.0.4")
                elif self.path in ("/healthz", "/health"):
                    self._reply(200, json.dumps(
                        {"ok": True}).encode())
                else:
                    self._reply(404, b'{"error": "not found"}')

            def do_POST(self):
                try:
                    epoch = self.headers.get("X-KTPU-Epoch")
                    seq = self.headers.get("X-KTPU-Seq")
                    out, w_epoch = server._core.handle(
                        self.path, self._body(),
                        epoch=int(epoch) if epoch is not None else None,
                        seq=int(seq) if seq is not None else None,
                        traceparent=self.headers.get("X-KTPU-Traceparent"))
                except WorkerError as e:
                    code = {E_STATE_LOST: 409, E_INVALID: 400}.get(
                        e.error_class, 500)
                    logger.warning("tpu-worker: %s -> %d %s: %s",
                                   self.path, code, e.error_class, e)
                    self._reply(code, json.dumps(
                        {"error": str(e), "class": e.error_class}).encode())
                    return
                except Exception as e:  # noqa: BLE001 — report, don't die
                    logger.exception("tpu-worker: %s failed", self.path)
                    self._reply(500, json.dumps(
                        {"error": str(e), "class": E_INTERNAL}).encode())
                    return
                if isinstance(out, bytes):
                    self._reply(200, _frame(out), "application/octet-stream",
                                epoch=w_epoch)
                else:
                    self._reply(200, _frame(json.dumps(out or {}).encode()),
                                "application/octet-stream", epoch=w_epoch)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.httpd.server_address[0]}:{self.port}"

    @property
    def tracer_provider(self):
        """The worker-side span flight recorder (served at /debug/traces;
        bench --trace merges it into the Chrome export)."""
        return self._core.tracer_provider

    def start(self) -> "DeviceWorker":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="tpu-worker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    def simulate_restart(self) -> None:
        """Chaos hook: drop resident state + mint a new epoch, keeping the
        socket (protocol-identical to a crash + same-port restart)."""
        self._core.reset()


# gRPC method name <-> worker verb (the reference's process-boundary
# precedent is gRPC: staging/src/k8s.io/cri-api/.../api.proto; the
# messages here are the packed byte buffers themselves — identity
# serializers, no protobuf intermediate copy of a 10+ MB tensor blob)
GRPC_SERVICE = "ktpu.TPUWorker"
_GRPC_VERBS = {
    "Init": "/init",
    "Static": "/static",
    "Refresh": "/refresh",
    "StepFull": "/step?variant=full",
    "StepFullSmall": "/step?variant=full_small",
    "StepPlain": "/step?variant=plain",
    "Preempt": "/preempt",
    "Timeline": "/timeline",
    "Health": "/health",
}
_GRPC_MSG_CAP = 512 << 20
_GRPC_OPTIONS = [
    ("grpc.max_receive_message_length", _GRPC_MSG_CAP),
    ("grpc.max_send_message_length", _GRPC_MSG_CAP),
]


class GrpcDeviceWorker:
    """The device half of TPUBatchBackend behind gRPC (HTTP/2).

    Same verbs and byte payloads as the HTTP DeviceWorker (shared
    _WorkerCore), but the transport is the one the north star names:
    each packed buffer travels as ONE gRPC message with binary framing —
    no chunked-encoding or content-length ceremony per step."""

    # WorkerError class -> status code (mirrors the HTTP 409/400/500 map)
    _STATUS_OF = None  # filled lazily (grpc import)

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._core = _WorkerCore()
        core = self._core
        status_of = {E_STATE_LOST: grpc.StatusCode.FAILED_PRECONDITION,
                     E_INVALID: grpc.StatusCode.INVALID_ARGUMENT,
                     E_INTERNAL: grpc.StatusCode.INTERNAL}

        def _unary(verb_path):
            def call(request: bytes, context) -> bytes:
                md = dict(context.invocation_metadata() or ())
                epoch = md.get("ktpu-epoch")
                seq = md.get("ktpu-seq")
                try:
                    out, _w_epoch = core.handle(
                        verb_path, request,
                        epoch=int(epoch) if epoch is not None else None,
                        seq=int(seq) if seq is not None else None,
                        traceparent=md.get("ktpu-traceparent"))
                except WorkerError as e:
                    logger.warning("tpu-worker(grpc): %s -> %s: %s",
                                   verb_path, e.error_class, e)
                    context.abort(
                        status_of.get(e.error_class,
                                      grpc.StatusCode.INTERNAL),
                        f"{e.error_class}: {e}")
                except Exception as e:  # noqa: BLE001 — report, don't die
                    logger.exception("tpu-worker(grpc): %s failed",
                                     verb_path)
                    context.abort(grpc.StatusCode.INTERNAL,
                                  f"{E_INTERNAL}: {e}")
                if isinstance(out, bytes):
                    return _frame(out)
                return _frame(json.dumps(out or {}).encode())
            return call

        handlers = {
            name: grpc.unary_unary_rpc_method_handler(_unary(path))
            for name, path in _GRPC_VERBS.items()}
        from concurrent import futures
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4,
                                       thread_name_prefix="tpu-worker-grpc"),
            options=_GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(GRPC_SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._host = host

    @property
    def url(self) -> str:
        return f"grpc://{self._host}:{self.port}"

    @property
    def tracer_provider(self):
        """See DeviceWorker.tracer_provider."""
        return self._core.tracer_provider

    def start(self) -> "GrpcDeviceWorker":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1.0)

    def simulate_restart(self) -> None:
        """Chaos hook: see DeviceWorker.simulate_restart."""
        self._core.reset()


# -- client transports ---------------------------------------------------
# One interface: post(verb, body, timeout=, epoch=, seq=) -> framed bytes,
# raising the classified SeamError ladder.  ops/faults.py FaultyTransport
# wraps either implementation.

class _HttpTransport:
    """Client side of the HTTP/1.1 seam: ONE pooled keep-alive
    connection per (host, port) per thread.

    The old per-request urlopen paid TCP handshake + header re-parse on
    every dispatch/wait — invisible against a 70ms tunnel round trip,
    but multi-process mode hammers this seam from N schedulers on one
    loopback.  Connections are thread-local (http.client connections are
    not thread-safe) and the pool retries ONCE on a stale keep-alive
    socket; the retry is safe even for mid-flight failures because the
    server dedups by (epoch, seq) — a replayed post is answered from the
    dedup cache, never re-executed.  The SeamError ladder is unchanged;
    unlike urlopen, http.client does not raise on 4xx/5xx, so status
    classification happens on resp.status."""

    kind = "http"

    def __init__(self, base_url: str):
        import http.client as _hc
        import urllib.parse as _up

        self.base_url = base_url
        parts = _up.urlsplit(base_url)
        self._hc = _hc
        self._host = parts.hostname or "127.0.0.1"
        self._port = parts.port or 80
        self._prefix = parts.path.rstrip("/")
        self._local = threading.local()
        self._lock = threading.Lock()
        self._conns: list = []  # every conn ever pooled, for close()
        self._closed = False

    def _conn(self, timeout: float):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self._hc.HTTPConnection(self._host, self._port,
                                           timeout=timeout)
            self._local.conn = conn
            with self._lock:
                self._conns.append(conn)
        # refresh the deadline for THIS request: set on the object for
        # the next connect and directly on a live socket
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        self._local.conn = None
        if conn is not None:
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def post(self, verb: str, body: bytes, *, timeout: float,
             epoch: int | None = None, seq: int | None = None,
             traceparent: str | None = None) -> bytes:
        headers = {"Content-Type": "application/octet-stream"}
        if epoch is not None:
            headers["X-KTPU-Epoch"] = str(epoch)
        if seq is not None:
            headers["X-KTPU-Seq"] = str(seq)
        if traceparent is not None:
            headers["X-KTPU-Traceparent"] = traceparent
        last: Exception | None = None
        for attempt in range(2):  # second pass only for a stale socket
            conn = self._conn(timeout)
            try:
                conn.request("POST", self._prefix + verb, body=body,
                             headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
                status = resp.status
            except (self._hc.HTTPException, OSError) as e:
                # stale keep-alive (server idle-closed between requests)
                # or a real network fault: drop the conn; retry once
                self._drop_conn()
                last = e
                continue
            if status < 400:
                return raw
            try:
                info = json.loads(raw)
                cls, msg = info.get("class", ""), info.get("error", "")
            except (ValueError, UnicodeDecodeError):
                cls, msg = "", repr(raw[:200])
            if status == 409 or cls == E_STATE_LOST:
                raise WorkerStateLostError(verb, msg) from None
            if 400 <= status < 500:
                raise WorkerProtocolError(
                    verb, f"HTTP {status} ({cls or 'error'}): {msg}"
                ) from None
            raise TransientSeamError(
                verb, f"HTTP {status} ({cls or 'error'}): {msg}") from None
        # both attempts died on the wire: the network or the worker
        # process, not the request
        raise TransientSeamError(verb, repr(last)) from None

    def close(self) -> None:
        with self._lock:
            conns, self._conns = self._conns, []
            self._closed = True
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass


class _GrpcTransport:
    """Client side of the gRPC seam: verb path -> unary call with
    identity (bytes) serializers; epoch/seq ride call metadata."""

    kind = "grpc"

    def __init__(self, target: str):
        import grpc

        self._grpc = grpc
        self._channel = grpc.insecure_channel(target,
                                              options=_GRPC_OPTIONS)
        self._calls = {
            path: self._channel.unary_unary(f"/{GRPC_SERVICE}/{name}")
            for name, path in _GRPC_VERBS.items()}

    def post(self, verb: str, body: bytes, *, timeout: float,
             epoch: int | None = None, seq: int | None = None,
             traceparent: str | None = None) -> bytes:
        md = []
        if epoch is not None:
            md.append(("ktpu-epoch", str(epoch)))
        if seq is not None:
            md.append(("ktpu-seq", str(seq)))
        if traceparent is not None:
            md.append(("ktpu-traceparent", traceparent))
        try:
            return self._calls[verb](body, timeout=timeout,
                                     metadata=tuple(md) or None)
        except self._grpc.RpcError as e:
            sc = self._grpc.StatusCode
            code = e.code()
            details = e.details() or ""
            if (code == sc.FAILED_PRECONDITION
                    or details.startswith(E_STATE_LOST)):
                raise WorkerStateLostError(verb, details) from None
            if code in (sc.INVALID_ARGUMENT, sc.UNIMPLEMENTED,
                        sc.UNAUTHENTICATED, sc.PERMISSION_DENIED):
                raise WorkerProtocolError(
                    verb, f"{code.name}: {details}") from None
            # UNAVAILABLE / DEADLINE_EXCEEDED / INTERNAL / UNKNOWN / ...
            raise TransientSeamError(
                verb, f"{code.name}: {details}") from None

    def close(self) -> None:
        self._channel.close()


def transport_for(worker_url: str):
    url = worker_url.rstrip("/")
    if url.startswith("grpc://"):
        return _GrpcTransport(url[len("grpc://"):])
    return _HttpTransport(url)


# the /refresh (and checkpoint) body: exactly the mirror's keys
_REFRESH_KEYS = ("used", "used_nz", "npods", "port_mask", "cd_sg", "cd_asg")


class RemoteTPUBatchBackend(TPUBatchBackend):
    """TPUBatchBackend whose device half lives in a DeviceWorker.

    Everything except the overridden device-seam methods is inherited:
    the tensors, encoder, mirror replay, patch diffing, chunking and the
    FLUSH_FIRST protocol run scheduler-side, and the SAME packed bytes
    that would go to a local chip go over the wire.

    Resilience (module docstring): per-verb deadlines, bounded jittered
    retries, seq-deduped exactly-once posts, and a checkpoint+journal
    that lets a worker restart be replayed transparently mid-stream:

      * checkpoint — at the first dispatch after the pipeline drains,
        snapshot the host mirror as a ready-to-post /refresh body (the
        mirror IS the device state whenever nothing is unresolved) and
        clear the journal; /static and /refresh posts checkpoint
        themselves (their body is the state).
      * journal — every /step body posted since the checkpoint, in
        order.  On `state_lost`: re-/init, post the checkpointed static
        + refresh, replay the journal, then re-post the failed step
        under a fresh seq.  Deterministic kernels make the rebuilt
        resident state bit-identical.
      * degradation — if the journal overflowed (journal_cap) or no
        checkpoint exists yet, raise WorkerUnavailableError instead:
        the scheduler requeues the batch and the next dispatch rebuilds
        the device state from the authoritative tensors.  Slower, never
        wrong.
    """

    # device_census is inherited: the step fns are built client-side and
    # the worker compiles the same bytes, so the client-side lowering IS
    # the worker's program
    census_kind = "remote"

    def __init__(self, worker_url: str, caps: Caps | None = None,
                 batch_size: int = 256,
                 weights: dict[str, float] | None = None,
                 k_cap: int = 1024, full_batch_cap: int | None = None,
                 timeout: float | None = None,
                 policy: RemoteSeamPolicy | None = None,
                 transport=None, rng_seed: int = 0):
        self.worker_url = worker_url.rstrip("/")
        if policy is None:
            policy = RemoteSeamPolicy()
        if timeout is not None:
            # legacy knob: one deadline for every verb
            policy = replace(policy, init_timeout=timeout,
                             static_timeout=timeout, refresh_timeout=timeout,
                             step_timeout=timeout)
        self.policy = policy
        self.timeout = policy.step_timeout  # back-compat attribute
        self._rng = random.Random(rng_seed)
        self._transport = (transport if transport is not None
                           else transport_for(self.worker_url))
        self._seq = 0
        self._epoch: int | None = None
        self._needs_reinit = False
        self._init_body: bytes | None = None
        self._ckpt_static_body: bytes | None = None
        self._ckpt_refresh_body: bytes | None = None
        self._journal: list[tuple[str, bytes]] = []
        self._journal_overflow = False
        self.seam_stats = {"retries": 0, "resyncs": 0, "state_lost": 0,
                           "corrupt_frames": 0, "giveups": 0}
        super().__init__(caps, batch_size=batch_size, weights=weights,
                         k_cap=k_cap, full_batch_cap=full_batch_cap)
        self._init_body = json.dumps({
            "caps": vars(self.caps), "batch_size": batch_size,
            "weights": weights, "k_cap": k_cap,
            # explicit so a future mixed fleet fails loudly: today's
            # workers only build the single-chip kernel (sharded is
            # mesh-local; see DeviceWorker._init)
            "backend_kind": "tpu",
            "full_batch_cap": self.full_cap,
            # the CLIENT's wave-cap/retry setting governs both halves: the
            # worker must build its main kernel with the same cap the
            # client's resolve() compensates for, or capped-kernel
            # leftovers decode as UNSCHEDULABLE with no retry
            "full_main_waves": self.FULL_MAIN_WAVES}).encode()
        got = json.loads(self._post("/init", self._init_body))
        self.full_cap = got["full_cap"]
        self._epoch = got.get("epoch")

    # -- resilient transport ---------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _post_once(self, verb: str, body: bytes, seq: int | None) -> bytes:
        # propagate the batch trace across the seam: the scheduler's
        # current (root) span rides the post as a W3C traceparent, so the
        # worker's verb spans parent into the client trace by ids —
        # including after retries/resync, because every re-post reads the
        # same thread-local root (no orphan traces)
        span = tracing.current_span()
        tp = (span.traceparent()
              if span is not None and span.sampled else None)
        out = self._transport.post(verb, body,
                                   timeout=self.policy.timeout_for(verb),
                                   epoch=self._epoch, seq=seq,
                                   traceparent=tp)
        try:
            return _unframe(out, verb)
        except CorruptFrameError:
            self.seam_stats["corrupt_frames"] += 1
            raise

    def _call(self, verb: str, body: bytes, seq: int | None,
              allow_resync: bool = True) -> bytes:
        """One logical post: bounded retries with exponential backoff +
        seeded jitter for transient faults, transparent resync for a
        state-lost worker, immediate raise for protocol errors."""
        p = self.policy
        attempt = 0
        resyncs = 0
        need_resync = False
        while True:
            try:
                if need_resync:
                    need_resync = False
                    self._resync()
                return self._post_once(verb, body, seq)
            except WorkerStateLostError:
                self.seam_stats["state_lost"] += 1
                if not allow_resync or verb == "/init":
                    raise
                resyncs += 1
                if resyncs > p.resync_attempts:
                    self.seam_stats["giveups"] += 1
                    raise
                need_resync = True
                self._seam_event("seam_resync", verb=verb, resync=resyncs)
                # the failed post replays under a FRESH seq: the old
                # seq's dedup slot died with the worker's state
                seq = self._next_seq() if seq is not None else None
            except WorkerUnavailableError:
                raise  # a nested resync already gave up
            except TransientSeamError as e:
                attempt += 1
                if attempt > p.max_retries:
                    self.seam_stats["giveups"] += 1
                    raise WorkerUnavailableError(
                        verb, f"retries exhausted "
                        f"({p.max_retries}): {e}") from e
                self.seam_stats["retries"] += 1
                # retries are EVENTS on the live batch span, never new
                # traces: the re-post inherits the same trace context
                self._seam_event("seam_retry", verb=verb, attempt=attempt,
                                 error_class=e.error_class)
                time.sleep(p.backoff(attempt, self._rng))

    @staticmethod
    def _seam_event(name: str, **attrs) -> None:
        span = tracing.current_span()
        if span is not None and span.sampled:
            span.add_event(name, **attrs)

    def _post(self, verb: str, body: bytes) -> bytes:
        """A state-mutating post: one seq for its lifetime (retries dedup
        worker-side); successful steps are journaled for resync replay."""
        out = self._call(verb, body, self._next_seq())
        if verb.startswith("/step"):
            self._journal.append((verb, body))
            if len(self._journal) > self.policy.journal_cap:
                # transparent replay is no longer possible; the next
                # quiescent dispatch re-checkpoints, and a restart before
                # then degrades to a failed batch + full rebuild
                self._journal_overflow = True
                del self._journal[:]
        return out

    def _resync(self) -> None:
        """The worker lost its resident state (restart): rebuild it to
        exactly the post-last-successful-step state and carry on."""
        self.seam_stats["resyncs"] += 1
        if (self._init_body is None or self._ckpt_static_body is None
                or self._ckpt_refresh_body is None or self._journal_overflow):
            self._degrade()
            raise WorkerUnavailableError(
                "/resync", "worker restarted with no replayable checkpoint; "
                "batch requeued, device state rebuilds next dispatch")
        logger.warning(
            "remote seam: worker state lost; resyncing (init + static + "
            "refresh + %d journaled steps)", len(self._journal))
        self._epoch = None  # accept whichever incarnation answers
        got = json.loads(self._call("/init", self._init_body,
                                    self._next_seq(), allow_resync=False))
        if got["full_cap"] != self.full_cap:
            raise WorkerProtocolError(
                "/init", f"full_cap changed across restart "
                f"({self.full_cap} -> {got['full_cap']})")
        self._epoch = got.get("epoch")
        self._call("/static", self._ckpt_static_body, self._next_seq(),
                   allow_resync=False)
        self._call("/refresh", self._ckpt_refresh_body, self._next_seq(),
                   allow_resync=False)
        for verb, body in self._journal:
            self._call(verb, body, self._next_seq(), allow_resync=False)

    def _degrade(self) -> None:
        """No replayable checkpoint: forget the device state so the next
        dispatch re-inits and uploads static + refresh from the
        authoritative tensors (correct by construction — the failed
        batch's pods were requeued, never bound)."""
        self._needs_reinit = True
        self._epoch = None
        self._static_node = None
        self._state = None
        self._mirror = None
        self._ckpt_refresh_body = None
        del self._journal[:]
        self._journal_overflow = False

    # -- checkpointing hooks ---------------------------------------------

    def dispatch(self, pod_infos, snapshot):
        with self._lock:
            self._seam_prepare()
        return super().dispatch(pod_infos, snapshot)

    def _seam_prepare(self) -> None:
        if self._needs_reinit and self._init_body is not None:
            got = json.loads(self._call("/init", self._init_body,
                                        self._next_seq(),
                                        allow_resync=False))
            if got["full_cap"] != self.full_cap:
                raise WorkerProtocolError(
                    "/init", f"full_cap changed across restart "
                    f"({self.full_cap} -> {got['full_cap']})")
            self._epoch = got.get("epoch")
            self._needs_reinit = False
        if ((self._journal or self._journal_overflow)
                and not self._unresolved and self._mirror is not None):
            # quiescent: every dispatched step has been resolved AND
            # replayed into the mirror, so the mirror == device state;
            # snapshot it as a ready-to-post /refresh body (gen rides
            # along so a resync replay lands on the same generation
            # lineage: G_ckpt + len(journal) == the client's counter)
            self._ckpt_refresh_body = _dump_arrays(
                {**{k: self._mirror[k] for k in _REFRESH_KEYS},
                 "gen": np.asarray(self._gen, np.int32)})
            del self._journal[:]
            self._journal_overflow = False

    def health(self, timeout: float | None = None) -> dict:
        """One /health round trip (raises the SeamError ladder).  Used by
        the failover breaker's half-open probe."""
        out = self._transport.post(
            "/health", b"",
            timeout=timeout if timeout is not None
            else self.policy.health_timeout,
            epoch=None, seq=None)
        return json.loads(_unframe(out, "/health"))

    def close(self) -> None:
        self._transport.close()

    # -- the device seam, remoted ---------------------------------------

    def _ensure_full(self):
        if self._spec_full is None:
            from ..models.assign import PackSpec
            self._spec_full = PackSpec(self.caps, self.full_cap,
                                       self._k_cap)
        return None  # the worker holds the fns

    def _ensure_full_small(self):
        if self._spec_full_small is None:
            from ..models.assign import PackSpec
            self._spec_full_small = PackSpec(self.caps, self._retry_cap(),
                                             self._k_cap)
        return None  # the worker holds the fns

    def _ensure_plain(self):
        if self._spec_plain is None:
            from ..models.assign import PackSpec
            self._spec_plain = PackSpec(self.caps, self.batch_size,
                                        self._k_cap, plain=True)
        return None

    def _device_step(self, variant: str, buf: np.ndarray) -> np.ndarray:
        out = self._post(f"/step?variant={variant}",
                         np.ascontiguousarray(buf, np.float32).tobytes())
        self._gen += 1  # the worker's kernel computed state.gen + 1
        return np.frombuffer(out, np.int32)

    def _upload_static(self) -> None:
        t = self.tensors
        arrays = {
            "alloc": t.alloc, "maxpods": t.maxpods, "valid": t.valid,
            "taint_mask": t.taint_mask, "label_mask": t.label_mask,
            "key_mask": t.key_mask, "dom_sg": t.dom_sg,
            "dom_asg": t.dom_asg, "sg_ns_mask": t.sg_ns_mask,
            "asg_ns_mask": t.asg_ns_mask}
        if self._static_vict is not None:
            # once the preemption path has engaged, the victim section
            # rides every static body — the body doubles as the resync
            # checkpoint, so a restarted worker replays the victim
            # tensors too and post-resync /preempt answers stay
            # bit-identical
            arrays.update({
                "vict_prio": t.vict_prio, "vict_req": t.vict_req,
                "vict_pdb": t.vict_pdb, "vict_over": t.vict_over})
        body = _dump_arrays(arrays)
        self._post("/static", body)
        self._ckpt_static_body = body  # the post IS the checkpoint
        self._static_node = True  # sentinel: worker holds the arrays
        t.static_dirty_rows = set()
        t.static_full = False
        self._static_version = t.static_version

    def _ensure_vict(self) -> None:
        """Remote twin of TPUBatchBackend._ensure_vict: the victim
        tensors travel inside a full /static body (no wire patch path —
        preemption waves are rare and the body is the checkpoint)."""
        t = self.tensors
        t.refresh_victims()
        if (self._static_vict is not None and not t.vict_full
                and self._vict_version == t.vict_version):
            return
        self._static_vict = True  # sentinel: worker holds the arrays
        self._upload_static()
        t.vict_full = False
        self._vict_version = t.vict_version

    def _preempt_step(self, body: dict):
        """Ship one padded preemptor chunk to the worker's /preempt verb;
        the worker runs the dry-run kernel against ITS resident arrays.
        Read-only — never journaled; a state-lost worker resyncs (which
        replays the victim-carrying static checkpoint) and the re-post
        returns the same answer."""
        out = _load_arrays(self._post("/preempt", _dump_arrays(body)))
        return (out["cand"], out["viol"], out["highest"], out["psum"],
                out["nvic"], out["victims"], out["overflow"])

    def drain_worker_timeline(self) -> list:
        """Pull (and clear) the worker's timeline ring across the seam.

        Read-only and metrics-path: no seq (nothing to dedup; the worker
        serves it epoch-blind like /health), no resync — an uninitialized
        or restarted worker answers with an empty ring, and the caller
        treats any seam error as an empty drain."""
        out = self._call("/timeline", b"", None, allow_resync=False)
        return json.loads(out).get("intervals", [])

    def _full_refresh(self, cd_sg: np.ndarray, cd_asg: np.ndarray) -> None:
        t = self.tensors
        body = _dump_arrays({
            "used": t.used, "used_nz": t.used_nz, "npods": t.npods,
            "port_mask": t.port_mask, "cd_sg": cd_sg, "cd_asg": cd_asg,
            "gen": np.asarray(self._gen, np.int32)})
        self._post("/refresh", body)
        # a refresh replaces the device state outright: it IS a checkpoint,
        # and every journaled step before it is obsolete
        self._ckpt_refresh_body = body
        del self._journal[:]
        self._journal_overflow = False
        self._state = True  # sentinel: worker holds the arrays
        self._mirror_from_tensors(cd_sg, cd_asg)
        self.stats["full_refresh"] += 1

    def _restore_state_from_mirror(self) -> None:
        """Gen-stale recovery over the wire: post the host mirror as a
        fresh /refresh body on a bumped generation lineage.  The body
        doubles as a checkpoint (the mirror IS the intended device state),
        so the journaled steps behind it are obsolete."""
        self._gen += 1
        body = _dump_arrays({
            **{k: self._mirror[k] for k in _REFRESH_KEYS},
            "gen": np.asarray(self._gen, np.int32)})
        self._post("/refresh", body)
        self._ckpt_refresh_body = body
        del self._journal[:]
        self._journal_overflow = False
        self._state = True  # sentinel: worker holds the arrays
        self.stats["gen_recoveries"] = self.stats.get("gen_recoveries", 0) + 1

    def warmup(self) -> None:
        with self._lock:
            if self._needs_reinit:
                self._seam_prepare()
            if self._static_node is None:
                self._upload_static()
            if self._state is None:
                cd_sg, cd_asg = self.tensors.domain_base_counts()
                self._full_refresh(cd_sg, cd_asg)
            from ..models.assign import pack_pod_batch
            from .flatten import slice_pod_batch
            batch = self.encoder.encode([])
            empty = (np.empty(0, np.int32),
                     np.empty((0, self._f_patch), np.float32))
            self._ensure_full()
            self._device_step("full", pack_pod_batch(
                slice_pod_batch(batch, 0, 0, self.full_cap),
                self._spec_full, *empty))
            if self.FULL_MAIN_WAVES:
                # compile the straggler retry kernel now, not inside the
                # first straggler-carrying resolve()
                self._ensure_full_small()
                self._device_step("full_small", pack_pod_batch(
                    slice_pod_batch(batch, 0, 0, self._retry_cap()),
                    self._spec_full_small, *empty))
            self._ensure_plain()
            self._device_step("plain", pack_pod_batch(
                batch, self._spec_plain, *empty))
            # ship the dry-run warm chunk too: the worker compiles the
            # preemption kernel before the first real wave pays for it
            self._warm_preempt()
