"""Remote device worker — the scheduler<->JAX-worker shim made a process
boundary.

Reference/north-star lineage: BASELINE.json's design keeps the
apiserver-facing scheduler untouched and crosses a gRPC shim to a JAX
worker ("tensorized snapshot request -> assignment response"); the
in-tree precedent for an out-of-process scheduling hook is the HTTP
extender (pkg/scheduler/extender.go).  Round 1 collapsed the shim into
an in-process BatchBackend; this module restores the network seam
without giving up the resident-state transport:

  * `_WorkerCore` owns the jitted kernels and the resident device state
    (exactly TPUBatchBackend's device half) behind four verbs: /init
    (shape config), /static (full static upload), /refresh (dynamic
    state reset), /step (ONE packed pod+patch buffer in, assignments
    out).  `GrpcDeviceWorker` serves them over gRPC/HTTP-2 — the
    transport the north star names (reference precedent:
    staging/src/k8s.io/cri-api/.../api.proto), each packed buffer one
    gRPC message with identity serializers; `DeviceWorker` is the same
    core over plain HTTP/1.1.
  * `RemoteTPUBatchBackend` IS TPUBatchBackend with the three
    device-touching methods overridden to send the same byte payloads
    (grpc:// or http:// targets) — all host bookkeeping
    (ClusterTensors, encoder, mirror/diff/replay, chunking, preemption
    candidates fall back to local jax) is shared code, so wire format
    and semantics cannot drift.  bench.py's RemoteSeamGrpc config
    measures the seam cost vs in-process (~1.1x on a CPU mesh).

Transport: raw little-endian float32/int32 bodies (the packed buffer is
already a single 1-D f32 array; np.save framing for the array dicts).
The worker is single-tenant and ordered: steps apply to the resident
state in arrival order, which the client guarantees by being the only
writer (same contract the in-process backend's lock provides).
supports_pipelining stays True: /step returns after the device round
trip, so the client's resolve() is a no-op wait — pipelining degrades
gracefully to synchronous, it never corrupts.
"""

from __future__ import annotations

import io
import json
import logging
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from .backend import TPUBatchBackend
from .flatten import Caps

logger = logging.getLogger(__name__)


def _dump_arrays(arrays: dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _load_arrays(blob: bytes) -> dict[str, np.ndarray]:
    return dict(np.load(io.BytesIO(blob)))


class _WorkerCore:
    """The device half of TPUBatchBackend, transport-agnostic: both the
    HTTP DeviceWorker and the gRPC GrpcDeviceWorker serve exactly these
    verbs over the same byte payloads."""

    def __init__(self):
        self._lock = threading.Lock()
        self._backend: TPUBatchBackend | None = None

    def handle(self, path: str, body: bytes):
        with self._lock:
            return self._handle(path, body)

    def _handle(self, path: str, body: bytes):
        if path == "/init":
            cfg = json.loads(body)
            caps = Caps(**cfg["caps"])
            # a plain TPUBatchBackend, used ONLY for its device half —
            # the remote client owns all host bookkeeping
            self._backend = TPUBatchBackend(
                caps, batch_size=cfg["batch_size"],
                weights=cfg.get("weights"), k_cap=cfg.get("k_cap", 1024),
                full_batch_cap=cfg.get("full_batch_cap"))
            # instance override: the CLIENT's setting wins (its resolve()
            # is the half that must retry what a capped kernel leaves)
            self._backend.FULL_MAIN_WAVES = cfg.get(
                "full_main_waves", self._backend.FULL_MAIN_WAVES)
            self._backend._ensure_full()
            if self._backend.FULL_MAIN_WAVES:
                self._backend._ensure_full_small()
            self._backend._ensure_plain()
            return {"ok": True, "full_cap": self._backend.full_cap}
        b = self._backend
        if b is None:
            raise RuntimeError("worker not initialized (/init first)")
        if path == "/static":
            import jax.numpy as jnp

            from .backend import STATIC_CORE, STATIC_SEL
            arrays = _load_arrays(body)
            b._static_node = {k: jnp.asarray(arrays[k]) for k in STATIC_CORE}
            # the worker holds BOTH halves resident (its tensors are empty,
            # so the base _ensure_sel must never try to rebuild from them)
            b._static_sel = {k: jnp.asarray(arrays[k]) for k in STATIC_SEL}
            b._sel_stale = False
            return {"ok": True}
        if path == "/refresh":
            import jax.numpy as jnp
            arrays = _load_arrays(body)
            b._state = {k: jnp.asarray(v) for k, v in arrays.items()}
            return {"ok": True}
        if path.startswith("/step"):
            variant = path.rsplit("=", 1)[-1]
            buf = np.frombuffer(body, np.float32)
            rd = b._device_step(variant, buf)
            return np.asarray(rd).astype(np.int32).tobytes()
        raise RuntimeError(f"unknown verb {path!r}")


class DeviceWorker:
    """The device half of TPUBatchBackend behind HTTP."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._core = _WorkerCore()
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("tpu-worker: " + fmt, *args)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def _reply(self, code: int, body: bytes = b"{}",
                       ctype: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                try:
                    out = server._core.handle(self.path, self._body())
                except Exception as e:  # noqa: BLE001 — report, don't die
                    logger.exception("tpu-worker: %s failed", self.path)
                    self._reply(500, json.dumps(
                        {"error": str(e)}).encode())
                    return
                if isinstance(out, bytes):
                    self._reply(200, out, "application/octet-stream")
                else:
                    self._reply(200, json.dumps(out or {}).encode())

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.httpd.server_address[0]}:{self.port}"

    def start(self) -> "DeviceWorker":
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        name="tpu-worker", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


# gRPC method name <-> worker verb (the reference's process-boundary
# precedent is gRPC: staging/src/k8s.io/cri-api/.../api.proto; the
# messages here are the packed byte buffers themselves — identity
# serializers, no protobuf intermediate copy of a 10+ MB tensor blob)
GRPC_SERVICE = "ktpu.TPUWorker"
_GRPC_VERBS = {
    "Init": "/init",
    "Static": "/static",
    "Refresh": "/refresh",
    "StepFull": "/step?variant=full",
    "StepFullSmall": "/step?variant=full_small",
    "StepPlain": "/step?variant=plain",
}
_GRPC_MSG_CAP = 512 << 20
_GRPC_OPTIONS = [
    ("grpc.max_receive_message_length", _GRPC_MSG_CAP),
    ("grpc.max_send_message_length", _GRPC_MSG_CAP),
]


class GrpcDeviceWorker:
    """The device half of TPUBatchBackend behind gRPC (HTTP/2).

    Same verbs and byte payloads as the HTTP DeviceWorker (shared
    _WorkerCore), but the transport is the one the north star names:
    each packed buffer travels as ONE gRPC message with binary framing —
    no chunked-encoding or content-length ceremony per step."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._core = _WorkerCore()
        core = self._core

        def _unary(verb_path):
            def call(request: bytes, context) -> bytes:
                try:
                    out = core.handle(verb_path, request)
                except Exception as e:  # noqa: BLE001 — report, don't die
                    logger.exception("tpu-worker(grpc): %s failed",
                                     verb_path)
                    context.abort(grpc.StatusCode.INTERNAL, str(e))
                if isinstance(out, bytes):
                    return out
                return json.dumps(out or {}).encode()
            return call

        handlers = {
            name: grpc.unary_unary_rpc_method_handler(_unary(path))
            for name, path in _GRPC_VERBS.items()}
        from concurrent import futures
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=4,
                                       thread_name_prefix="tpu-worker-grpc"),
            options=_GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(GRPC_SERVICE, handlers),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self._host = host

    @property
    def url(self) -> str:
        return f"grpc://{self._host}:{self.port}"

    def start(self) -> "GrpcDeviceWorker":
        self._server.start()
        return self

    def stop(self) -> None:
        self._server.stop(grace=1.0)


class _GrpcTransport:
    """Client side of the gRPC seam: verb path -> unary call with
    identity (bytes) serializers."""

    def __init__(self, target: str, timeout: float):
        import grpc

        self.timeout = timeout
        self._channel = grpc.insecure_channel(target,
                                              options=_GRPC_OPTIONS)
        self._calls = {
            path: self._channel.unary_unary(f"/{GRPC_SERVICE}/{name}")
            for name, path in _GRPC_VERBS.items()}

    def post(self, verb: str, body: bytes) -> bytes:
        return self._calls[verb](body, timeout=self.timeout)

    def close(self) -> None:
        self._channel.close()


class RemoteTPUBatchBackend(TPUBatchBackend):
    """TPUBatchBackend whose device half lives in a DeviceWorker.

    Everything except the three overridden methods is inherited: the
    tensors, encoder, mirror replay, patch diffing, chunking and the
    FLUSH_FIRST protocol run scheduler-side, and the SAME packed bytes
    that would go to a local chip go over the wire.
    """

    def __init__(self, worker_url: str, caps: Caps | None = None,
                 batch_size: int = 256,
                 weights: dict[str, float] | None = None,
                 k_cap: int = 1024, full_batch_cap: int | None = None,
                 timeout: float = 120.0):
        self.worker_url = worker_url.rstrip("/")
        self.timeout = timeout
        self._grpc = None
        if self.worker_url.startswith("grpc://"):
            self._grpc = _GrpcTransport(
                self.worker_url[len("grpc://"):], timeout)
        super().__init__(caps, batch_size=batch_size, weights=weights,
                         k_cap=k_cap, full_batch_cap=full_batch_cap)
        got = self._post("/init", json.dumps({
            "caps": vars(self.caps), "batch_size": batch_size,
            "weights": weights, "k_cap": k_cap,
            "full_batch_cap": self.full_cap,
            # the CLIENT's wave-cap/retry setting governs both halves: the
            # worker must build its main kernel with the same cap the
            # client's resolve() compensates for, or capped-kernel
            # leftovers decode as UNSCHEDULABLE with no retry
            "full_main_waves": self.FULL_MAIN_WAVES}).encode())
        self.full_cap = json.loads(got)["full_cap"]

    def _post(self, verb: str, body: bytes) -> bytes:
        if self._grpc is not None:
            return self._grpc.post(verb, body)
        req = urllib.request.Request(self.worker_url + verb, data=body,
                                     method="POST")
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read()

    # -- the device seam, remoted ---------------------------------------

    def _ensure_full(self):
        if self._spec_full is None:
            from ..models.assign import PackSpec
            self._spec_full = PackSpec(self.caps, self.full_cap,
                                       self._k_cap)
        return None  # the worker holds the fns

    def _ensure_full_small(self):
        if self._spec_full_small is None:
            from ..models.assign import PackSpec
            self._spec_full_small = PackSpec(self.caps, self._retry_cap(),
                                             self._k_cap)
        return None  # the worker holds the fns

    def _ensure_plain(self):
        if self._spec_plain is None:
            from ..models.assign import PackSpec
            self._spec_plain = PackSpec(self.caps, self.batch_size,
                                        self._k_cap, plain=True)
        return None

    def _device_step(self, variant: str, buf: np.ndarray) -> np.ndarray:
        out = self._post(f"/step?variant={variant}",
                         np.ascontiguousarray(buf, np.float32).tobytes())
        return np.frombuffer(out, np.int32)

    def _upload_static(self) -> None:
        t = self.tensors
        self._post("/static", _dump_arrays({
            "alloc": t.alloc, "maxpods": t.maxpods, "valid": t.valid,
            "taint_mask": t.taint_mask, "label_mask": t.label_mask,
            "key_mask": t.key_mask, "dom_sg": t.dom_sg,
            "dom_asg": t.dom_asg}))
        self._static_node = True  # sentinel: worker holds the arrays
        t.static_dirty_rows = set()
        t.static_full = False
        self._static_version = t.static_version

    def _full_refresh(self, cd_sg: np.ndarray, cd_asg: np.ndarray) -> None:
        t = self.tensors
        self._post("/refresh", _dump_arrays({
            "used": t.used, "used_nz": t.used_nz, "npods": t.npods,
            "port_mask": t.port_mask, "cd_sg": cd_sg, "cd_asg": cd_asg}))
        self._state = True  # sentinel: worker holds the arrays
        self._mirror_from_tensors(cd_sg, cd_asg)
        self.stats["full_refresh"] += 1

    def warmup(self) -> None:
        with self._lock:
            if self._static_node is None:
                self._upload_static()
            if self._state is None:
                cd_sg, cd_asg = self.tensors.domain_base_counts()
                self._full_refresh(cd_sg, cd_asg)
            from ..models.assign import pack_pod_batch
            from .flatten import slice_pod_batch
            batch = self.encoder.encode([])
            empty = (np.empty(0, np.int32),
                     np.empty((0, self._f_patch), np.float32))
            self._ensure_full()
            self._device_step("full", pack_pod_batch(
                slice_pod_batch(batch, 0, 0, self.full_cap),
                self._spec_full, *empty))
            if self.FULL_MAIN_WAVES:
                # compile the straggler retry kernel now, not inside the
                # first straggler-carrying resolve()
                self._ensure_full_small()
                self._device_step("full_small", pack_pod_batch(
                    slice_pod_batch(batch, 0, 0, self._retry_cap()),
                    self._spec_full_small, *empty))
            self._ensure_plain()
            self._device_step("plain", pack_pod_batch(
                batch, self._spec_plain, *empty))
