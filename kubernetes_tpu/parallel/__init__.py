"""Device mesh + sharded hot path."""

from .mesh import NODE_AXIS, build_sharded_assign_fn, make_mesh  # noqa: F401
