"""Sharded batch backend: the scheduler's device path over a device Mesh.

SURVEY.md §2.6/§5: the node axis is this workload's long axis; sharding
it over the mesh is our sequence-parallelism analog.

This is the multi-chip realization of the BatchBackend contract
(scheduler/scheduler.py): the node axis shards across the mesh
(parallel/mesh.py shard_map, XLA ICI collectives), the pod batch and
domain-count tables replicate, and the whole Filter/Score/Assign step runs
as ONE jitted program per batch.  Used for multi-chip execution and the
driver's dryrun; the single-chip TPUBatchBackend (ops/backend.py) remains
the latency-optimized path (resident device state + packed transport) on
one chip.

Unlike the packed backend it re-uploads the node-side arrays per batch —
multi-host transports stage via each host's local devices, so the resident
single-buffer trick does not apply; snapshot deltas still keep the HOST
side incremental (ClusterTensors dirty-row re-encode).
"""

from __future__ import annotations

import logging
import threading
from typing import Sequence

import numpy as np

from ..ops.backend import decode_results
from ..ops.flatten import BatchEncoder, Caps, ClusterTensors, VocabFullError
from ..scheduler.cache import Snapshot
from ..scheduler.scheduler import BatchBackend
from ..scheduler.types import SKIP, PodInfo, Status
from .mesh import build_sharded_assign_fn, make_mesh, pod_specs

logger = logging.getLogger(__name__)

POD_KEYS = tuple(pod_specs())


class ShardedTPUBatchBackend(BatchBackend):
    # node arrays are rebuilt from the host snapshot per batch (no resident
    # device-state chaining), so an unresolved batch's placements are
    # invisible to the next dispatch: the scheduler must finish k before
    # dispatching k+1
    supports_pipelining = False

    def __init__(self, caps: Caps | None = None, batch_size: int = 256,
                 weights: dict[str, float] | None = None, mesh=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.caps = caps or Caps()
        n_dev = self.mesh.devices.size
        if self.caps.n_cap % n_dev != 0:
            raise ValueError(
                f"n_cap {self.caps.n_cap} must divide by {n_dev} devices")
        self.batch_size = batch_size
        self.tensors = ClusterTensors(self.caps)
        self.encoder = BatchEncoder(self.tensors, batch_size)
        self._fn = build_sharded_assign_fn(self.caps, self.mesh, weights)
        self._shardings = self._make_shardings()
        self._lock = threading.Lock()
        self.stats = {"batches": 0, "waves": 0}

    def _make_shardings(self):
        from jax.sharding import NamedSharding

        from .mesh import node_specs, pod_specs
        ns, ps = node_specs(), pod_specs()
        return ({k: NamedSharding(self.mesh, v) for k, v in ns.items()},
                {k: NamedSharding(self.mesh, v) for k, v in ps.items()})

    def _node_arrays(self):
        import jax
        t = self.tensors
        cd_sg, cd_asg = t.domain_base_counts()
        raw = {
            "alloc": t.alloc, "used": t.used, "used_nz": t.used_nz,
            "npods": t.npods, "maxpods": t.maxpods, "valid": t.valid,
            "taint_mask": t.taint_mask, "label_mask": t.label_mask,
            "key_mask": t.key_mask, "port_mask": t.port_mask,
            "dom_sg": t.dom_sg, "dom_asg": t.dom_asg,
            "cd_sg": cd_sg, "cd_asg": cd_asg,
        }
        shard = self._shardings[0]
        return {k: jax.device_put(v, shard[k]) for k, v in raw.items()}

    # -- BatchBackend -----------------------------------------------------

    def dispatch(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot):
        import jax
        with self._lock:
            try:
                self.tensors.update_from_snapshot(snapshot)
                batch = self.encoder.encode(list(pod_infos))
            except VocabFullError as e:
                logger.warning("tensorization overflow (%s); batch -> "
                               "oracle path", e)
                results = [(None, Status(SKIP, str(e)))] * len(pod_infos)
                return lambda: results
            node_arrays = self._node_arrays()
            pshard = self._shardings[1]
            pod_arrays = {k: jax.device_put(getattr(batch, k), pshard[k])
                          for k in POD_KEYS}
            out = self._fn(node_arrays, pod_arrays)
            self.stats["batches"] += 1
            row_infos = list(self.tensors.node_infos)  # view at dispatch

        n = len(pod_infos)

        def resolve():
            assignments = np.asarray(out["assignments"])
            with self._lock:
                self.stats["waves"] += int(np.asarray(out["waves"]))
            return decode_results(assignments, n, self.batch_size,
                                  set(batch.escape), row_infos,
                                  "no feasible node (sharded batch filter)")

        return resolve

    def assign(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot):
        return self.dispatch(pod_infos, snapshot)()
