"""Sharded batch backend: the scheduler's device path over a device Mesh.

SURVEY.md §2.6/§5: the node axis is this workload's long axis; sharding
it over the mesh is our sequence-parallelism analog.

This is the multi-chip realization of the BatchBackend contract
(scheduler/scheduler.py): the node axis shards across the mesh
(parallel/mesh.py shard_map, XLA ICI collectives), the pod batch and
domain-count tables replicate, and the whole Filter/Score/Assign step
runs as ONE jitted program per batch.

Round 2 ported the single-chip backend's transport design here
(VERDICT r1 weak #3): node DYNAMICS (used/npods/ports/domain counts)
live resident on the mesh as donated sharded buffers chained batch to
batch; a host mirror replays the kernel's commit rules; external changes
ride a bounded replicated row-patch upload that each shard applies to
its own slab (no collective); statics re-upload only on static_version
changes.  supports_pipelining is True under the same FLUSH_FIRST
protocol as ops/backend.py — steady state moves ZERO node-side bytes.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Sequence

import numpy as np

from ..component_base.timeline import default_timeline
from ..ops.backend import (
    FLUSH_FIRST, ResidentHostMirror, decode_results, record_batch_stats,
)
from ..ops.flatten import BatchEncoder, Caps, ClusterTensors, VocabFullError
from ..scheduler.cache import Snapshot
from ..scheduler.scheduler import BatchBackend
from ..scheduler.types import SKIP, PodInfo, Status
from .mesh import (
    STATE_KEYS, STATIC_KEYS, build_sharded_step_fn, make_mesh, pod_specs,
    state_specs, static_specs,
)

logger = logging.getLogger(__name__)

POD_KEYS = tuple(pod_specs())


class ShardedTPUBatchBackend(ResidentHostMirror, BatchBackend):
    # resident device-state chaining (donated sharded buffers): batch k+1
    # may dispatch while k is in flight, as long as no patch/refresh is
    # needed — the same contract as the single-chip backend
    supports_pipelining = True
    census_kind = "sharded"

    def __init__(self, caps: Caps | None = None, batch_size: int = 256,
                 weights: dict[str, float] | None = None, mesh=None,
                 k_cap: int = 1024):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.caps = caps or Caps()
        n_dev = self.mesh.devices.size
        if self.caps.n_cap % n_dev != 0:
            # shard_map needs an even node split; round up instead of
            # making operators do mesh math (100k nodes on 8 devices
            # just works, at the cost of a few padding rows)
            from .census import round_caps_to_mesh
            n_was = self.caps.n_cap
            round_caps_to_mesh(self.caps, n_dev)
            logger.warning(
                "n_cap %d not divisible by %d devices; rounded up to %d "
                "(%.2f%% padding overhead)", n_was, n_dev, self.caps.n_cap,
                100.0 * (self.caps.n_cap - n_was) / n_was)
        self.batch_size = batch_size
        self.tensors = ClusterTensors(self.caps)
        self.encoder = BatchEncoder(self.tensors, batch_size)
        self._weights = weights
        self._fn = build_sharded_step_fn(self.caps, self.mesh, weights,
                                         k_cap=k_cap)
        self._fn_plain = None  # lazily built; most batches are plain
        self._k_cap = k_cap
        self._f_patch = 2 * self.caps.r + 1 + self.caps.pt_cap
        self._shardings = self._make_shardings()
        self._lock = threading.Lock()
        self._state = None          # sharded device arrays (STATE_KEYS)
        self._static_node = None    # sharded device arrays (STATIC_KEYS)
        self._static_version = -1
        self._mirror: dict[str, np.ndarray] | None = None
        self._unresolved: list[object] = []
        self._carry_dirty: set[int] = set()
        self._last_epoch: int | None = None  # see ops/backend.py epoch skip
        # host expectation of the device state-generation counter (the
        # resolve fence — see ops/backend.py)
        self._gen = 0
        # steady-state pipeline fence (see ops/backend.py): >0 while a
        # fenced wave — dispatched with its patches deliberately held
        # back in the mirror — has not yet resolved and replayed
        self._fence_pending = 0
        # A/B baseline knob — see ops/backend.py
        self.FORCE_REFLATTEN = bool(os.environ.get("KTPU_FORCE_REFLATTEN"))
        self.stats = {"batches": 0, "waves": 0, "full_refresh": 0,
                      "patched_rows": 0, "flush_first": 0,
                      "waves_patched": 0, "waves_reflattened": 0,
                      "event_patches": 0, "patch_seconds": 0.0,
                      "flatten_seconds": 0.0}

    def _make_shardings(self):
        from jax.sharding import NamedSharding

        return ({k: NamedSharding(self.mesh, v)
                 for k, v in state_specs().items()},
                {k: NamedSharding(self.mesh, v)
                 for k, v in static_specs().items()},
                {k: NamedSharding(self.mesh, v)
                 for k, v in pod_specs().items()})

    # -- namespace events ------------------------------------------------

    def note_namespace_event(self, event_type: str, obj, old=None) -> None:
        """Namespace informer feed — see ops/backend.py; keeps the
        namespaceSelector resolution cache coherent between batches."""
        with self._lock:
            self.tensors.note_namespace(obj, deleted=event_type == "DELETED")

    # -- device sync -----------------------------------------------------

    def warmup(self) -> None:
        """Compile the sharded step and initialize resident state before
        the first real batch."""
        with self._lock:
            if self._static_node is None:
                self._upload_static()
            if self._state is None:
                cd_sg, cd_asg = self.tensors.domain_base_counts()
                self._full_refresh(cd_sg, cd_asg)
            batch = self.encoder.encode([])
            # trace BOTH variants: an all-invalid batch leaves the
            # resident state numerically unchanged, and paying the full
            # kernel's multi-second XLA compile here beats paying it
            # inside the first constraint-carrying scheduling cycle
            prows, pvals = self._empty_patches()
            # the step DONATES its pod transport (mesh.py): each trace
            # needs its own freshly-placed pod arrays — reusing the
            # first call's would read deleted buffers
            self._state, a, _w, _g = self._fn(
                self._state, self._static_node, self._pod_arrays(batch),
                prows, pvals)
            self._gen += 1
            self._state, a, _w, _g = self._ensure_plain()(
                self._state, self._static_node, self._pod_arrays(batch),
                # donate-ok: host-side np patch arrays; each call's jit
                # conversion places (and donates) fresh device copies
                prows, pvals)
            self._gen += 1
            import jax
            # sync-point: warmup barrier — block until the round trips land
            jax.device_get(a)

    def _empty_patches(self):
        return (np.full(self._k_cap, -1, np.int32),
                np.zeros((self._k_cap, self._f_patch), np.float32))

    def _pod_arrays(self, batch):
        """Shard/replicate the pod-side arrays, materializing lazy
        (None == zeros) PodBatch fields first."""
        import jax
        pshard = self._shardings[2]
        return {k: jax.device_put(v, pshard[k])
                for k, v in batch.materialized(self.caps,
                                               POD_KEYS).items()}

    def _upload_static(self) -> None:
        import jax
        t = self.tensors
        raw = {"alloc": t.alloc, "maxpods": t.maxpods, "valid": t.valid,
               "taint_mask": t.taint_mask, "label_mask": t.label_mask,
               "key_mask": t.key_mask, "dom_sg": t.dom_sg,
               "dom_asg": t.dom_asg, "sg_ns_mask": t.sg_ns_mask,
               "asg_ns_mask": t.asg_ns_mask}
        shard = self._shardings[1]
        self._static_node = {k: jax.device_put(v, shard[k])
                             for k, v in raw.items()}
        t.static_dirty_rows = set()  # full upload covers them
        t.static_full = False
        self._static_version = t.static_version

    def _full_refresh(self, cd_sg: np.ndarray, cd_asg: np.ndarray) -> None:
        import jax
        t = self.tensors
        raw = {"used": t.used, "used_nz": t.used_nz, "npods": t.npods,
               "port_mask": t.port_mask, "cd_sg": cd_sg, "cd_asg": cd_asg,
               "gen": np.int32(self._gen)}
        shard = self._shardings[0]
        self._state = {k: jax.device_put(v, shard[k])
                       for k, v in raw.items()}
        self._mirror_from_tensors(cd_sg, cd_asg)
        self.stats["full_refresh"] += 1

    def _restore_state_from_mirror(self) -> None:
        """Gen-stale recovery (see ops/backend.py): re-seed the sharded
        device state from the host mirror on a fresh generation lineage."""
        import jax
        self._gen += 1
        m = self._mirror
        shard = self._shardings[0]
        state = {k: jax.device_put(m[k], shard[k])
                 for k in ("used", "used_nz", "npods", "port_mask",
                           "cd_sg", "cd_asg")}
        state["gen"] = jax.device_put(np.int32(self._gen), shard["gen"])
        self._state = state
        self.stats["gen_recoveries"] = self.stats.get("gen_recoveries", 0) + 1

    def _ensure_plain(self):
        if self._fn_plain is None:
            from ..models.assign import PLAIN_FEATURES
            self._fn_plain = build_sharded_step_fn(
                self.caps, self.mesh, self._weights, k_cap=self._k_cap,
                features=PLAIN_FEATURES)
        return self._fn_plain

    def device_census(self, batch_size: int | None = None,
                      variants: Sequence[str] = ("full", "plain")) -> dict:
        """Static cost census of the compiled sharded step: lower each
        variant at the census shapes (parallel/census.py — the SAME
        shapes tools/collective_census.py pins, so the exported gauges
        match the offline tool bit-for-bit) and walk its optimized HLO.
        Costs a fresh AOT compile per variant — callers reach this only
        through the profiling: stanza (Scheduler.run_device_census)."""
        from .census import census_step_fn
        b = batch_size or self.batch_size
        out = {}
        for v in variants:
            fn = self._fn if v == "full" else self._ensure_plain()
            out[v] = census_step_fn(fn, self.caps, b, self._k_cap)
        return out

    def _dispatch_locked(self, batch, prows, pvals):
        """Async sharded step: donates the current state and immediately
        re-points self._state at the returned (future) arrays, so a
        pipelined next batch chains off them without waiting.  Plain
        batches (no selectors/constraints/ports/pins) run the
        constraint-elided variant — same split as the single-chip
        backend's _needs_full."""
        pod_arrays = self._pod_arrays(batch)
        fn = self._fn if self._needs_full(batch) else self._ensure_plain()
        self._state, assignments, waves, gen_dev = fn(
            self._state, self._static_node, pod_arrays, prows, pvals)
        self._gen += 1  # the kernel computes the identical state.gen + 1
        for h in (assignments, waves, gen_dev):
            copy_async = getattr(h, "copy_to_host_async", None)
            if copy_async is not None:  # see ops/backend.py _device_step
                copy_async()
        return assignments, waves, gen_dev

    # -- BatchBackend ----------------------------------------------------

    def dispatch(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot):
        with self._lock:
            if self._warm_pending:
                self._warm_sweep(snapshot)
            # epoch fast path (see ops/backend.py dispatch): unchanged
            # cache epoch == all changes since last sync were our own
            # replayed binds — skip the O(nodes) re-encode + diff
            epoch_fn = getattr(snapshot, "epoch", None)
            epoch = epoch_fn() if epoch_fn is not None else None
            skip_sync = (epoch is not None and self._state is not None
                         and epoch == self._last_epoch
                         and not self._carry_dirty
                         and not self.FORCE_REFLATTEN)
            try:
                if skip_sync:
                    dirty = set()
                else:
                    t_sync = time.monotonic()
                    try:
                        dirty = set(
                            self.tensors.update_from_snapshot_tracked(
                                snapshot))
                    finally:
                        t_sync_end = time.monotonic()
                        self.stats["flatten_seconds"] += t_sync_end - t_sync
                        if default_timeline.enabled:
                            # wave timeline: host tensor-maintenance leg
                            default_timeline.record("patch", t_sync,
                                                    t_sync_end)
                    dirty |= self._carry_dirty
                    self._last_epoch = epoch
                batch = self.encoder.encode(list(pod_infos))
            except VocabFullError as e:
                logger.warning("tensorization overflow (%s); batch -> "
                               "oracle path", e)
                self._state = None
                self._carry_dirty = set()
                results = [(None, Status(SKIP, str(e)))] * len(pod_infos)
                return lambda: results

            inflight = bool(self._unresolved)
            # deterministic compaction point (see ops/backend.py):
            # tombstone reclamation is anchored to the wave boundary so
            # free-list order — and therefore row tie-breaks — cannot
            # depend on pipeline depth
            if (self.tensors.tombstone_count() * self.COMPACT_TOMBSTONE_DIV
                    >= self.caps.n_cap):
                if inflight:
                    self._carry_dirty = dirty
                    self.stats["flush_first"] += 1
                    return FLUSH_FIRST
                if self.tensors.compact():
                    self.stats["compactions"] = self.stats.get(
                        "compactions", 0) + 1
            static_changed = (self._static_version
                              != self.tensors.static_version)
            if skip_sync and not static_changed:
                patches = (np.empty(0, np.int32),
                           np.empty((0, self._f_patch), np.float32))
                needs_refresh = needs_patch = False
            else:
                cd_sg, cd_asg = self.tensors.domain_base_counts()
                patches = None
                have_state = self._state is not None
                if have_state and self._mirror is not None:
                    if (np.array_equal(cd_sg, self._mirror["cd_sg"])
                            and np.array_equal(cd_asg, self._mirror["cd_asg"])):
                        patches = self._diff_patches(sorted(dirty))
                needs_refresh = not have_state or patches is None
                needs_patch = patches is not None and len(patches[0]) > 0
            # pipeline admission (see ops/backend.py for the full
            # derivation): a full re-encode or static change never
            # overlaps an in-flight wave, and only one fenced wave rides
            # the pipeline at a time.  A dynamic row patch while clean
            # dispatches FENCED — the patch lands in the mirror, gen is
            # bumped so this wave's first run provably trips the fence,
            # and the authoritative result comes from the mirror-restored
            # replay at its resolve.
            will_fence = False
            if inflight and (needs_refresh or static_changed):
                # static never fences (see ops/backend.py): a retained
                # wave's re-run at resolve would read the swapped static
                # arrays — future node state against a past wave
                self._carry_dirty = dirty
                self.stats["flush_first"] += 1
                return FLUSH_FIRST
            if inflight and needs_patch:
                if self._fence_pending:
                    self._carry_dirty = dirty
                    self.stats["flush_first"] += 1
                    return FLUSH_FIRST
                will_fence = True

            if static_changed:
                # pipeline is empty here (static change over an in-flight
                # wave flushed above): no retained wave can replay
                # against these swapped arrays
                self._upload_static()
            if needs_refresh:
                self._full_refresh(cd_sg, cd_asg)
                prows, pvals = self._empty_patches()
            elif needs_patch:
                self._sync_mirror_rows(patches[0])
                prows, pvals = self._empty_patches()
                if will_fence:
                    # patch VALUES travel via the mirror rows just
                    # synced, never via the retained upload: the
                    # in-flight predecessor's replay ADDs its commits
                    # onto those rows before this wave's re-run, and a
                    # buffer-borne patch would SET them back, wiping it
                    self.stats["patched_rows"] += len(patches[0])
                else:
                    k = len(patches[0])
                    prows[:k] = patches[0]
                    pvals[:k] = patches[1]
                    self.stats["patched_rows"] += k
            else:
                prows, pvals = self._empty_patches()
            if will_fence:
                self._gen += 1  # guarantee this wave's fence trips
                self._fence_pending += 1
                self.stats["fenced_waves"] = self.stats.get(
                    "fenced_waves", 0) + 1
            # tentpole accounting: did this wave ride the patch path or
            # pay a full re-flatten/refresh of the device tensors?
            self.stats["waves_reflattened" if needs_refresh
                       else "waves_patched"] += 1
            self._carry_dirty = set()

            t_h2d = time.monotonic()
            assignments_dev, waves_dev, gen_dev = self._dispatch_locked(
                batch, prows, pvals)
            t_launch = time.monotonic()
            if default_timeline.enabled:
                # wave timeline: pack + shard upload + kernel enqueue
                default_timeline.record("h2d", t_h2d, t_launch)
            expect_gen = self._gen
            self.stats["batches"] += 1
            holder = object()
            self._unresolved.append(holder)
            # names, not NodeInfos: live NodeInfos can have .node nulled
            # in place mid-wave (cache drain of a node still holding pods)
            row_names = list(self.tensors.row_names)  # view at dispatch

        n = len(pod_infos)

        def resolve():
            nonlocal will_fence
            import jax
            try:
                with self._lock:
                    t_d2h0 = time.monotonic()
                    # sync-point: sharded wave resolve — the pipeline's
                    # d2h pull
                    assignments, waves, gen = jax.device_get(
                        (assignments_dev, waves_dev, gen_dev))
                    if int(gen) != expect_gen or will_fence:
                        # generation fence tripped: the resident lineage
                        # this wave chained off is not the one the host
                        # mirrored.  Re-seed device state from the mirror
                        # and replay the batch synchronously on the fresh
                        # lineage.  For a fenced wave this IS the
                        # steady-state discipline (its dispatch held the
                        # patches back in the mirror on purpose), not an
                        # anomaly.
                        if will_fence:
                            self.stats["fence_replays"] = self.stats.get(
                                "fence_replays", 0) + 1
                        else:
                            logger.warning(
                                "sharded state generation mismatch "
                                "(device %d, expected %d); re-seeding "
                                "from host mirror", int(gen), expect_gen)
                            self.stats["gen_stale_waves"] = (
                                self.stats.get("gen_stale_waves", 0) + 1)
                        self._restore_state_from_mirror()
                        a_dev, w_dev, _g = self._dispatch_locked(
                            batch, prows, pvals)
                        # sync-point: gen-stale recovery replay
                        assignments, waves = jax.device_get((a_dev, w_dev))
                    if default_timeline.enabled:
                        # wave timeline: device-step launch -> results
                        # landed (recovery replay included); d2h is the
                        # blocking pull inside it
                        t_dev_end = time.monotonic()
                        default_timeline.record("device-step", t_launch,
                                                t_dev_end)
                        default_timeline.record("d2h", t_d2h0, t_dev_end)
                    self.stats["waves"] += int(waves)
                    self._replay(batch, assignments)
                    try:
                        self._unresolved.remove(holder)
                    except ValueError:  # pragma: no cover - double resolve
                        pass
            finally:
                # the fence slot frees even on a failed resolve, or every
                # future patch dispatch wedges behind FLUSH_FIRST
                if will_fence:
                    self._fence_pending = max(0, self._fence_pending - 1)
                    will_fence = False
            out = decode_results(
                assignments, n, self.batch_size, set(batch.escape),
                row_names, "no feasible node (sharded batch filter)",
                nofit_escapes=set(batch.nofit_oracle))
            record_batch_stats(self.stats, self._lock, out, n)
            return out

        return resolve

    def abandon_wave(self) -> None:
        """Stuck-wave watchdog cancel (see ops/backend.py abandon_wave:
        same best-effort lock and the same safety argument).  Drops the
        pipeline bookkeeping, the resident sharded state, and any
        pending fence; the next dispatch full-refreshes from the
        authoritative cache view."""
        got = self._lock.acquire(timeout=0.1)
        try:
            self._unresolved.clear()
            self._state = None
            self._last_epoch = None
            self._fence_pending = 0
            self.stats["abandoned_waves"] = (
                self.stats.get("abandoned_waves", 0) + 1)
        finally:
            if got:
                self._lock.release()

    def assign(self, pod_infos: Sequence[PodInfo], snapshot: Snapshot):
        resolve = self.dispatch(pod_infos, snapshot)
        if resolve is FLUSH_FIRST:  # pragma: no cover - sync caller
            raise RuntimeError("FLUSH_FIRST with no pipelined caller")
        return resolve()
