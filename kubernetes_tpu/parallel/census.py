"""Runtime device cost census for the sharded scheduling step.

One shared lowering path serves three callers — the offline CLI
(tools/collective_census.py), ShardedTPUBatchBackend.device_census(),
and the parity test (tests/test_profiling.py) — so the committed
`tpu_wave_collective_bytes` gauges and the tool output agree bit-for-bit
by construction: same fn builder, same abstract input shapes, same HLO
walk (component_base/profiling.census_from_hlo).

Nothing here executes on a device; lowering is shape-exact, so the
counts/bytes are the ones a real v5e-8 would run.

Reference: no upstream analogue (the reference scheduler has no device
kernel to census); the gauges it feeds follow the
staging/src/k8s.io/component-base/metrics export contract.
"""

from __future__ import annotations

from ..component_base import profiling
from ..ops.flatten import Caps


def round_caps_to_mesh(caps: Caps, n_dev: int) -> Caps:
    """Round n_cap up to a mesh multiple (shard_map needs an even node
    split); mutates and returns caps, mirroring the backend's own
    divisibility requirement."""
    if caps.n_cap % n_dev:
        caps.n_cap += n_dev - caps.n_cap % n_dev
    return caps


def abstract_step_inputs(caps: Caps, batch: int, k_cap: int = 1024):
    """Shape-only abstract inputs (state, static, pods, prows, pvals)
    for build_sharded_step_fn at a given pod-batch size — the single
    definition of the lowering shapes the census is pinned at."""
    import jax
    import jax.numpy as jnp

    c = caps
    P_, R, PT = batch, c.r, c.pt_cap

    def zeros(shape, dtype=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dtype)

    state = {"used": zeros((c.n_cap, R)), "used_nz": zeros((c.n_cap, R)),
             "npods": zeros((c.n_cap,)), "port_mask": zeros((c.n_cap, PT)),
             "cd_sg": zeros((c.sg_cap, c.n_cap)),
             "cd_asg": zeros((c.asg_cap, c.n_cap)),
             "gen": zeros((), jnp.int32)}
    static = {"alloc": zeros((c.n_cap, R)), "maxpods": zeros((c.n_cap,)),
              "valid": zeros((c.n_cap,), jnp.bool_),
              "taint_mask": zeros((c.n_cap, c.t_cap)),
              "label_mask": zeros((c.n_cap, c.l_cap)),
              "key_mask": zeros((c.n_cap, c.kl_cap)),
              "dom_sg": zeros((c.sg_cap, c.n_cap), jnp.int32),
              "dom_asg": zeros((c.asg_cap, c.n_cap), jnp.int32),
              "sg_ns_mask": zeros((c.sg_cap, c.ns_cap + 1)),
              "asg_ns_mask": zeros((c.asg_cap, c.ns_cap + 1))}
    pods = {"req": zeros((P_, R)), "req_nz": zeros((P_, R)),
            "p_valid": zeros((P_,), jnp.bool_),
            "untol_hard": zeros((P_, c.t_cap)),
            "untol_prefer": zeros((P_, c.t_cap)),
            "sel_any": zeros((P_, c.g_cap, c.l_cap)),
            "sel_any_active": zeros((P_, c.g_cap)),
            "sel_forb": zeros((P_, c.l_cap)),
            "key_any": zeros((P_, c.kg_cap, c.kl_cap)),
            "key_any_active": zeros((P_, c.kg_cap)),
            "key_forb": zeros((P_, c.kl_cap)),
            "ports": zeros((P_, PT)),
            "node_row": zeros((P_,), jnp.int32),
            "c_kind": zeros((P_, c.c_cap), jnp.int32),
            "c_sg": zeros((P_, c.c_cap), jnp.int32),
            "c_maxskew": zeros((P_, c.c_cap)),
            "c_selfmatch": zeros((P_, c.c_cap)),
            "c_weight": zeros((P_, c.c_cap)),
            "inc_sg": zeros((P_, c.sg_cap)),
            "inc_asg": zeros((P_, c.asg_cap)),
            "match_asg": zeros((P_, c.asg_cap)),
            "pod_ns": zeros((P_,), jnp.int32)}
    prows = zeros((k_cap,), jnp.int32)
    pvals = zeros((k_cap, 2 * R + 1 + PT))
    return state, static, pods, prows, pvals


def census_step_fn(fn, caps: Caps, batch: int, k_cap: int = 1024) -> dict:
    """Lower + compile one sharded step fn at the census shapes and walk
    its optimized HLO (profiling.census_lowered)."""
    return profiling.census_lowered(
        fn.lower(*abstract_step_inputs(caps, batch, k_cap)))


def sharded_census(nodes: int, batch: int, variant: str,
                   weights: dict[str, float] | None = None,
                   k_cap: int = 1024) -> dict:
    """The offline-tool entry point: build the sharded step at bench
    shapes (perf.caps_for_nodes, mesh-rounded) and census it.  Assumes
    jax is already bootstrapped onto the virtual mesh
    (profiling.ensure_virtual_mesh)."""
    import jax

    from ..models.assign import ALL_FEATURES, PLAIN_FEATURES
    from ..perf import caps_for_nodes
    from .mesh import build_sharded_step_fn, make_mesh

    caps = round_caps_to_mesh(caps_for_nodes(nodes), len(jax.devices()))
    mesh = make_mesh()
    features = PLAIN_FEATURES if variant == "plain" else ALL_FEATURES
    fn = build_sharded_step_fn(caps, mesh, weights, k_cap=k_cap,
                               features=features)
    rec = census_step_fn(fn, caps, batch, k_cap)
    return {"nodes": nodes, "batch": batch, "variant": variant,
            "mesh_devices": len(jax.devices()), "n_cap": caps.n_cap, **rec}
