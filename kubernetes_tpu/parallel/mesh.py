"""Device-mesh sharding of the scheduler hot path.

The node axis is the "long axis" of this workload (100k+ nodes); it shards
across TPU cores the way sequence parallelism shards tokens (SURVEY.md §5):
each core owns a contiguous slab of node rows, computes local feasibility +
scores, and placement is a per-core top-1 + all_gather + global pick.  The
running-sum state (used/npods/ports) lives sharded; the small domain-count
tables (cd_sg/cd_asg) are replicated and kept coherent with a psum of the
winning shard's domain ids.  All collectives are XLA ICI collectives — no
NCCL on TPU (reference's comm backbone analysis: SURVEY.md §2.6).

Multi-host: jax.distributed.initialize() + the same Mesh spanning all
processes gives DCN+ICI automatically; nothing here changes.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.assign import make_assign_core
from ..ops.flatten import Caps

NODE_AXIS = "nodes"


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def node_specs(axis: str = NODE_AXIS) -> dict:
    """PartitionSpec per node-side array (the real tp-style shardings)."""
    return {
        "alloc": P(axis, None), "used": P(axis, None), "used_nz": P(axis, None),
        "npods": P(axis), "maxpods": P(axis), "valid": P(axis),
        "taint_mask": P(axis, None), "label_mask": P(axis, None),
        "key_mask": P(axis, None), "port_mask": P(axis, None),
        "dom_sg": P(None, axis), "dom_asg": P(None, axis),
        # per-domain count tables are small and replicated
        "cd_sg": P(), "cd_asg": P(),
        # per-group namespace membership masks have no node axis: replicated
        "sg_ns_mask": P(), "asg_ns_mask": P(),
    }


def pod_specs() -> dict:
    """Pod-side arrays are replicated (the batch is small)."""
    keys = ["req", "req_nz", "p_valid", "untol_hard", "untol_prefer",
            "sel_any", "sel_any_active", "sel_forb", "key_any",
            "key_any_active", "key_forb", "ports", "node_row", "c_kind",
            "c_sg", "c_maxskew", "c_selfmatch", "c_weight", "inc_sg",
            "inc_asg", "match_asg", "pod_ns"]
    return {k: P() for k in keys}


STATE_KEYS = ("used", "used_nz", "npods", "port_mask", "cd_sg", "cd_asg")
STATIC_KEYS = ("alloc", "maxpods", "valid", "taint_mask", "label_mask",
               "key_mask", "dom_sg", "dom_asg", "sg_ns_mask", "asg_ns_mask")


def state_specs(axis: str = NODE_AXIS) -> dict:
    ns = node_specs(axis)
    return {k: ns[k] for k in STATE_KEYS}


def static_specs(axis: str = NODE_AXIS) -> dict:
    ns = node_specs(axis)
    return {k: ns[k] for k in STATIC_KEYS}


def build_sharded_step_fn(caps: Caps, mesh: Mesh,
                          weights: dict[str, float] | None = None,
                          axis: str = NODE_AXIS, k_cap: int = 1024,
                          features=None):
    """Resident-state sharded step: fn(state, static, pods, prows, pvals)
    -> (new_state, assignments, waves), with `state` DONATED and returned
    updated — the multi-chip twin of the single-chip packed kernel's
    resident-dynamics design (ops/backend.py transport notes).

    prows i32[k_cap] are GLOBAL node rows to overwrite from pvals
    f32[k_cap, 2R+1+PT] (used | used_nz | npods | port_mask — the same
    patch layout as models/assign.PackSpec.f_patch) before the wave
    solve; -1 rows are padding.  Each shard applies only the patches that
    land in its slab, so the upload is replicated but the scatter is
    local — no collective needed.
    """
    import jax.numpy as jnp

    n_shards = mesh.devices.size
    if caps.n_cap % n_shards != 0:
        raise ValueError(f"n_cap {caps.n_cap} not divisible by {n_shards}")
    shard_n = caps.n_cap // n_shards
    R, PT = caps.r, caps.pt_cap
    from ..models.assign import ALL_FEATURES
    core = make_assign_core(
        caps, weights, axis_name=axis,
        features=ALL_FEATURES if features is None else features)

    def stepped(state, static, pods, prows, pvals):
        local = prows - jax.lax.axis_index(axis) * shard_n
        in_shard = (prows >= 0) & (local >= 0) & (local < shard_n)
        # out-of-shard/padding entries scatter to an out-of-bounds
        # sentinel and are DROPPED — a masked write of row 0 would race
        # a genuine patch of row 0 through duplicate-index set()
        li = jnp.where(in_shard, local, shard_n)

        def put(arr, vals):
            return arr.at[li].set(vals, mode="drop")

        node = dict(static)
        node["used"] = put(state["used"], pvals[:, :R])
        node["used_nz"] = put(state["used_nz"], pvals[:, R:2 * R])
        node["npods"] = put(state["npods"], pvals[:, 2 * R])
        node["port_mask"] = put(state["port_mask"],
                                pvals[:, 2 * R + 1:2 * R + 1 + PT])
        node["cd_sg"] = state["cd_sg"]
        node["cd_asg"] = state["cd_asg"]
        out = core(node, pods)
        new_state = {k: out[k] for k in STATE_KEYS}
        return new_state, out["assignments"], out["waves"]

    ss, st = state_specs(axis), static_specs(axis)
    fn = jax.shard_map(
        stepped, mesh=mesh,
        in_specs=(ss, st, pod_specs(), P(), P()),
        out_specs=(ss, P(), P()),
        check_vma=False,
    )
    # compile-cached: built once per mesh at backend setup; the caller
    # holds the returned callable (and its jit cache) for every wave
    return jax.jit(fn, donate_argnums=(0,))


def build_sharded_assign_fn(caps: Caps, mesh: Mesh,
                            weights: dict[str, float] | None = None,
                            axis: str = NODE_AXIS):
    """shard_map'd assignment over the node axis. caps.n_cap must divide
    evenly by the mesh size."""
    n_shards = mesh.devices.size
    if caps.n_cap % n_shards != 0:
        raise ValueError(f"n_cap {caps.n_cap} not divisible by {n_shards} devices")
    core = make_assign_core(caps, weights, axis_name=axis)
    fn = jax.shard_map(
        core, mesh=mesh,
        in_specs=(node_specs(axis), pod_specs()),
        out_specs={"assignments": P(), "waves": P(),
                   "used": P(axis, None), "used_nz": P(axis, None),
                   "npods": P(axis), "port_mask": P(axis, None),
                   "cd_sg": P(), "cd_asg": P()},
        check_vma=False,
    )
    # compile-cached: built once per mesh at backend setup; the caller
    # holds the returned callable (and its jit cache) for every wave
    return jax.jit(fn)
