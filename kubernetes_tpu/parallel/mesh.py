"""Device-mesh sharding of the scheduler hot path.

The node axis is the "long axis" of this workload (100k+ nodes); it shards
across TPU cores the way sequence parallelism shards tokens (SURVEY.md §5):
each core owns a contiguous slab of node rows, computes local feasibility +
scores, and placement is a per-core top-1 + all_gather + global pick.  The
running-sum state (used/npods/ports) lives sharded; the small domain-count
tables (cd_sg/cd_asg) are replicated and kept coherent with a psum of the
winning shard's domain ids.  The [P,P] conflict matrices of the wave
solver are slab-partitioned: each shard resolves a contiguous pod slab via
reduce-scatter and winners merge with a small all-gather
(models/assign.py gather_cols_rs).  All collectives are XLA ICI
collectives — no NCCL on TPU (reference's comm backbone analysis:
SURVEY.md §2.6).

Shardings are DECLARATIVE here: NODE_PARTITION_RULES maps every node-side
array name to an explicit PartitionSpec (match_partition_rules, the
exemplar shape of SNIPPETS.md [2]), and compile_sharded is the
pjit-preferred compile helper (SNIPPETS.md [3]) shared by
parallel/backend.py and parallel/census.py: jit==pjit drives placement +
donation over a shard_map manual region, falling back to a plain jit wrap
where the pjit sharding kwargs are unavailable.

Multi-host: jax.distributed.initialize() + the same Mesh spanning all
processes gives DCN+ICI automatically; nothing here changes.
"""

from __future__ import annotations

import re

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.assign import make_assign_core
from ..ops.flatten import Caps

NODE_AXIS = "nodes"

# Sentinel used in NODE_PARTITION_RULES entries; substituted with the
# mesh axis name by match_partition_rules.
_AXIS = "@nodes"

# Rule table: (name regex, PartitionSpec dims) — every node-side array
# entering the sharded step MUST match exactly one rule, so a new array
# cannot silently default to replicated (match_partition_rules raises on
# a miss; the replicated-large-tensor lint rule convicts capacity-scaled
# arrays that pair with P() without an annotation).
NODE_PARTITION_RULES = (
    # [n_cap, *] row-major node tensors: shard the node axis
    (r"^(alloc|used|used_nz|taint_mask|label_mask|key_mask|port_mask)$",
     (_AXIS, None)),
    # [n_cap] per-node vectors
    (r"^(npods|maxpods|valid)$", (_AXIS,)),
    # [cap, n_cap] domain-id tables: node axis is the LAST dim
    (r"^dom_(sg|asg)$", (None, _AXIS)),
    # [cap, dom] per-domain count tables stay replicated: the kernel
    # gathers per-node domain ids into them from every shard
    # (take_along_axis + psum commits) and reads total = sum(cnt_rows)
    # locally; a sharded copy would add a collective per constraint slot
    (r"^cd_(sg|asg)$", ()),  # replicated-ok: kernel-coherent count table
    # [cap, ns_vocab] namespace masks have no node axis and fold into
    # pod bits once per batch (_fold_ns_masks)
    (r"^(sg|asg)_ns_mask$", ()),  # replicated-ok: no node axis
    # scalar state-generation counter (the resolve fence; every shard
    # computes the identical gen+1 so it stays coherent without psum)
    (r"^gen$", ()),  # replicated-ok: scalar counter
)


def match_partition_rules(rules, names, axis: str = NODE_AXIS) -> dict:
    """Resolve array names against a (regex, spec-dims) rule table into
    {name: PartitionSpec}.  First match wins; an unmatched name raises so
    sharding stays exhaustive by construction (SNIPPETS.md [2])."""
    specs = {}
    for name in names:
        for pattern, dims in rules:
            if re.search(pattern, name):
                specs[name] = P(*(axis if d == _AXIS else d for d in dims))
                break
        else:
            raise ValueError(
                f"no partition rule matches node array {name!r} — add it "
                f"to NODE_PARTITION_RULES")
    return specs


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


NODE_KEYS = ("alloc", "used", "used_nz", "npods", "maxpods", "valid",
             "taint_mask", "label_mask", "key_mask", "port_mask",
             "dom_sg", "dom_asg", "cd_sg", "cd_asg",
             "sg_ns_mask", "asg_ns_mask")


def node_specs(axis: str = NODE_AXIS) -> dict:
    """PartitionSpec per node-side array, resolved from the rule table."""
    return match_partition_rules(NODE_PARTITION_RULES, NODE_KEYS, axis)


def pod_specs() -> dict:
    """Pod-side arrays are replicated (the batch is small)."""
    keys = ["req", "req_nz", "p_valid", "untol_hard", "untol_prefer",
            "sel_any", "sel_any_active", "sel_forb", "key_any",
            "key_any_active", "key_forb", "ports", "node_row", "c_kind",
            "c_sg", "c_maxskew", "c_selfmatch", "c_weight", "inc_sg",
            "inc_asg", "match_asg", "pod_ns"]
    return {k: P() for k in keys}


AGGREGATE_KEYS = ("used", "used_nz", "npods", "port_mask", "cd_sg", "cd_asg")
STATE_KEYS = AGGREGATE_KEYS + ("gen",)
STATIC_KEYS = ("alloc", "maxpods", "valid", "taint_mask", "label_mask",
               "key_mask", "dom_sg", "dom_asg", "sg_ns_mask", "asg_ns_mask")


def state_specs(axis: str = NODE_AXIS) -> dict:
    # resolved straight from the rule table (gen has no NODE_KEYS entry:
    # it is wave state only, never an input to the snapshot assign fn)
    return match_partition_rules(NODE_PARTITION_RULES, STATE_KEYS, axis)


def static_specs(axis: str = NODE_AXIS) -> dict:
    ns = node_specs(axis)
    return {k: ns[k] for k in STATIC_KEYS}


def shard_map_compat(f, mesh, in_specs, out_specs):
    """jax.shard_map across the API straddle: prefer the stable entry
    (check_vma, jax>=0.4.35-ish), fall back to jax.experimental.shard_map
    (check_rep) on runtimes that predate the promotion.  Replication
    checking is off either way: the wave solver's manual collectives
    (psum-of-owner gathers, reduce-scatter slabs) are replicated by
    construction, not by inference."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)


def _specs_to_shardings(mesh: Mesh, tree):
    from jax.sharding import NamedSharding
    return jax.tree.map(lambda s: NamedSharding(mesh, s), tree,
                        is_leaf=lambda x: isinstance(x, P))


def compile_sharded(fn, mesh: Mesh, in_specs, out_specs,
                    donate_argnums: tuple = ()):
    """pjit-preferred compile of a sharded step (SNIPPETS.md [3] shape):
    the body runs as a shard_map manual region (per-shard collectives need
    axis names), and jit==pjit around it carries explicit NamedSharding
    in/out shardings so XLA places/donates buffers without inferring
    layouts from the first call.  Where this jax predates the sharding
    kwargs, fall back to the bare shard_map wrap — same program, placement
    then comes from the device_put'd operands."""
    mapped = shard_map_compat(fn, mesh, in_specs, out_specs)
    try:
        # compile-cached: this IS the compile helper — callers build once
        # at backend setup and hold the returned jitted fn
        return jax.jit(mapped,
                       in_shardings=_specs_to_shardings(mesh, in_specs),
                       out_shardings=_specs_to_shardings(mesh, out_specs),
                       donate_argnums=donate_argnums)
    except TypeError:  # pragma: no cover - older jit signature
        # compile-cached: same — fallback arm of the one-shot compile
        return jax.jit(mapped, donate_argnums=donate_argnums)


def build_sharded_step_fn(caps: Caps, mesh: Mesh,
                          weights: dict[str, float] | None = None,
                          axis: str = NODE_AXIS, k_cap: int = 1024,
                          features=None):
    """Resident-state sharded step: fn(state, static, pods, prows, pvals)
    -> (new_state, assignments, waves), with `state` DONATED and returned
    updated — the multi-chip twin of the single-chip packed kernel's
    resident-dynamics design (ops/backend.py transport notes).

    prows i32[k_cap] are GLOBAL node rows to overwrite from pvals
    f32[k_cap, 2R+1+PT] (used | used_nz | npods | port_mask — the same
    patch layout as models/assign.PackSpec.f_patch) before the wave
    solve; -1 rows are padding.  Each shard applies only the patches that
    land in its slab, so the upload is replicated but the scatter is
    local — no collective needed.
    """
    import jax.numpy as jnp

    n_shards = mesh.devices.size
    if caps.n_cap % n_shards != 0:
        raise ValueError(f"n_cap {caps.n_cap} not divisible by {n_shards}")
    shard_n = caps.n_cap // n_shards
    R, PT = caps.r, caps.pt_cap
    from ..models.assign import ALL_FEATURES
    core = make_assign_core(
        caps, weights, axis_name=axis, n_shards=n_shards,
        features=ALL_FEATURES if features is None else features)

    def stepped(state, static, pods, prows, pvals):
        gen = state["gen"] + 1
        local = prows - jax.lax.axis_index(axis) * shard_n
        in_shard = (prows >= 0) & (local >= 0) & (local < shard_n)
        # out-of-shard/padding entries scatter to an out-of-bounds
        # sentinel and are DROPPED — a masked write of row 0 would race
        # a genuine patch of row 0 through duplicate-index set()
        li = jnp.where(in_shard, local, shard_n)

        def put(arr, vals):
            return arr.at[li].set(vals, mode="drop")

        node = dict(static)
        node["used"] = put(state["used"], pvals[:, :R])
        node["used_nz"] = put(state["used_nz"], pvals[:, R:2 * R])
        node["npods"] = put(state["npods"], pvals[:, 2 * R])
        node["port_mask"] = put(state["port_mask"],
                                pvals[:, 2 * R + 1:2 * R + 1 + PT])
        node["cd_sg"] = state["cd_sg"]
        node["cd_asg"] = state["cd_asg"]
        out = core(node, pods)
        new_state = {k: out[k] for k in AGGREGATE_KEYS}
        new_state["gen"] = gen
        return new_state, out["assignments"], out["waves"], gen

    ss, st = state_specs(axis), static_specs(axis)
    # compile-cached: built once per mesh at backend setup; the caller
    # holds the returned callable (and its jit cache) for every wave.
    # The per-wave uploads (pods dict + patch rows/vals, argnums 2-4)
    # are donated with the resident state: a depth-2 pipeline keeps two
    # waves' transports in flight, and donation lets XLA reclaim each
    # the moment the solve consumes it — HBM stays flat instead of
    # scaling with pipeline depth (the host retains its own copies for
    # fenced re-runs; nothing re-reads a device-side transport).
    return compile_sharded(stepped, mesh,
                           in_specs=(ss, st, pod_specs(), P(), P()),
                           out_specs=(ss, P(), P(), P()),
                           donate_argnums=(0, 2, 3, 4))


def build_sharded_assign_fn(caps: Caps, mesh: Mesh,
                            weights: dict[str, float] | None = None,
                            axis: str = NODE_AXIS):
    """shard_map'd assignment over the node axis. caps.n_cap must divide
    evenly by the mesh size."""
    n_shards = mesh.devices.size
    if caps.n_cap % n_shards != 0:
        raise ValueError(f"n_cap {caps.n_cap} not divisible by {n_shards} devices")
    core = make_assign_core(caps, weights, axis_name=axis, n_shards=n_shards)
    # compile-cached: built once per mesh at backend setup; the caller
    # holds the returned callable (and its jit cache) for every wave
    return compile_sharded(
        core, mesh,
        in_specs=(node_specs(axis), pod_specs()),
        out_specs={"assignments": P(), "waves": P(),
                   "used": P(axis, None), "used_nz": P(axis, None),
                   "npods": P(axis), "port_mask": P(axis, None),
                   "cd_sg": P(), "cd_asg": P()})
