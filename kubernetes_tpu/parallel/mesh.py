"""Device-mesh sharding of the scheduler hot path.

The node axis is the "long axis" of this workload (100k+ nodes); it shards
across TPU cores the way sequence parallelism shards tokens (SURVEY.md §5):
each core owns a contiguous slab of node rows, computes local feasibility +
scores, and placement is a per-core top-1 + all_gather + global pick.  The
running-sum state (used/npods/ports) lives sharded; the small domain-count
tables (cd_sg/cd_asg) are replicated and kept coherent with a psum of the
winning shard's domain ids.  All collectives are XLA ICI collectives — no
NCCL on TPU (reference's comm backbone analysis: SURVEY.md §2.6).

Multi-host: jax.distributed.initialize() + the same Mesh spanning all
processes gives DCN+ICI automatically; nothing here changes.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.assign import make_assign_core
from ..ops.flatten import Caps

NODE_AXIS = "nodes"


def make_mesh(devices=None, axis: str = NODE_AXIS) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.array(devices), (axis,))


def node_specs(axis: str = NODE_AXIS) -> dict:
    """PartitionSpec per node-side array (the real tp-style shardings)."""
    return {
        "alloc": P(axis, None), "used": P(axis, None), "used_nz": P(axis, None),
        "npods": P(axis), "maxpods": P(axis), "valid": P(axis),
        "taint_mask": P(axis, None), "label_mask": P(axis, None),
        "key_mask": P(axis, None), "port_mask": P(axis, None),
        "dom_sg": P(None, axis), "dom_asg": P(None, axis),
        # per-domain count tables are small and replicated
        "cd_sg": P(), "cd_asg": P(),
    }


def pod_specs() -> dict:
    """Pod-side arrays are replicated (the batch is small)."""
    keys = ["req", "req_nz", "p_valid", "untol_hard", "untol_prefer",
            "sel_any", "sel_any_active", "sel_forb", "key_any",
            "key_any_active", "key_forb", "ports", "node_row", "c_kind",
            "c_sg", "c_maxskew", "c_selfmatch", "c_weight", "inc_sg",
            "inc_asg", "match_asg"]
    return {k: P() for k in keys}


def build_sharded_assign_fn(caps: Caps, mesh: Mesh,
                            weights: dict[str, float] | None = None,
                            axis: str = NODE_AXIS):
    """shard_map'd assignment over the node axis. caps.n_cap must divide
    evenly by the mesh size."""
    n_shards = mesh.devices.size
    if caps.n_cap % n_shards != 0:
        raise ValueError(f"n_cap {caps.n_cap} not divisible by {n_shards} devices")
    core = make_assign_core(caps, weights, axis_name=axis)
    fn = jax.shard_map(
        core, mesh=mesh,
        in_specs=(node_specs(axis), pod_specs()),
        out_specs={"assignments": P(), "waves": P(),
                   "used": P(axis, None), "used_nz": P(axis, None),
                   "npods": P(axis), "port_mask": P(axis, None),
                   "cd_sg": P(), "cd_asg": P()},
        check_vma=False,
    )
    return jax.jit(fn)
