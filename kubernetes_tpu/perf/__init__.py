"""scheduler_perf harness (reference: test/integration/scheduler_perf)."""

import os

import yaml

from .scheduler_perf import (  # noqa: F401
    PerfCluster, ThroughputCollector, ThroughputSummary, run_named_workload,
    run_workload, setup_cluster, wait_for_pods_scheduled,
)

_CONFIG = os.path.join(os.path.dirname(__file__), "config",
                       "performance-config.yaml")


def load_workloads(path: str | None = None) -> dict[str, dict]:
    with open(path or _CONFIG) as f:
        entries = yaml.safe_load(f)
    return {e["name"]: e for e in entries}
