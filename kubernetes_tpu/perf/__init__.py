"""scheduler_perf harness (reference: test/integration/scheduler_perf)."""

import os

import yaml

from .scheduler_perf import (  # noqa: F401
    PerfCluster, ThroughputCollector, ThroughputSummary, run_named_workload,
    run_workload, setup_cluster, wait_for_pods_scheduled,
)

_CONFIG = os.path.join(os.path.dirname(__file__), "config",
                       "performance-config.yaml")


def load_workloads(path: str | None = None) -> dict[str, dict]:
    with open(path or _CONFIG) as f:
        entries = yaml.safe_load(f)
    return {e["name"]: e for e in entries}


def caps_for_nodes(n_nodes: int):
    """THE bench cap policy (shared by bench.py and tools/profile_host.py
    so the profiler always measures the configuration the bench runs):
    node capacity rounded up to a 256 multiple with ~10% headroom;
    c_cap=2 because every tracked workload carries <=1 constraint per
    pod and each constraint slot costs [P,P] conflict work per wave in
    the full kernel — pods with more constraints escape to the per-pod
    oracle."""
    from ..ops.flatten import Caps
    n_cap = max(1024, -(-int(n_nodes * 1.1) // 256) * 256)
    return Caps(n_cap=n_cap, l_cap=256, kl_cap=62, t_cap=16, pt_cap=16,
                s_cap=3, sg_cap=16, asg_cap=16, c_cap=2, ns_cap=256)
