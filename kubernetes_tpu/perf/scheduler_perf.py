"""scheduler_perf — the reference's scale benchmark harness, ported.

Reference: test/integration/scheduler_perf/
  scheduler_perf_test.go:57-63  workload opcodes: createNodes / createPods /
                                churn / barrier / sleep
  util.go:79  mustSetupScheduler (in-proc apiserver + real scheduler)
  util.go:288-355  throughputCollector: samples scheduled-pod count at a
                   fixed window -> SchedulingThroughput Average/PercNN
  config/performance-config.yaml  workload definitions

Workloads are YAML/dict configs of the same shape:

  name: SchedulingBasic
  workloadTemplate:
    - opcode: createNodes
      count: 500
    - opcode: createPods
      count: 500
      podTemplate: {...}         # optional; default is a small-request pod
    - opcode: barrier            # wait until all pending pods scheduled
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ..api import meta
from ..client import LocalClient, SharedInformerFactory
from ..client.clientset import NODES, PODS
from ..scheduler import Profile, Scheduler, new_default_framework, new_scheduler
from ..store import kv
from ..testing import make_node, make_pod

DEFAULT_SAMPLE_INTERVAL = 1.0  # util.go: 1s window


@dataclass
class ThroughputSummary:
    average: float = 0.0
    perc50: float = 0.0
    perc90: float = 0.0
    perc99: float = 0.0
    total_pods: int = 0
    duration: float = 0.0

    def to_dict(self) -> dict:
        return {"Average": round(self.average, 1), "Perc50": round(self.perc50, 1),
                "Perc90": round(self.perc90, 1), "Perc99": round(self.perc99, 1),
                "TotalPods": self.total_pods,
                "DurationSeconds": round(self.duration, 2)}


class ThroughputCollector:
    """Samples scheduled-pod deltas per window (util.go:288-355).

    Counts via a pods WATCH instead of re-listing the store: at 100k+
    pods the reference-style full scan costs ~0.4s of GIL per 1s sample
    (plus the barrier's polling scans), which measurably throttles the
    pipeline being measured.  The watch is O(events) and the store emits
    each bind exactly once."""

    def __init__(self, store: kv.MemoryStore, interval: float = DEFAULT_SAMPLE_INTERVAL):
        self.store = store
        self.interval = interval
        self.samples: list[float] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._start_time = 0.0
        self._count = 0           # pods observed bound since start()
        self._count_lock = threading.Lock()
        self._scheduled: set[str] = set()
        self._watch: kv.Watch | None = None
        self._base = 0            # pods already bound when start() ran
        self._frozen_at = 0.0     # freeze(): end of the measured window
        self._frozen_count = 0
        self._frozen_samples: list[float] = []
        self._last_sched_at = 0.0  # drain time of the newest bind seen

    def scheduled_total(self) -> int:
        """Pods bound since start() (drain-backed; cheap)."""
        with self._count_lock:
            return self._count

    def bound_total(self) -> int:
        """ALL bound pods: pre-start (warm-up ops) + since start().
        Barriers use this; the throughput window uses scheduled_total."""
        with self._count_lock:
            return self._base + self._count

    def _drain(self) -> None:
        evs = self._watch.next_batch(timeout=0.05)
        if not evs:
            return
        new = 0
        seen = self._scheduled
        DELETED = kv.DELETED
        for ev in evs:
            o = ev.object
            md = o["metadata"]
            ns = md.get("namespace", "")
            k = f"{ns}/{md['name']}" if ns else md["name"]
            if ev.type == DELETED:
                seen.discard(k)
            elif (o.get("spec") or {}).get("nodeName"):
                if k not in seen:
                    seen.add(k)
                    new += 1
        if new:
            with self._count_lock:
                self._count += new
                self._last_sched_at = time.monotonic()

    @property
    def started(self) -> bool:
        return self._start_time != 0.0

    @property
    def frozen(self) -> bool:
        return self._frozen_at != 0.0

    def start(self) -> None:
        # watch first, then count what was already bound (warm-up ops
        # before the measured one): a bind landing between the two is
        # seen by BOTH, so seed the dedup set from the scan — it can
        # only overcount the base, never undercount bound_total
        self._watch = self.store.watch(PODS)
        items, _rv = self.store.list(PODS, None)
        for o in items:
            if (o.get("spec") or {}).get("nodeName"):
                md = o["metadata"]
                ns = md.get("namespace", "")
                self._scheduled.add(f"{ns}/{md['name']}" if ns
                                    else md["name"])
        self._base = len(self._scheduled)
        # the window opens AFTER the O(pods) seeding scan: the measured
        # createPods haven't been created yet, so no bind can be missed,
        # and the scan's duration must not deflate the reported rate
        self._start_time = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def freeze(self) -> None:
        """Close the measurement window NOW (the measured op's barrier
        completed): samples/duration after this point are excluded, like
        the reference cancelling its collector right after the measured
        createPods' waitUntil (scheduler_perf_test.go:744-751).  Watch
        draining continues so scheduled_total stays usable for later
        barriers."""
        self._drain()
        with self._count_lock:
            self._frozen_count = self._count
            # end the window at the drain that saw the final bind, not
            # at barrier detection: the barrier polls at 50 ms, so its
            # detection latency would quantize the window and read as
            # up to a few percent of phantom throughput loss on short
            # runs (the --timeline A/B measures exactly this margin)
            self._frozen_at = self._last_sched_at or time.monotonic()
        self._frozen_samples = list(self.samples)

    def _run(self) -> None:
        window_start = time.monotonic()
        window_count = 0
        while not self._stop.is_set():
            self._drain()
            now = time.monotonic()
            if now - window_start >= self.interval:
                cur = self.scheduled_total()
                self.samples.append((cur - window_count)
                                    / (now - window_start))
                window_start, window_count = now, cur

    def stop(self) -> ThroughputSummary:
        self._stop.set()
        if self._thread:
            self._thread.join(2.0)
        self._drain()  # pick up the tail
        if self._watch is not None:
            self._watch.stop()
        if self.frozen:
            # window closed at the measured barrier; trailing ops
            # (sleep/churn/later floods) are excluded
            total = self._frozen_count
            dur = max(self._frozen_at - self._start_time, 1e-9)
            self.samples = self._frozen_samples
        else:
            end = time.monotonic()
            total = self.scheduled_total()
            dur = max(end - self._start_time, 1e-9)
        s = ThroughputSummary(total_pods=total, duration=dur,
                              average=total / dur)
        if self.samples:
            xs = sorted(self.samples)
            def perc(p: float) -> float:
                return xs[min(int(len(xs) * p), len(xs) - 1)]
            s.perc50, s.perc90, s.perc99 = perc(0.50), perc(0.90), perc(0.99)
        return s


@dataclass
class PerfCluster:
    store: object               # MemoryStore, or the HTTPClient when the
    client: object              # apiserver runs out of process (it only
    factory: SharedInformerFactory  # needs .watch()/.list())
    scheduler: Scheduler
    server: object = None       # APIServer when via_http
    worker: object = None       # in-process DeviceWorker when remote_seam
    _tmpdir: object = None      # WAL dir lifetime
    _proc: object = None        # subprocess.Popen when via_http="process"

    def shutdown(self) -> None:
        self.scheduler.stop()
        if self.worker is not None:
            # after scheduler.stop(): the final flush still needs the seam
            for p in self.scheduler.profiles.values():
                close = getattr(p.batch_backend, "close", None)
                if close is not None:
                    close()
            self.worker.stop()
        self.factory.stop()
        self.client.close()  # event-broadcaster thread
        if self.server is not None:
            self.server.stop()
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 - then kill + reap
                self._proc.kill()
                self._proc.wait()
        if self._tmpdir is not None:
            self._tmpdir.cleanup()


def setup_cluster(tpu: bool = False, caps=None, batch_size: int = 512,
                  store: kv.MemoryStore | None = None,
                  pipeline_depth: int = 1,
                  admission_interval: float = 0.0,
                  via_http: bool = False,
                  null_device: bool = False,
                  percentage_of_nodes_to_score: int = 0,
                  remote_seam: str | None = None,
                  backend_kind: str = "tpu",
                  tracing_provider=None,
                  overload=None,
                  chaos_schedule=None,
                  profiling_policy=None,
                  device_flight_s: float = 0.0) -> PerfCluster:
    """mustSetupScheduler (util.go:79): in-proc everything, no kubelet.

    pipeline_depth/admission_interval select latency mode (scheduler.py):
    depth ~4 + a few-ms admission interval turns the batch path into
    overlapped micro-batches for p99-targeted runs.

    remote_seam ("grpc" or "http") routes the batch backend through an
    in-process DeviceWorker (ops/remote.py) instead of the in-process
    jax backend — the shape bench --trace uses so worker-side spans
    exercise the real traceparent propagation.  tracing_provider attaches
    a component_base.tracing.TracerProvider to the scheduler
    (configure_tracing); None leaves the pipeline untraced.

    via_http runs the FRONT DOOR: a real apiserver with RBAC +
    admission + WAL durability, and the scheduler (informers, binds,
    events) plus the workload submitter all speaking HTTP to it — the
    reference harness's shape (util.go:79-108 schedules via a real
    apiserver), quantifying what LocalClient bypasses.
    via_http="process" goes further and runs the apiserver as a
    SEPARATE OS PROCESS (`python -m kubernetes_tpu.cmd.apiserver`),
    the reference's actual deployment shape (separate binaries): the
    server's JSON/admission/WAL work then runs on its own interpreter
    and cores instead of sharing the scheduler's GIL.

    overload takes a config.OverloadPolicy (configure_overload: bounded
    admission + AIMD waves + escape breaker + watchdog); chaos_schedule
    takes an ops.faults.OverloadSchedule and wraps the batch backend in
    ChaosBatchBackend — together they are the bench --overload shape.

    backend_kind selects the in-process device backend via
    ops/backend.make_batch_backend ("tpu" single-chip resident kernel,
    "sharded" the mesh-partitioned shard_map path, "null" device step
    nulled) — the same vocabulary as the scheduler config's `backend:`
    stanza and `bench.py --backend`.  null_device/remote_seam take
    precedence (they predate the stanza and the remote seam needs a
    worker, not a kind)."""
    from ..utils.gctune import tune_for_throughput
    tune_for_throughput()  # CPython gen-2 pauses cost ~35% at bench scale
    server = tmpdir = proc = None
    if via_http == "process":
        if store is not None:
            raise ValueError("via_http builds its own store")
        import secrets as pysecrets
        import socket as socketlib
        import subprocess
        import sys
        import tempfile

        from ..client.http_client import HTTPClient
        tmpdir = tempfile.TemporaryDirectory(prefix="bench-wal-")
        token = pysecrets.token_urlsafe(16)
        with socketlib.socket() as s:  # pick a free port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        proc = subprocess.Popen(
            [sys.executable, "-m", "kubernetes_tpu.cmd.apiserver",
             "--secure-port", str(port), "--token", token,
             "--authorization-mode", "RBAC",
             "--enable-default-admission",
             # no controllers run in the harness, so the plugins that
             # depend on them come off — the reference harness disables
             # exactly these (scheduler_perf/util.go:84-85)
             "--disable-admission-plugins",
             "ServiceAccount,TaintNodesByCondition,Priority",
             "--data-dir", tmpdir.name],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        client = HTTPClient.from_url(f"http://127.0.0.1:{port}",
                                     token=token)
        deadline = time.monotonic() + 30
        while True:
            try:
                client._request("GET", "/healthz")
                break
            except Exception:  # noqa: BLE001 - still starting
                if proc.poll() is not None \
                        or time.monotonic() > deadline:
                    proc.kill()
                    proc.wait()
                    tmpdir.cleanup()
                    raise RuntimeError("apiserver process failed to "
                                       "start")
                time.sleep(0.1)
        store = client  # collector watches through the HTTP client
    elif via_http:
        if store is not None:
            raise ValueError("via_http builds its own WAL-backed store; "
                             "a caller-provided store would be ignored")
        import secrets as pysecrets
        import tempfile

        from ..apiserver import APIServer
        from ..client.http_client import HTTPClient
        tmpdir = tempfile.TemporaryDirectory(prefix="bench-wal-")
        store = kv.MemoryStore(history=1_000_000,
                               durable_dir=tmpdir.name)
        token = pysecrets.token_urlsafe(16)
        server = APIServer(store, token=token, enable_rbac=True,
                           enable_default_admission=True,
                           # scheduler_perf/util.go:84-85: the plugins
                           # that need controllers come off
                           disable_admission_plugins=frozenset(
                               ("ServiceAccount", "TaintNodesByCondition",
                                "Priority"))).start()
        client = HTTPClient.from_url(server.url, token=token)
    else:
        store = store or kv.MemoryStore(history=1_000_000)
        client = LocalClient(store)
    factory = SharedInformerFactory(client)
    worker = None
    if tpu:
        from ..ops.flatten import Caps
        if null_device:
            # host-only measurement: device step nulled (LATENCY.md's
            # host-tail rows; the host-wall ceiling in isolation)
            from ..ops.nullbackend import NullBatchBackend
            backend = NullBatchBackend(caps or Caps(),
                                       batch_size=batch_size)
        elif remote_seam:
            from ..ops.remote import (
                DeviceWorker, GrpcDeviceWorker, RemoteTPUBatchBackend,
            )
            worker = (GrpcDeviceWorker() if remote_seam == "grpc"
                      else DeviceWorker()).start()
            backend = RemoteTPUBatchBackend(worker.url, caps or Caps(),
                                            batch_size=batch_size)
        else:
            from ..ops.backend import make_batch_backend
            backend = make_batch_backend(backend_kind, caps or Caps(),
                                         batch_size=batch_size)
        backend.warmup()
        if chaos_schedule is not None:
            from ..ops.faults import ChaosBatchBackend
            backend = ChaosBatchBackend(backend, chaos_schedule)
        if device_flight_s > 0:
            from ..ops.nullbackend import FlightDelayBackend
            backend = FlightDelayBackend(backend, device_flight_s)
        fw = new_default_framework(client, factory)
        profiles = {"default-scheduler": Profile(
            fw, batch_backend=backend, batch_size=batch_size,
            percentage_of_nodes_to_score=percentage_of_nodes_to_score)}
        sched = Scheduler(client, factory, profiles,
                          pipeline_depth=pipeline_depth,
                          admission_interval=admission_interval)
    else:
        sched = new_scheduler(client, factory)
    if overload is not None:
        sched.configure_overload(overload)
    if tracing_provider is not None:
        sched.configure_tracing(tracing_provider)
    if profiling_policy is not None and (profiling_policy.enabled
                                         or profiling_policy.census
                                         or profiling_policy.timeline):
        # same wiring scheduler_from_config applies for the profiling:
        # stanza — bench --profile reuses the ProfilingPolicy dataclass
        from ..component_base import profiling as cbp
        profiler = None
        if profiling_policy.enabled:
            profiler = cbp.default_host_profiler
            profiler.reset()
            profiler.interval = profiling_policy.sample_interval_ms / 1000.0
            profiler.max_stacks = profiling_policy.max_stacks
            profiler.start()
        slo = cbp.SLOTracker(
            target_ms=profiling_policy.slo_target_ms,
            objective=profiling_policy.slo_objective,
            windows=profiling_policy.burn_windows_s)
        timeline = None
        if profiling_policy.timeline:
            # arm the process-local interval ring the backends record
            # into (bench --timeline rides the same switch the
            # profiling: stanza flips)
            from ..component_base import timeline as cb_timeline
            timeline = cb_timeline.default_timeline
            timeline.configure(enabled=True,
                               ring=profiling_policy.timeline_ring)
            timeline.reset()
        sched.configure_profiling(profiler, slo,
                                  census=profiling_policy.census,
                                  timeline=timeline)
        if profiling_policy.enabled or profiling_policy.census:
            sched.run_device_census()
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    return PerfCluster(store, client, factory, sched, server=server,
                       worker=worker, _tmpdir=tmpdir, _proc=proc)


# -- workload ops (scheduler_perf_test.go opcodes) -------------------------

def _default_pod(i: int, params: dict) -> dict:
    """Build pod #i for a createPods op.  The op's invariant shape is
    built ONCE and cached on the params dict; each pod is then a C
    fastcopy + name fill (a from-scratch wrapper build cost ~4µs/pod of
    GIL on the submission thread, which competes with the pipeline
    being measured — the reference harness's client-side encoding cost
    sits outside its apiserver for the same reason)."""
    tmpl = params.get("_pod_tmpl_cache")
    if tmpl is None:
        w = make_pod(params.get("podNamePrefix", "pod-"),
                     params.get("namespace", "default"))
        user_tmpl = params.get("podTemplate") or {}
        if user_tmpl:
            pod = meta.deep_copy(w.build())
            spec = meta.deep_copy(user_tmpl.get("spec") or {})
            pod["spec"].update(spec)
            if "metadata" in user_tmpl:
                md = meta.deep_copy(user_tmpl["metadata"])
                name = pod["metadata"]["name"]
                ns = pod["metadata"]["namespace"]
                pod["metadata"].update(md)
                pod["metadata"]["name"] = name
                pod["metadata"]["namespace"] = ns
        else:
            pod = w.req(cpu=params.get("cpu", "100m"),
                        mem=params.get("memory", "128Mi")).build()
        tmpl = params["_pod_tmpl_cache"] = pod
    pod = meta.deep_copy(tmpl)
    pod["metadata"]["name"] = params.get("podNamePrefix", "pod-") + str(i)
    nrr = params.get("namespaceRoundRobin")
    if nrr:
        # pod #i lands in {prefix}{i % count} — the NSSelector
        # workloads' createPodSets analog (N pods per init namespace)
        pod["metadata"]["namespace"] = (
            f"{nrr.get('prefix', 'init-ns-')}{i % int(nrr['count'])}")
    ds = params.get("distinctServices")
    if ds:
        # high-label-cardinality shape: pod #i belongs to service
        # svc-{i%ds}; its labels AND its (anti-)affinity selectors track
        # the service, so the workload carries `ds` distinct selector
        # groups (the regime that overflows fixed selector-group caps)
        svc = f"svc-{i % int(ds)}"
        pod["metadata"].setdefault("labels", {})["app"] = svc
        aff = (pod.get("spec") or {}).get("affinity") or {}
        for side in ("podAntiAffinity", "podAffinity"):
            for term in (aff.get(side) or {}).get(
                    "requiredDuringSchedulingIgnoredDuringExecution") or ():
                sel = term.get("labelSelector")
                if sel and "matchLabels" in sel:
                    sel["matchLabels"] = {"app": svc}
    esc = params.get("escapeEvery")
    if esc and i % int(esc) == 0:
        # every Nth pod carries a Gt node-affinity term — one of the
        # constraint shapes the tensor path deliberately does NOT encode
        # (flatten._encode_affinity_terms escapes Gt/Lt), so these pods
        # measure the blended tensor+oracle regime and a non-zero
        # escape_rate (the honest-coverage bench config)
        pod["spec"]["affinity"] = {"nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [
                    {"key": "ktpu.io/rack", "operator": "Gt",
                     "values": ["9"]}]}]}}}
    pg = params.get("podGroups")
    if pg:
        # gang membership: contiguous blocks of minMember pods per group
        # (the Coscheduling workload; BASELINE tracked config #4)
        size = pg.get("minMember", 10)
        group = f"{pg.get('namePrefix', 'pg-')}{i // size}"
        pod["metadata"].setdefault("labels", {})[
            "scheduling.x-k8s.io/pod-group"] = group
    return pod


def _default_node(i: int, params: dict) -> dict:
    """Node #i: template + fastcopy, like _default_pod (a 100k-node flood
    built from scratch costs ~0.4s of GIL before the first pod lands)."""
    tmpl = params.get("_node_tmpl_cache")
    if tmpl is None:
        w = make_node(params.get("nodeNamePrefix", "node-"))
        w.capacity(cpu=params.get("cpu", "32"),
                   mem=params.get("memory", "256Gi"),
                   pods=params.get("pods", 110))
        w.labels(**dict(params.get("labels") or {}))
        tmpl = params["_node_tmpl_cache"] = w.build()
    node = meta.deep_copy(tmpl)
    name = params.get("nodeNamePrefix", "node-") + str(i)
    node["metadata"]["name"] = name
    labels = node["metadata"].setdefault("labels", {})
    if params.get("zones"):
        zones = params["zones"]
        labels["topology.kubernetes.io/zone"] = zones[i % len(zones)]
    if params.get("rackLabels"):
        # numeric label for Gt/Lt node-affinity workloads (the operator
        # pair the tensor encoding does NOT carry — those pods escape to
        # the per-pod oracle by design)
        labels["ktpu.io/rack"] = str(i % int(params["rackLabels"]))
    labels.setdefault("kubernetes.io/hostname", name)
    return node


def _bulk_create(client, resource: str, count: int, offset: int,
                 build, op: dict, chunk: int = 512) -> None:
    """createNodes/createPods submission: chunked bulk writes when the
    client supports ownership-transfer bulk create (the reference harness
    pumps objects through a 5000-QPS/5000-burst client, util.go:92;
    chunked create_many is the LocalClient transport analog)."""
    creator = getattr(client, "create_bulk", None)
    if creator is not None and count >= 256:
        for lo in range(0, count, chunk):
            creator(resource, [build(offset + i, op)
                               for i in range(lo, min(lo + chunk, count))])
    elif count >= 64:
        # remote client (HTTP): fan the submission over a few
        # connections — the reference harness pumps through a
        # concurrent rate-limited client the same way (util.go:92);
        # HTTPClient keeps one keep-alive connection per thread
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=8) as pool:
            list(pool.map(
                lambda i: client.create(resource, build(offset + i, op)),
                range(count)))
    else:
        for i in range(count):
            client.create(resource, build(offset + i, op))


def wait_for_pods_scheduled(cluster: PerfCluster, want: int,
                            timeout: float = 600.0, namespace=None,
                            collector: ThroughputCollector | None = None
                            ) -> bool:
    """barrier opcode: wait until `want` pods have nodeName set.

    With a collector the check is its watch-backed counter (O(1));
    the full-scan fallback costs O(pods) per poll and throttles the
    pipeline at 100k+ pods."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if collector is not None and collector.started and namespace is None:
            n = collector.bound_total()
        else:
            items, _ = cluster.store.list(PODS, namespace)
            n = sum(1 for p in items if meta.pod_node_name(p))
        if n >= want:
            return True
        time.sleep(0.05)
    return False


def is_measured(op: dict, ops: list[dict]) -> bool:
    """Reference collectMetrics semantics (scheduler_perf_test.go:716-751):
    when any createPods op declares collectMetrics, ONLY those ops are
    measured — earlier createPods are warm-up, outside the throughput
    window.  Templates without the flag keep the old behavior (every
    createPods measured, the first opens the window).  Shared by the
    harness and bench.py's count/rate overrides so they can't diverge."""
    if op.get("opcode", "createPods") != "createPods":
        return False
    any_cm = any(o.get("collectMetrics") for o in ops
                 if o.get("opcode") == "createPods")
    return op.get("collectMetrics", not any_cm)


def run_workload(cluster: PerfCluster, ops: list[dict],
                 collector: ThroughputCollector | None = None) -> dict:
    """Execute a workloadTemplate op list. Returns op stats."""
    created_pods = 0
    created_nodes = 0
    # pods expected to actually schedule: createPods ops marked
    # skipWaitToCompletion (the Unschedulable workload's parked pods,
    # performance-config.yaml:437-443) are excluded from barrier targets
    expected_scheduled = 0
    stats: dict[str, Any] = {}
    churn_stop: list[threading.Event] = []
    storm_drivers: list = []
    for op in ops:
        opcode = op["opcode"]
        if opcode == "createNodes":
            _bulk_create(cluster.client, NODES, op["count"], created_nodes,
                         _default_node, op)
            created_nodes += op["count"]
        elif opcode == "createNamespaces":
            # namespace objects with labels (the NSSelector workloads'
            # namespace-with-labels.yaml shape)
            from ..client.clientset import NAMESPACES
            prefix = op.get("prefix", "ns-")
            for i in range(op["count"]):
                nsobj = meta.new_object("Namespace", f"{prefix}{i}",
                                        namespace=None)
                if op.get("labels"):
                    nsobj["metadata"]["labels"] = dict(op["labels"])
                try:
                    cluster.client.create(NAMESPACES, nsobj)
                except kv.ConflictError:
                    pass
        elif opcode == "createPods":
            if collector is not None and not collector.started \
                    and is_measured(op, ops):
                # measurement window opens with the first measured pods
                # (reference: CollectMetrics on the createPods op)
                collector.start()
                if hasattr(cluster.scheduler, "metrics") \
                        and stats.get("barrier_ok", True):
                    # the warm-up barrier saw the binds in the STORE; the
                    # scheduler records each e2e entry only after its bulk
                    # commit returns, so briefly wait for the metric to
                    # catch up or in-flight warm-up latencies would land
                    # after the watermark and pollute the measured e2e.
                    # Skipped when the warm-up barrier already failed
                    # (the mark can never reach the target), and bounded
                    # by progress: a stalled mark exits early.
                    m = cluster.scheduler.metrics
                    deadline = time.monotonic() + 5.0
                    last, last_change = m.e2e_mark(), time.monotonic()
                    while (last < expected_scheduled
                           and time.monotonic() < deadline):
                        time.sleep(0.005)
                        cur = m.e2e_mark()
                        if cur != last:
                            last, last_change = cur, time.monotonic()
                        elif time.monotonic() - last_change > 0.25:
                            break  # mark stopped advancing
                    stats["e2e_mark"] = m.e2e_mark()
            rate = op.get("ratePerSecond")
            if rate:
                # paced arrival (the reference harness's client-QPS knob,
                # util.go:92): steady load below capacity is what the
                # p99-latency target is ABOUT — a full-backlog dump makes
                # p99 the backlog drain time by construction
                chunk = max(1, int(rate) // 100)  # 10ms ticks
                next_t = time.monotonic()
                for lo in range(0, op["count"], chunk):
                    hi = min(lo + chunk, op["count"])
                    _bulk_create(cluster.client, PODS, hi - lo,
                                 created_pods + lo, _default_pod, op)
                    next_t += (hi - lo) / rate
                    delay = next_t - time.monotonic()
                    if delay > 0:
                        time.sleep(delay)
            else:
                _bulk_create(cluster.client, PODS, op["count"],
                             created_pods, _default_pod, op)
            created_pods += op["count"]
            if not op.get("skipWaitToCompletion"):
                expected_scheduled += op["count"]
        elif opcode == "createPodGroups":
            from ..client.clientset import PODGROUPS
            prefix = op.get("namePrefix", "pg-")
            for i in range(op["count"]):
                pg = meta.new_object("PodGroup", f"{prefix}{i}", "default")
                pg["spec"] = {"minMember": op.get("minMember", 10),
                              "scheduleTimeoutSeconds": op.get(
                                  "scheduleTimeoutSeconds", 120)}
                cluster.client.create(PODGROUPS, pg)
        elif opcode == "barrier":
            want = op.get("count", expected_scheduled)
            ok = wait_for_pods_scheduled(cluster, want,
                                         timeout=op.get("timeout", 600.0),
                                         collector=collector)
            stats["barrier_ok"] = ok
            if collector is not None and collector.started \
                    and not collector.frozen:
                # measured window closes at the barrier that covers the
                # measured createPods (reference: collectorCancel right
                # after waitUntilPodsScheduled)
                collector.freeze()
        elif opcode == "sleep":
            time.sleep(op.get("duration", 1.0))
        elif opcode == "churn":
            # background create/delete loop (scheduler_perf churn op,
            # scheduler_perf_test.go churnOp).  mode=recreate keeps
            # `number` live copies of each templated object, deleting the
            # oldest as new ones land (SchedulingWithMixedChurn cycles a
            # capacity-0 node, an unschedulable high-priority pod, and a
            # service every interval — each a different event source for
            # the scheduler's requeue gating).
            ev = threading.Event()
            churn_stop.append(ev)
            interval = op.get("intervalMilliseconds", 500) / 1000.0
            mode = op.get("mode", "create")
            objects = op.get("objects", ["pod"])
            number = op.get("number", 1)

            def churn_objects(i: int) -> list[tuple[str, str | None, str, dict]]:
                from ..client.clientset import SERVICES
                out = []
                for kind in objects:
                    name = f"churn-{kind}-{i}"
                    if kind == "node":
                        n = make_node(name).capacity(cpu="1", mem="1Gi",
                                                     pods=0).build()
                        out.append((NODES, None, name, n))
                    elif kind == "service":
                        svc = meta.new_object("Service", name, "churn")
                        svc["spec"] = {"selector": {"app": "foo"},
                                       "ports": [{"protocol": "TCP",
                                                  "port": 8080}]}
                        out.append((SERVICES, "churn", name, svc))
                    elif mode == "recreate":
                        # pod: high-priority, oversized (never schedules;
                        # pod-high-priority-large-cpu.yaml shape)
                        p = make_pod(name, "churn").req(cpu="9",
                                                        mem="500Mi").build()
                        p["spec"]["priority"] = 10
                        out.append((PODS, "churn", name, p))
                    else:  # legacy create-mode churn: tiny schedulable pod
                        p = make_pod(name, "churn").req(cpu="1m").build()
                        out.append((PODS, "churn", name, p))
                return out

            def churn_loop(ev=ev, interval=interval):
                from collections import deque
                live: deque = deque()
                i = 0
                while not ev.wait(interval):
                    for res, ns, name, obj in churn_objects(i):
                        try:
                            cluster.client.create(res, obj)
                            live.append((res, ns, name))
                        except kv.StoreError:
                            pass
                    while len(live) > number * len(objects):
                        res, ns, name = live.popleft()
                        try:
                            cluster.client.delete(res, ns, name)
                        except kv.StoreError:
                            pass
                    if mode != "recreate":
                        # legacy create mode: delete immediately
                        while live:
                            res, ns, name = live.popleft()
                            try:
                                cluster.client.delete(res, ns, name)
                            except kv.StoreError:
                                pass
                    i += 1

            threading.Thread(target=churn_loop, daemon=True).start()
        elif opcode == "nodeStorm":
            # seeded topology churn (ChurnStormSchedule + NodeStormDriver):
            # floods node adds / drains / relabels through the informer
            # while pod floods are in flight, stressing the backend's row
            # patches, between-wave compaction and pipelined gen fences.
            # Background thread like churn; stepped at a fixed interval,
            # stopped at end-of-workload with the same stop-event list.
            from ..ops.faults import ChurnStormSchedule, NodeStormDriver
            storm_sched = ChurnStormSchedule(
                seed=op.get("seed", 0),
                add_rate=op.get("addRate", 0.0),
                drain_rate=op.get("drainRate", 0.0),
                relabel_rate=op.get("relabelRate", 0.0))
            prefix = op.get("nodeNamePrefix", "node-")
            driver = NodeStormDriver(
                cluster.client, storm_sched,
                [f"{prefix}{i}" for i in range(created_nodes)],
                min_nodes=op.get("minNodes", max(1, created_nodes // 2)),
                max_nodes=op.get("maxNodes", max(4, created_nodes * 2)),
                cpu=op.get("cpu", "32"), mem=op.get("memory", "256Gi"),
                rack_labels=op.get("rackLabels", 0))
            storm_drivers.append(driver)
            ev = threading.Event()
            churn_stop.append(ev)
            interval = op.get("intervalMilliseconds", 50) / 1000.0
            max_steps = op.get("steps", 0)

            def storm_loop(ev=ev, driver=driver, interval=interval,
                           max_steps=max_steps):
                while not ev.wait(interval):
                    if max_steps and driver.steps >= max_steps:
                        return
                    driver.step()

            threading.Thread(target=storm_loop, daemon=True).start()
        else:
            raise ValueError(f"unknown opcode {opcode!r}")
    for ev in churn_stop:
        ev.set()
    stats["created_pods"] = created_pods
    stats["created_nodes"] = created_nodes
    if storm_drivers:
        stats["storm"] = {
            "steps": sum(d.steps for d in storm_drivers),
            "injected": {k: sum(d.injected[k] for d in storm_drivers)
                         for k in storm_drivers[0].injected},
            "live_nodes": sum(len(d._names) for d in storm_drivers),
        }
    return stats


def run_named_workload(config: dict, tpu: bool = False, caps=None,
                       batch_size: int = 512, pipeline_depth: int = 1,
                       admission_interval: float = 0.0,
                       via_http: bool = False,
                       null_device: bool = False,
                       percentage_of_nodes_to_score: int = 0,
                       remote_seam: str | None = None,
                       backend_kind: str = "tpu",
                       tracing_provider=None,
                       overload=None,
                       chaos_schedule=None,
                       profiling_policy=None,
                       device_flight_s: float = 0.0
                       ) -> tuple[ThroughputSummary, dict]:
    """Run one workload config end to end; returns (throughput, stats)."""
    cluster = setup_cluster(
        tpu=tpu, caps=caps, batch_size=batch_size,
        pipeline_depth=pipeline_depth,
        admission_interval=admission_interval,
        via_http=via_http, null_device=null_device,
        percentage_of_nodes_to_score=percentage_of_nodes_to_score,
        remote_seam=remote_seam, backend_kind=backend_kind,
        tracing_provider=tracing_provider,
        overload=overload, chaos_schedule=chaos_schedule,
        profiling_policy=profiling_policy,
        device_flight_s=device_flight_s)
    collector = ThroughputCollector(cluster.store)
    try:
        ops = config["workloadTemplate"]
        t0 = time.monotonic()
        # The collector starts AT the first createPods op, not here: the
        # reference runs its throughputCollector only while the measured
        # createPods op is in flight (scheduler_perf_test.go:716-751,
        # CollectMetrics gates collector run/cancel around createPods +
        # waitUntil), so node-preparation floods are outside the window.
        stats = run_workload(cluster, ops, collector)
        if not collector.started:  # no createPods op in workload
            collector.start()
        summary = collector.stop()
        stats["wall"] = time.monotonic() - t0
        stats["e2e"] = cluster.scheduler.metrics.e2e_summary(
            since=stats.get("e2e_mark", 0))
        if cluster.scheduler.metrics.preemption_attempts:
            stats["preemption_attempts"] = (
                cluster.scheduler.metrics.preemption_attempts)
        from ..utils import stagelat
        if stagelat.ENABLED:
            stats["stage_latency"] = stagelat.summary()
            stagelat.reset()  # don't bleed into the next workload
        if tracing_provider is not None and cluster.worker is not None:
            # worker-side spans (parented into the client trace via the
            # propagated traceparent); the caller merges them with the
            # scheduler provider's for the Chrome export
            wp = getattr(cluster.worker, "tracer_provider", None)
            if wp is not None:
                stats["worker_spans"] = wp.snapshot()
        for p in cluster.scheduler.profiles.values():
            if p.batch_backend is not None:
                stats["backend_stats"] = dict(p.batch_backend.stats)
                pods = stats["backend_stats"].get("pods", 0)
                esc = stats["backend_stats"].get("escaped", 0)
                if pods:
                    stats["escape_rate"] = round(esc / pods, 4)
                injected = getattr(p.batch_backend, "injected", None)
                if injected is not None:  # ChaosBatchBackend wrapper
                    stats["chaos_injected"] = dict(injected)
                maint_fn = getattr(p.batch_backend,
                                   "maintenance_snapshot", None)
                if maint_fn is not None:
                    # incremental-flatten readout: patched-vs-reflattened
                    # wave counts + the snapshot.patch / snapshot.flatten
                    # host seconds every BENCH row reports
                    stats["tensor_maintenance"] = maint_fn()
                break
        if profiling_policy is not None and (profiling_policy.enabled
                                             or profiling_policy.census):
            # the performance-observatory read-out bench --profile emits
            # as the PROFILE artifact: per-stage host attribution, the
            # device census, and the SLO window view
            sched = cluster.scheduler
            if sched._profiler is not None:
                sched._profiler.stop()
                stats["host_stages"] = sched._profiler.stage_seconds()
                stats["profile_samples"] = sched._profiler.samples_total()
                stats["hot_stacks"] = sched._profiler.top_stacks(10)
            if sched._census:
                stats["device_census"] = sched._census
            if sched._slo is not None:
                stats["slo"] = {
                    **sched._slo.quantiles(),
                    "burn_rates": sched._slo.burn_rates(),
                }
        if profiling_policy is not None and profiling_policy.timeline:
            # wave-timeline read-out: expose_metrics drains the worker
            # seam (remote backend) into the ring and refreshes the
            # union-derived gauges, then the summary + per-segment
            # quantiles land in the BENCH row
            sched = cluster.scheduler
            sched.expose_metrics()
            tl = sched._timeline
            if tl is not None:
                stats["timeline"] = {
                    **tl.snapshot_summary(),
                    "pods_decomposed": len(tl.pods()),
                    "segments": sched.metrics.segment_summary(),
                }
        if overload is not None:
            cluster.scheduler.expose_metrics()  # drain shed/defer tallies
            prom = cluster.scheduler.metrics.prom
            tuner = cluster.scheduler._wave_tuner
            stats["overload"] = {
                "shed": {f"{r}/{b}": v for (r, b), v
                         in prom.queue_shed_total.values().items()},
                "deferred": sum(
                    prom.overload_deferred_total.values().values()),
                "wave_cancels": sum(
                    prom.overload_wave_cancel_total.values().values()),
                "final_wave": (tuner.current() if tuner is not None
                               else batch_size),
                "engagement": cluster.scheduler.overload_engagement,
                "transitions": {
                    f"{f}->{t}/{r}": v for (f, t, r), v
                    in prom.overload_transition_total.values().items()},
            }
        return summary, stats
    finally:
        cluster.shutdown()
