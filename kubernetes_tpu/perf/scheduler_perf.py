"""scheduler_perf — the reference's scale benchmark harness, ported.

Reference: test/integration/scheduler_perf/
  scheduler_perf_test.go:57-63  workload opcodes: createNodes / createPods /
                                churn / barrier / sleep
  util.go:79  mustSetupScheduler (in-proc apiserver + real scheduler)
  util.go:288-355  throughputCollector: samples scheduled-pod count at a
                   fixed window -> SchedulingThroughput Average/PercNN
  config/performance-config.yaml  workload definitions

Workloads are YAML/dict configs of the same shape:

  name: SchedulingBasic
  workloadTemplate:
    - opcode: createNodes
      count: 500
    - opcode: createPods
      count: 500
      podTemplate: {...}         # optional; default is a small-request pod
    - opcode: barrier            # wait until all pending pods scheduled
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any

from ..api import meta
from ..client import LocalClient, SharedInformerFactory
from ..client.clientset import NODES, PODS
from ..scheduler import Profile, Scheduler, new_default_framework, new_scheduler
from ..store import kv
from ..testing import make_node, make_pod

DEFAULT_SAMPLE_INTERVAL = 1.0  # util.go: 1s window


@dataclass
class ThroughputSummary:
    average: float = 0.0
    perc50: float = 0.0
    perc90: float = 0.0
    perc99: float = 0.0
    total_pods: int = 0
    duration: float = 0.0

    def to_dict(self) -> dict:
        return {"Average": round(self.average, 1), "Perc50": round(self.perc50, 1),
                "Perc90": round(self.perc90, 1), "Perc99": round(self.perc99, 1),
                "TotalPods": self.total_pods,
                "DurationSeconds": round(self.duration, 2)}


class ThroughputCollector:
    """Samples scheduled-pod deltas per window (util.go:288-355)."""

    def __init__(self, store: kv.MemoryStore, interval: float = DEFAULT_SAMPLE_INTERVAL):
        self.store = store
        self.interval = interval
        self.samples: list[float] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._start_time = 0.0
        self._start_count = 0

    def _scheduled_count(self) -> int:
        items, _ = self.store.list(PODS)
        return sum(1 for p in items if meta.pod_node_name(p))

    def start(self) -> None:
        self._start_time = time.monotonic()
        self._start_count = self._scheduled_count()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        last = self._start_count
        while not self._stop.wait(self.interval):
            cur = self._scheduled_count()
            self.samples.append((cur - last) / self.interval)
            last = cur

    def stop(self) -> ThroughputSummary:
        self._stop.set()
        if self._thread:
            self._thread.join(2.0)
        end = time.monotonic()
        total = self._scheduled_count() - self._start_count
        dur = max(end - self._start_time, 1e-9)
        s = ThroughputSummary(total_pods=total, duration=dur,
                              average=total / dur)
        if self.samples:
            xs = sorted(self.samples)
            def perc(p: float) -> float:
                return xs[min(int(len(xs) * p), len(xs) - 1)]
            s.perc50, s.perc90, s.perc99 = perc(0.50), perc(0.90), perc(0.99)
        return s


@dataclass
class PerfCluster:
    store: kv.MemoryStore
    client: LocalClient
    factory: SharedInformerFactory
    scheduler: Scheduler

    def shutdown(self) -> None:
        self.scheduler.stop()
        self.factory.stop()
        self.client.close()  # event-broadcaster thread


def setup_cluster(tpu: bool = False, caps=None, batch_size: int = 512,
                  store: kv.MemoryStore | None = None) -> PerfCluster:
    """mustSetupScheduler (util.go:79): in-proc everything, no kubelet."""
    from ..utils.gctune import tune_for_throughput
    tune_for_throughput()  # CPython gen-2 pauses cost ~35% at bench scale
    store = store or kv.MemoryStore(history=1_000_000)
    client = LocalClient(store)
    factory = SharedInformerFactory(client)
    if tpu:
        from ..ops.backend import TPUBatchBackend
        from ..ops.flatten import Caps
        backend = TPUBatchBackend(caps or Caps(), batch_size=batch_size)
        backend.warmup()
        fw = new_default_framework(client, factory)
        profiles = {"default-scheduler": Profile(
            fw, batch_backend=backend, batch_size=batch_size)}
        sched = Scheduler(client, factory, profiles)
    else:
        sched = new_scheduler(client, factory)
    factory.start()
    factory.wait_for_cache_sync()
    sched.run()
    return PerfCluster(store, client, factory, sched)


# -- workload ops (scheduler_perf_test.go opcodes) -------------------------

def _default_pod(i: int, params: dict) -> dict:
    w = make_pod(params.get("podNamePrefix", "pod-") + str(i),
                 params.get("namespace", "default"))
    tmpl = params.get("podTemplate") or {}
    if tmpl:
        pod = meta.deep_copy(w.build())
        spec = meta.deep_copy(tmpl.get("spec") or {})
        pod["spec"].update(spec)
        if "metadata" in tmpl:
            md = meta.deep_copy(tmpl["metadata"])
            name = pod["metadata"]["name"]
            ns = pod["metadata"]["namespace"]
            pod["metadata"].update(md)
            pod["metadata"]["name"] = name
            pod["metadata"]["namespace"] = ns
        return pod
    return w.req(cpu=params.get("cpu", "100m"),
                 mem=params.get("memory", "128Mi")).build()


def _default_node(i: int, params: dict) -> dict:
    w = make_node(params.get("nodeNamePrefix", "node-") + str(i))
    w.capacity(cpu=params.get("cpu", "32"), mem=params.get("memory", "256Gi"),
               pods=params.get("pods", 110))
    labels = dict(params.get("labels") or {})
    if params.get("zones"):
        zones = params["zones"]
        labels["topology.kubernetes.io/zone"] = zones[i % len(zones)]
    labels.setdefault("kubernetes.io/hostname", meta.name(w.obj))
    w.labels(**labels)
    return w.build()


def _bulk_create(client, resource: str, count: int, offset: int,
                 build, op: dict, chunk: int = 512) -> None:
    """createNodes/createPods submission: chunked bulk writes when the
    client supports ownership-transfer bulk create (the reference harness
    pumps objects through a 5000-QPS/5000-burst client, util.go:92;
    chunked create_many is the LocalClient transport analog)."""
    creator = getattr(client, "create_bulk", None)
    if creator is not None and count >= 256:
        for lo in range(0, count, chunk):
            creator(resource, [build(offset + i, op)
                               for i in range(lo, min(lo + chunk, count))])
    else:
        for i in range(count):
            client.create(resource, build(offset + i, op))


def wait_for_pods_scheduled(cluster: PerfCluster, want: int,
                            timeout: float = 600.0, namespace=None) -> bool:
    """barrier opcode: wait until `want` pods have nodeName set."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        items, _ = cluster.store.list(PODS, namespace)
        n = sum(1 for p in items if meta.pod_node_name(p))
        if n >= want:
            return True
        time.sleep(0.05)
    return False


def run_workload(cluster: PerfCluster, ops: list[dict],
                 collector: ThroughputCollector | None = None) -> dict:
    """Execute a workloadTemplate op list. Returns op stats."""
    created_pods = 0
    created_nodes = 0
    stats: dict[str, Any] = {}
    churn_stop: list[threading.Event] = []
    for op in ops:
        opcode = op["opcode"]
        if opcode == "createNodes":
            _bulk_create(cluster.client, NODES, op["count"], created_nodes,
                         _default_node, op)
            created_nodes += op["count"]
        elif opcode == "createPods":
            _bulk_create(cluster.client, PODS, op["count"], created_pods,
                         _default_pod, op)
            created_pods += op["count"]
        elif opcode == "barrier":
            want = op.get("count", created_pods)
            ok = wait_for_pods_scheduled(cluster, want,
                                         timeout=op.get("timeout", 600.0))
            stats["barrier_ok"] = ok
        elif opcode == "sleep":
            time.sleep(op.get("duration", 1.0))
        elif opcode == "churn":
            # background create/delete loop (scheduler_perf churn op)
            ev = threading.Event()
            churn_stop.append(ev)
            interval = op.get("intervalMilliseconds", 500) / 1000.0

            def churn_loop(ev=ev, interval=interval, op=op):
                i = 0
                while not ev.wait(interval):
                    name = f"churn-{i}"
                    try:
                        cluster.client.create(
                            PODS, make_pod(name, "churn").req(cpu="1m").build())
                        cluster.client.delete(PODS, "churn", name)
                    except kv.StoreError:
                        pass
                    i += 1

            threading.Thread(target=churn_loop, daemon=True).start()
        else:
            raise ValueError(f"unknown opcode {opcode!r}")
    for ev in churn_stop:
        ev.set()
    stats["created_pods"] = created_pods
    stats["created_nodes"] = created_nodes
    return stats


def run_named_workload(config: dict, tpu: bool = False, caps=None,
                       batch_size: int = 512) -> tuple[ThroughputSummary, dict]:
    """Run one workload config end to end; returns (throughput, stats)."""
    cluster = setup_cluster(tpu=tpu, caps=caps, batch_size=batch_size)
    collector = ThroughputCollector(cluster.store)
    try:
        ops = config["workloadTemplate"]
        t0 = time.monotonic()
        collector.start()
        stats = run_workload(cluster, ops, collector)
        summary = collector.stop()
        stats["wall"] = time.monotonic() - t0
        stats["e2e"] = cluster.scheduler.metrics.e2e_summary()
        return summary, stats
    finally:
        cluster.shutdown()
