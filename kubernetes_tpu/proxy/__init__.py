"""Service dataplane (reference: pkg/proxy)."""

from .proxier import ServiceProxy  # noqa: F401
