"""kube-proxy: Service -> Endpoint dataplane.

Reference: pkg/proxy/
  iptables/proxier.go:775 (syncProxyRules: full ruleset rebuild per sync,
    rendered as ONE iptables-restore input; change trackers in
    pkg/proxy/{service,endpoints}.go)
  ipvs/proxier.go:1019 (virtual-server table + real servers per service)
  session affinity: ClientIP -> recent-client map with timeout
    (proxier.go affinity tracking / iptables -m recent)

The in-process dataplane is a rule table: each Service clusterIP:port (and
NodePort) maps to its backend endpoints, `route()` performs the random
endpoint selection iptables' statistic module does (or ipvs round-robin in
ipvs mode), and `render_iptables()`/`render_ipvs()` emit the textual rule
program a real node agent would hand to iptables-restore / ipvsadm —
the table shape matches what syncProxyRules builds.

Backends come from EndpointSlices (discovery.k8s.io, the reference's
default since 1.19) with legacy Endpoints as fallback when no slice
exists for a service.
"""

from __future__ import annotations

import logging
import random
import threading
import time

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import ENDPOINTS, ENDPOINTSLICES, SERVICES, Client
from ..client.informer import SharedInformerFactory

logger = logging.getLogger(__name__)

SERVICE_NAME_LABEL = "kubernetes.io/service-name"
MODE_IPTABLES = "iptables"
MODE_IPVS = "ipvs"


class ServiceProxy:
    def __init__(self, client: Client, factory: SharedInformerFactory,
                 node_name: str = "", mode: str = MODE_IPTABLES):
        self.client = client
        self.node_name = node_name
        self.mode = mode
        self.svc_informer = factory.informer(SERVICES)
        self.ep_informer = factory.informer(ENDPOINTS)
        self.slice_informer = factory.informer(ENDPOINTSLICES)
        self._lock = threading.Lock()
        # (ip, port, proto) -> {"service", "backends", "affinity",
        #                       "affinity_seconds"}; NodePorts use ip=""
        self.rules: dict[tuple[str, int, str], dict] = {}
        # session affinity state: (rule key, client ip) -> (backend, stamp)
        self._affinity: dict[tuple, tuple[tuple[str, int], float]] = {}
        self._rr: dict[tuple, int] = {}  # ipvs round-robin cursors
        self.sync_count = 0
        self._pending = threading.Event()
        for inf in (self.svc_informer, self.ep_informer, self.slice_informer):
            inf.add_event_handler(lambda *a: self._pending.set())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ServiceProxy":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"kube-proxy-{self.node_name}")
        self._thread.start()
        self._pending.set()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pending.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._pending.wait(timeout=1.0):
                self._pending.clear()
                try:
                    self.sync_proxy_rules()
                except Exception:  # noqa: BLE001
                    logger.exception("syncProxyRules failed")

    # -- backend collection ----------------------------------------------

    def _slice_backends(self, svc: Obj, slices_by_svc: dict) -> \
            dict[str, list] | None:
        """port-name -> [(ip, port)] from EndpointSlices, None if no slice
        exists for the service (fall back to legacy Endpoints)."""
        slices = slices_by_svc.get(
            (meta.namespace(svc), meta.name(svc)))
        if not slices:
            return None
        out: dict[str, list] = {}
        for sl in slices:
            ports = sl.get("ports") or ()
            for ep in sl.get("endpoints") or ():
                if not (ep.get("conditions") or {}).get("ready", True):
                    continue
                for addr in ep.get("addresses") or ():
                    for p in ports:
                        out.setdefault(p.get("name") or "", []).append(
                            (addr, p.get("port")))
        return out

    def _endpoints_backends(self, svc: Obj) -> dict[str, list]:
        ep = self.ep_informer.get(meta.namespace(svc), meta.name(svc))
        out: dict[str, list] = {}
        for subset in (ep or {}).get("subsets") or ():
            for port in subset.get("ports") or ():
                out.setdefault(port.get("name", ""), [])
                for addr in subset.get("addresses") or ():
                    out[port.get("name", "")].append(
                        (addr["ip"], port["port"]))
        return out

    # syncProxyRules (iptables/proxier.go:775): full rebuild each sync
    def sync_proxy_rules(self) -> None:
        new_rules: dict[tuple[str, int, str], dict] = {}
        # one slice index per sync: O(services + slices), not services*slices
        slices_by_svc: dict[tuple[str, str], list] = {}
        for sl in self.slice_informer.list():
            svc_name = meta.labels(sl).get(SERVICE_NAME_LABEL)
            if svc_name:
                slices_by_svc.setdefault(
                    (meta.namespace(sl), svc_name), []).append(sl)
        for svc in self.svc_informer.list():
            spec = svc.get("spec") or {}
            cluster_ip = spec.get("clusterIP")
            if not cluster_ip or cluster_ip == "None":
                continue
            backends = self._slice_backends(svc, slices_by_svc)
            if backends is None:
                backends = self._endpoints_backends(svc)
            affinity = (spec.get("sessionAffinity") == "ClientIP")
            aff_secs = (((spec.get("sessionAffinityConfig") or {})
                         .get("clientIP") or {}).get("timeoutSeconds")
                        or 10800)
            for p in spec.get("ports") or ():
                entry = {
                    "service": meta.namespaced_name(svc),
                    "backends": backends.get(p.get("name") or "", []),
                    "affinity": affinity,
                    "affinity_seconds": aff_secs,
                }
                proto = p.get("protocol", "TCP")
                new_rules[(cluster_ip, p.get("port"), proto)] = entry
                node_port = p.get("nodePort")
                if node_port and spec.get("type") in ("NodePort",
                                                      "LoadBalancer"):
                    # NodePort rules match any node IP; key on ip=""
                    new_rules[("", node_port, proto)] = entry
        with self._lock:
            self.rules = new_rules
            self.sync_count += 1
            # prune dead rules AND expired pins (kube-proxy ages affinity
            # entries out; without this the map grows one entry per client)
            now = time.time()
            self._affinity = {
                k: v for k, v in self._affinity.items()
                if k[0] in new_rules
                and now - v[1] < new_rules[k[0]]["affinity_seconds"]}
            self._rr = {k: v for k, v in self._rr.items() if k in new_rules}

    # -- the dataplane lookup (what the DNAT chain / ipvs director does) --

    def route(self, ip: str, port: int, proto: str = "TCP",
              client_ip: str = "", rng: random.Random | None = None,
              now: float | None = None) -> tuple[str, int] | None:
        """Resolve a (virtual ip, port) to a backend.  ip="" or an unknown
        ip with a NodePort rule matches the NodePort path."""
        now = time.time() if now is None else now
        with self._lock:
            key = (ip, port, proto)
            rule = self.rules.get(key)
            if rule is None:
                key = ("", port, proto)  # NodePort: matches any node ip
                rule = self.rules.get(key)
            if not rule or not rule["backends"]:
                return None
            # affinity/rr state keys on the MATCHED rule key, so NodePort
            # lookups via concrete node ips share state and survive the
            # sync-time prune
            if rule["affinity"] and client_ip:
                akey = (key, client_ip)
                hit = self._affinity.get(akey)
                if (hit and hit[0] in rule["backends"]
                        and now - hit[1] < rule["affinity_seconds"]):
                    self._affinity[akey] = (hit[0], now)
                    return hit[0]
            if self.mode == MODE_IPVS:
                cur = self._rr.get(key, 0)
                self._rr[key] = cur + 1
                backend = rule["backends"][cur % len(rule["backends"])]
            else:
                backend = (rng or random).choice(rule["backends"])
            if rule["affinity"] and client_ip:
                self._affinity[(key, client_ip)] = (backend, now)
            return backend

    def rule_table(self) -> dict:
        with self._lock:
            return {f"{ip or '*'}:{port}/{proto}": dict(r)
                    for (ip, port, proto), r in self.rules.items()}

    # -- rule-program rendering ------------------------------------------

    def render_iptables(self) -> str:
        """The iptables-restore input syncProxyRules writes (shape of
        proxier.go's natRules: KUBE-SERVICES -> KUBE-SVC-* -> KUBE-SEP-*
        with statistic-module probabilities)."""
        lines = ["*nat", ":KUBE-SERVICES - [0:0]", ":KUBE-NODEPORTS - [0:0]"]
        # the terminal rule that links NodePorts into the traffic path
        # (syncProxyRules appends it after all per-service rules)
        chains: list[str] = [
            "-A KUBE-SERVICES -m addrtype --dst-type LOCAL "
            "-j KUBE-NODEPORTS"]
        with self._lock:
            items = sorted(self.rules.items(),
                           key=lambda kv: (kv[1]["service"], kv[0]))
            for (ip, port, proto), rule in items:
                svc_id = rule["service"].replace("/", "-").upper()
                svc_chain = f"KUBE-SVC-{svc_id}-{port}"
                lines.append(f":{svc_chain} - [0:0]")
                if ip:
                    lines.append(
                        f"-A KUBE-SERVICES -d {ip}/32 -p {proto.lower()} "
                        f"--dport {port} -j {svc_chain}")
                else:
                    lines.append(
                        f"-A KUBE-NODEPORTS -p {proto.lower()} "
                        f"--dport {port} -j {svc_chain}")
                n = len(rule["backends"])
                for i, (bip, bport) in enumerate(rule["backends"]):
                    sep = f"KUBE-SEP-{svc_id}-{port}-{i}"
                    lines.append(f":{sep} - [0:0]")
                    if i < n - 1:
                        prob = 1.0 / (n - i)
                        chains.append(
                            f"-A {svc_chain} -m statistic --mode random "
                            f"--probability {prob:.5f} -j {sep}")
                    else:
                        chains.append(f"-A {svc_chain} -j {sep}")
                    chains.append(
                        f"-A {sep} -p {proto.lower()} -j DNAT "
                        f"--to-destination {bip}:{bport}")
        lines.extend(chains)
        lines.append("COMMIT")
        return "\n".join(lines) + "\n"

    def render_ipvs(self) -> str:
        """The ipvsadm program (ipvs/proxier.go virtual/real servers)."""
        lines = []
        with self._lock:
            items = sorted(self.rules.items(),
                           key=lambda kv: (kv[1]["service"], kv[0]))
            for (ip, port, proto), rule in items:
                flag = "-t" if proto == "TCP" else "-u"
                vip = ip or "<node-ip>"
                persist = (f" -p {rule['affinity_seconds']}"
                           if rule["affinity"] else "")
                lines.append(f"-A {flag} {vip}:{port} -s rr{persist}")
                for bip, bport in rule["backends"]:
                    lines.append(f"-a {flag} {vip}:{port} -r {bip}:{bport} -m")
        return "\n".join(lines) + "\n"
