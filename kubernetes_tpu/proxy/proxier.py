"""kube-proxy: Service -> Endpoint dataplane, simulated.

Reference: pkg/proxy/iptables/proxier.go:775 (syncProxyRules: rebuild the
full ruleset on every change, via change trackers in pkg/proxy/{service,
endpoints}.go).  The dataplane here is a rule table instead of netfilter:
each Service clusterIP:port maps to its backend endpoints, and route()
performs the random-endpoint selection iptables' statistic module does.
A real node agent would render self.rules into iptables-restore input —
the shape of the table matches what syncProxyRules builds.
"""

from __future__ import annotations

import logging
import random
import threading

from ..api import meta
from ..api.meta import Obj
from ..client.clientset import ENDPOINTS, SERVICES, Client
from ..client.informer import SharedInformerFactory

logger = logging.getLogger(__name__)


class ServiceProxy:
    def __init__(self, client: Client, factory: SharedInformerFactory,
                 node_name: str = ""):
        self.client = client
        self.node_name = node_name
        self.svc_informer = factory.informer(SERVICES)
        self.ep_informer = factory.informer(ENDPOINTS)
        self._lock = threading.Lock()
        # (clusterIP, port, proto) -> {"service": ns/name, "backends": [(ip, port)]}
        self.rules: dict[tuple[str, int, str], dict] = {}
        self.sync_count = 0
        self._pending = threading.Event()
        self.svc_informer.add_event_handler(lambda *a: self._pending.set())
        self.ep_informer.add_event_handler(lambda *a: self._pending.set())
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "ServiceProxy":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"kube-proxy-{self.node_name}")
        self._thread.start()
        self._pending.set()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._pending.set()

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._pending.wait(timeout=1.0):
                self._pending.clear()
                try:
                    self.sync_proxy_rules()
                except Exception:  # noqa: BLE001
                    logger.exception("syncProxyRules failed")

    # syncProxyRules (iptables/proxier.go:775): full rebuild each sync
    def sync_proxy_rules(self) -> None:
        new_rules: dict[tuple[str, int, str], dict] = {}
        eps_by_key = {meta.namespaced_name(ep): ep
                      for ep in self.ep_informer.list()}
        for svc in self.svc_informer.list():
            spec = svc.get("spec") or {}
            cluster_ip = spec.get("clusterIP")
            if not cluster_ip or cluster_ip == "None":
                continue
            ep = eps_by_key.get(meta.namespaced_name(svc))
            backends_by_portname: dict[str, list[tuple[str, int]]] = {}
            for subset in (ep or {}).get("subsets") or ():
                for port in subset.get("ports") or ():
                    backends_by_portname.setdefault(port.get("name", ""), [])
                    for addr in subset.get("addresses") or ():
                        backends_by_portname[port.get("name", "")].append(
                            (addr["ip"], port["port"]))
            for p in spec.get("ports") or ():
                key = (cluster_ip, p.get("port"), p.get("protocol", "TCP"))
                new_rules[key] = {
                    "service": meta.namespaced_name(svc),
                    "backends": backends_by_portname.get(p.get("name", ""), []),
                }
        with self._lock:
            self.rules = new_rules
            self.sync_count += 1

    # the dataplane lookup (what an iptables DNAT chain would do)
    def route(self, cluster_ip: str, port: int, proto: str = "TCP",
              rng: random.Random | None = None) -> tuple[str, int] | None:
        with self._lock:
            rule = self.rules.get((cluster_ip, port, proto))
            if not rule or not rule["backends"]:
                return None
            return (rng or random).choice(rule["backends"])

    def rule_table(self) -> dict:
        with self._lock:
            return {f"{ip}:{port}/{proto}": dict(r)
                    for (ip, port, proto), r in self.rules.items()}
