"""TPU-native scheduler (reference: pkg/scheduler)."""

from __future__ import annotations

from ..client.clientset import Client
from ..client.informer import SharedInformerFactory
from .cache import Cache, Snapshot
from .config import (
    SchedulerConfig, load_config, scheduler_from_config,
)
from .extender import Extender, HTTPExtender
from .framework import CycleState, Framework, Handle
from .plugins import DEFAULT_PLUGINS, DEFAULT_SCORE_WEIGHTS, build_default_plugins
from .queue import SchedulingQueue
from .scheduler import BatchBackend, Profile, Scheduler
from .types import FitError, NodeInfo, PodInfo, QueuedPodInfo, Status


def new_default_framework(client: Client, informer_factory=None,
                          profile_name: str = "default-scheduler",
                          enabled: list[str] | None = None,
                          plugin_args: dict | None = None,
                          score_weights: dict[str, int] | None = None) -> Framework:
    handle = Handle(client=client, informer_factory=informer_factory)
    plugins = build_default_plugins(handle, enabled, plugin_args)
    return Framework(profile_name, plugins,
                     score_weights=score_weights or DEFAULT_SCORE_WEIGHTS,
                     handle=handle)


def new_scheduler(client: Client, informer_factory: SharedInformerFactory,
                  profiles: dict[str, Profile] | None = None) -> Scheduler:
    """scheduler.New (scheduler.go:239) with default profile."""
    if profiles is None:
        fw = new_default_framework(client, informer_factory)
        profiles = {"default-scheduler": Profile(fw)}
    return Scheduler(client, informer_factory, profiles)
