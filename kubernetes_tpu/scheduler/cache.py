"""Scheduler cache: authoritative in-memory cluster mirror with the
assume/confirm lifecycle and incremental snapshotting.

Reference semantics:
  pkg/scheduler/internal/cache/interface.go:59-104 (Cache contract)
  pkg/scheduler/internal/cache/cache.go:197 (UpdateSnapshot: generation-based
    delta copy — only NodeInfos whose generation advanced since the last
    snapshot are re-cloned)
  pkg/scheduler/internal/cache/snapshot.go:29-43 (Snapshot: ordered node list
    + affinity sublists + usedPVCSet; implements SharedLister)

The assume/confirm protocol is what lets scheduling run ahead of the
apiserver: `assume` optimistically adds the pod to the target node before the
Binding write lands; the informer's Add event later *confirms* it; `forget`
rolls it back on bind failure.  The TPU batch path relies on this exactly as
the per-pod path does — each assignment out of a batch is assumed
individually so failure handling stays per-pod.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Iterable

from ..api import meta
from ..api.meta import Obj
from .types import NodeInfo, PodInfo, _generation

logger = logging.getLogger(__name__)


class Snapshot:
    """Immutable per-cycle view of the cluster (snapshot.go:29).

    `generation` is the max NodeInfo generation included; UpdateSnapshot uses
    it to copy only dirty nodes.  The TPU flattener keys its dirty-row
    re-encode off per-node generations too (ops/flatten.py).
    """

    def __init__(self) -> None:
        self.node_info_map: dict[str, NodeInfo] = {}
        self.node_info_list: list[NodeInfo] = []
        self.have_pods_with_affinity_list: list[NodeInfo] = []
        self.have_pods_with_required_anti_affinity_list: list[NodeInfo] = []
        self.used_pvc_set: set[str] = set()
        self.generation: int = 0

    # SharedLister surface (framework.SharedLister)
    def get(self, node_name: str) -> NodeInfo | None:
        return self.node_info_map.get(node_name)

    def list(self) -> list[NodeInfo]:
        return self.node_info_list

    def __len__(self) -> int:
        return len(self.node_info_list)


class _PodState:
    __slots__ = ("pod", "assumed", "binding_finished", "deadline")

    def __init__(self, pod: Obj, assumed: bool = False):
        self.pod = pod
        self.assumed = assumed
        self.binding_finished = False
        self.deadline: float | None = None


class Cache:
    """scheduler cache (cache.go)."""

    def __init__(self, ttl: float = 0.0):
        self._lock = threading.RLock()
        self._nodes: dict[str, NodeInfo] = {}
        self._pod_states: dict[str, _PodState] = {}
        self._assumed_pods: set[str] = set()
        self._ttl = ttl  # 0 = assumed pods never expire (reference default, scheduler.go:54)
        # Bumped on every mutation the TPU batch backend does NOT already
        # know about (everything except bulk batch-assume, the matching
        # confirm fast path, and finish_binding).  The backend's host
        # mirror replays its own batches' commits, so when this epoch is
        # unchanged between dispatches the whole node re-encode + mirror
        # diff is provably a no-op and is skipped (ops/backend.py).
        self.mutation_epoch = 0
        # Incremental flatten feed: names of nodes whose NodeInfo changed /
        # went dead since the last drain.  ONE consumer (the scheduler's
        # batch backend via CacheFlattenView.run_locked_dirty) — a second
        # draining view would starve the first.  _flatten_synced gates the
        # first drain to a full scan so a consumer attaching to a
        # pre-populated cache misses nothing.
        self._dirty_nodes: set[str] = set()
        self._removed_nodes: set[str] = set()
        self._flatten_synced = False

    # -- pods ------------------------------------------------------------

    def stats(self) -> dict[str, int]:
        """Cache sizes for the scheduler_cache_size{type=} gauge."""
        with self._lock:
            return {"nodes": len(self._nodes),
                    "pods": len(self._pod_states),
                    "assumed_pods": len(self._assumed_pods)}

    def assume_pod(self, pod: Obj) -> None:
        key = meta.namespaced_name(pod)
        with self._lock:
            if key in self._pod_states:
                raise ValueError(f"pod {key} already in cache")
            self.mutation_epoch += 1
            self._add_pod_to_node(pod)
            ps = _PodState(pod, assumed=True)
            self._pod_states[key] = ps
            self._assumed_pods.add(key)

    def assume_pods(self, items: list[tuple[Obj, "PodInfo"]]
                    ) -> list[str | None]:
        """Bulk assume under ONE lock acquisition (batch tail hot path).

        Each item is (assumed_pod, pod_info) where pod_info is a
        clone_with_pod of the already-parsed PodInfo — skips both the
        per-pod lock round trip and the PodInfo re-parse.  Returns one
        error string (or None) per item, same order."""
        errs: list[str | None] = []
        with self._lock:
            for pod, pi in items:
                key = pi.key
                if key in self._pod_states:
                    errs.append(f"pod {key} already in cache")
                    continue
                node_name = meta.pod_node_name(pod)
                if node_name:
                    ni = self._nodes.get(node_name)
                    if ni is None:
                        ni = self._nodes[node_name] = NodeInfo()
                    ni.add_pod(pi)
                    self._dirty_nodes.add(node_name)
                ps = _PodState(pod, assumed=True)
                self._pod_states[key] = ps
                self._assumed_pods.add(key)
                errs.append(None)
        return errs

    def finish_binding(self, pod: Obj) -> None:
        key = meta.namespaced_name(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps and ps.assumed:
                ps.binding_finished = True
                if self._ttl > 0:
                    ps.deadline = time.monotonic() + self._ttl

    def finish_bindings(self, pods: list[Obj]) -> None:
        """Bulk finish_binding under one lock (batch bind tail)."""
        with self._lock:
            now = time.monotonic() if self._ttl > 0 else 0.0
            for pod in pods:
                ps = self._pod_states.get(meta.namespaced_name(pod))
                if ps and ps.assumed:
                    ps.binding_finished = True
                    if self._ttl > 0:
                        ps.deadline = now + self._ttl

    def forget_pod(self, pod: Obj) -> None:
        key = meta.namespaced_name(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                return
            if not ps.assumed:
                raise ValueError(f"pod {key} is not assumed; cannot forget")
            self.mutation_epoch += 1
            self._remove_pod_from_node(ps.pod)
            del self._pod_states[key]
            self._assumed_pods.discard(key)

    def add_pod(self, pod: Obj) -> None:
        """Informer confirm: pod observed bound via watch."""
        key = meta.namespaced_name(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is not None and ps.assumed:
                # confirmation of an assumed pod
                if meta.pod_node_name(ps.pod) != meta.pod_node_name(pod):
                    # scheduled somewhere else than assumed: fix up
                    self.mutation_epoch += 1
                    self._remove_pod_from_node(ps.pod)
                    self._add_pod_to_node(pod)
                self._pod_states[key] = _PodState(pod)
                self._assumed_pods.discard(key)
            elif ps is None:
                self.mutation_epoch += 1
                self._add_pod_to_node(pod)
                self._pod_states[key] = _PodState(pod)
            else:
                # duplicate add — treat as update
                self.mutation_epoch += 1
                self._remove_pod_from_node(ps.pod)
                self._add_pod_to_node(pod)
                self._pod_states[key] = _PodState(pod)

    def confirm_or_add_pods(self, pods: list[Obj]) -> None:
        """Bulk add_pod for a burst of newly-bound watch events (the
        scheduler's own binds coming back).  Fast path: the pod is assumed
        on the same node — just swap in the confirmed state.  Everything
        else takes the ordinary add_pod route.  One lock round per burst."""
        states = self._pod_states
        assumed = self._assumed_pods
        mk = _PodState
        with self._lock:
            for pod in pods:
                md = pod["metadata"]
                ns = md.get("namespace", "")
                key = f"{ns}/{md['name']}" if ns else md["name"]
                ps = states.get(key)
                if ps is not None and ps.assumed and (
                        (ps.pod.get("spec") or {}).get("nodeName")
                        == (pod.get("spec") or {}).get("nodeName")):
                    states[key] = mk(pod)
                    assumed.discard(key)
                else:
                    self.add_pod(pod)  # RLock: safe to re-enter

    def update_pod(self, old: Obj, new: Obj) -> None:
        key = meta.namespaced_name(new)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                self.add_pod(new)
                return
            self.mutation_epoch += 1
            self._remove_pod_from_node(ps.pod)
            self._add_pod_to_node(new)
            self._pod_states[key] = _PodState(new)
            self._assumed_pods.discard(key)

    def remove_pod(self, pod: Obj) -> None:
        key = meta.namespaced_name(pod)
        with self._lock:
            ps = self._pod_states.get(key)
            if ps is None:
                return
            self.mutation_epoch += 1
            self._remove_pod_from_node(ps.pod)
            del self._pod_states[key]
            self._assumed_pods.discard(key)

    def is_assumed_pod(self, pod: Obj) -> bool:
        with self._lock:
            return meta.namespaced_name(pod) in self._assumed_pods

    def get_pod(self, pod: Obj) -> Obj | None:
        with self._lock:
            ps = self._pod_states.get(meta.namespaced_name(pod))
            return ps.pod if ps else None

    def assumed_pod_count(self) -> int:
        with self._lock:
            return len(self._assumed_pods)

    def _add_pod_to_node(self, pod: Obj) -> None:
        node_name = meta.pod_node_name(pod)
        if not node_name:
            return
        ni = self._nodes.get(node_name)
        if ni is None:
            # pod bound to a node we haven't seen yet: create placeholder
            # (reference keeps imaginary nodes for this case)
            ni = self._nodes[node_name] = NodeInfo()
        ni.add_pod(PodInfo(pod))
        self._dirty_nodes.add(node_name)

    def _remove_pod_from_node(self, pod: Obj) -> None:
        node_name = meta.pod_node_name(pod)
        ni = self._nodes.get(node_name)
        if ni is not None:
            ni.remove_pod(pod)
            self._dirty_nodes.add(node_name)
            if ni.node is None and not ni.pods:
                del self._nodes[node_name]

    # -- nodes -----------------------------------------------------------

    def add_node(self, node: Obj) -> None:
        self.add_nodes([node])

    def add_nodes(self, nodes: list[Obj]) -> None:
        """Bulk add/update: one lock round for a registration flood (a
        100k-node creation burst otherwise pays a lock acquire + epoch
        bump per node on the informer thread)."""
        with self._lock:
            self.mutation_epoch += 1
            table = self._nodes
            dirty = self._dirty_nodes
            removed = self._removed_nodes
            for node in nodes:
                name = meta.name(node)
                ni = table.get(name)
                if ni is None:
                    ni = table[name] = NodeInfo()
                ni.set_node(node)
                dirty.add(name)
                removed.discard(name)

    def update_node(self, node: Obj) -> None:
        self.add_node(node)

    def remove_node(self, node: Obj) -> None:
        name = meta.name(node)
        with self._lock:
            ni = self._nodes.get(name)
            if ni is None:
                return
            self.mutation_epoch += 1
            if ni.pods:
                # keep NodeInfo for remaining (possibly assumed) pods
                ni.node = None
                ni.generation = next(_generation)
            else:
                del self._nodes[name]
            # either way the node left the schedulable set
            self._dirty_nodes.discard(name)
            self._removed_nodes.add(name)

    def node_count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def pod_count(self) -> int:
        with self._lock:
            return sum(len(ni.pods) for ni in self._nodes.values())

    # -- snapshot --------------------------------------------------------

    def update_snapshot(self, snapshot: Snapshot) -> Snapshot:
        """Incremental snapshot refresh (cache.go:197).

        Copies only NodeInfos whose generation advanced past the snapshot's;
        rebuilds the ordered lists only when membership or affinity-list
        composition changed.
        """
        with self._lock:
            changed = False
            max_gen = snapshot.generation
            for name, ni in self._nodes.items():
                if ni.node is None:
                    continue  # placeholder for orphaned assumed pods
                if ni.generation > snapshot.generation:
                    snapshot.node_info_map[name] = ni.clone()
                    changed = True
                    if ni.generation > max_gen:
                        max_gen = ni.generation
            # removals
            live = {n for n, ni in self._nodes.items() if ni.node is not None}
            if len(snapshot.node_info_map) != len(live):
                for name in list(snapshot.node_info_map):
                    if name not in live:
                        del snapshot.node_info_map[name]
                changed = True
            snapshot.generation = max_gen
            if changed:
                snapshot.node_info_list = list(snapshot.node_info_map.values())
                snapshot.have_pods_with_affinity_list = [
                    ni for ni in snapshot.node_info_list if ni.pods_with_affinity]
                snapshot.have_pods_with_required_anti_affinity_list = [
                    ni for ni in snapshot.node_info_list
                    if ni.pods_with_required_anti_affinity]
                snapshot.used_pvc_set = {
                    pvc for ni in snapshot.node_info_list for pvc in ni.pvc_ref_counts}
            return snapshot

    def flatten_view(self) -> "CacheFlattenView":
        """Zero-copy view for the TPU batch flattener (see
        CacheFlattenView)."""
        return CacheFlattenView(self)

    def comparison_snapshot(self) -> tuple[set[str], set[str], set[str]]:
        """(node names, pod keys, assumed pod keys) under one lock — the
        comparer's view (internal/cache/debugger/comparer.go)."""
        with self._lock:
            return ({n for n, ni in self._nodes.items() if ni.node is not None},
                    set(self._pod_states), set(self._assumed_pods))

    def dump(self) -> dict:
        """Debug dump (internal/cache/debugger semantics)."""
        with self._lock:
            return {
                "nodes": {n: len(ni.pods) for n, ni in self._nodes.items()},
                "assumed_pods": sorted(self._assumed_pods),
                "pod_count": self.pod_count(),
            }


class CacheFlattenView:
    """Zero-copy alternative to update_snapshot for the TPU batch path.

    The per-pod oracle path needs an immutable Snapshot because its
    Filter/Score loops read NodeInfos over a long cycle.  The batch
    flattener only needs each NodeInfo for the microseconds it takes to
    re-encode its row, so it can read the cache's live NodeInfos directly —
    under the cache lock — and skip the NodeInfo.clone per dirty node
    (~8µs/pod at bench scale, reference analog: the generation-delta copy
    in internal/cache/cache.go:197 that this view makes unnecessary)."""

    def __init__(self, cache: Cache):
        self._cache = cache

    def epoch(self) -> int:
        """The cache's external-mutation epoch (int read; GIL-atomic).
        Unchanged epoch == every change since the last read came from the
        batch backend's own assume/confirm lifecycle."""
        return self._cache.mutation_epoch

    def run_locked(self, fn):
        c = self._cache
        with c._lock:
            return fn([ni for ni in c._nodes.values() if ni.node is not None])

    def run_locked_dirty(self, fn):
        """Incremental feed: fn(dirty_pairs, removed_names) under the cache
        lock, where dirty_pairs is [(name, NodeInfo)] for every node whose
        state changed since the last drain and removed_names lists nodes
        that left the schedulable set.  The first drain falls back to a
        full scan (fn(all_pairs, []) with every node marked) so a consumer
        attaching late sees the whole cluster.  O(changed), not O(nodes) —
        at 100k nodes the full scan cost ~0.8s per sync."""
        c = self._cache
        with c._lock:
            if not c._flatten_synced:
                pairs = [(name, ni) for name, ni in c._nodes.items()
                         if ni.node is not None]
                out = fn(pairs, [])  # raises -> stay unsynced, retry full
                c._flatten_synced = True
                c._dirty_nodes.clear()
                c._removed_nodes.clear()
                return out
            dirty, c._dirty_nodes = c._dirty_nodes, set()
            removed, c._removed_nodes = c._removed_nodes, set()
            nodes = c._nodes
            pairs = []
            for name in dirty:
                ni = nodes.get(name)
                if ni is None or ni.node is None:
                    removed.add(name)  # died between dirty and drain
                else:
                    pairs.append((name, ni))
            try:
                return fn(pairs, list(removed))
            except BaseException:
                # a failed (e.g. VocabFull) sync must not lose the delta:
                # un-drain so the retry revisits every pending node
                c._dirty_nodes |= dirty
                c._removed_nodes |= removed
                raise

    def run_locked_node(self, name: str, fn):
        """Event-patch feed: fn(NodeInfo | None) for ONE node under the
        cache lock — NodeInfo when the node is live, None when it has left
        the schedulable set.  On success the node's pending dirty/removed
        delta entry is discarded (the patch consumed it); a later mutation
        re-adds it, and the wave-time run_locked_dirty drain remains the
        authoritative backstop.  Before the first full drain the delta is
        left untouched: a consumer that has never seen the whole cluster
        must still take the full scan."""
        c = self._cache
        with c._lock:
            ni = c._nodes.get(name)
            if ni is not None and ni.node is None:
                ni = None
            out = fn(ni)  # raises -> delta stays pending for the drain
            if c._flatten_synced:
                c._dirty_nodes.discard(name)
                if ni is None:
                    c._removed_nodes.discard(name)
            return out
