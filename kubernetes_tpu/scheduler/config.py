"""KubeSchedulerConfiguration — the scheduler's component config.

Reference: pkg/scheduler/apis/config/ (types.go:41 KubeSchedulerConfiguration,
types.go:100+ KubeSchedulerProfile/Plugins/PluginSet, types_pluginargs.go)
and apis/config/v1/default_plugins.go:28 (the single MultiPoint default
list + the enabled/disabled merge rules).  Shape accepted (YAML or dict):

  apiVersion: kubescheduler.config.k8s.io/v1
  kind: KubeSchedulerConfiguration
  parallelism: 16
  percentageOfNodesToScore: 0
  podInitialBackoffSeconds: 1
  podMaxBackoffSeconds: 10
  profiles:
    - schedulerName: default-scheduler
      percentageOfNodesToScore: 0
      plugins:
        multiPoint:
          enabled: [{name: Coscheduling}]
          disabled: [{name: ImageLocality}]     # or [{name: "*"}]
        score:
          disabled: [{name: NodeResourcesFit}]  # point-scoped disable
          enabled: [{name: TaintToleration, weight: 3}]
      pluginConfig:
        - name: NodeResourcesFit
          args: {strategy: MostAllocated}
  extenders:
    - urlPrefix: http://127.0.0.1:9000
      filterVerb: filter
      weight: 2
  remoteSeam:                # deadlines/retries for the TPU worker seam
    stepTimeoutSeconds: 30   # (ops/remote.py; no upstream analogue)
    maxRetries: 3
    failureThreshold: 3
    probeIntervalSeconds: 5
  tracing:                       # batch-pipeline span sampling
    samplingRatePerMillion: 10000  # (component_base/tracing.py; mirrors
    maxSpans: 4096                 #  apiserver TracingConfiguration's
    maxTraces: 256                 #  samplingRatePerMillion field)
  overload:                   # closed-loop overload protection (no upstream
    queueCap: 16384           #  analogue; see OverloadPolicy below)
    sloP99Ms: 250
    escapeRateThreshold: 0.5
    waveDeadlineSeconds: 30
  scaleOut:                   # N cooperating instances over one store
    instanceCount: 4          #  (Omega-style optimistic binding; see
    instanceIndex: 1          #  ScaleOutPolicy / scheduler/scaleout.py)
    partitionBy: nodePoolRing # or namespaceHash
    leaseDurationSeconds: 2

Merge semantics (default_plugins.go mergePlugins):
  1. start from the default MultiPoint list;
  2. multiPoint.disabled removes names ("*" clears the list);
  3. multiPoint.enabled appends (weight applies to Score);
  4. each point's .disabled masks that point only ("*" masks every default);
  5. each point's .enabled appends plugins to that point only.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .extender import build_extenders
from .framework import Framework, Handle
from .plugins import (
    DEFAULT_PLUGINS, DEFAULT_SCORE_WEIGHTS, build_default_plugins,
    in_tree_registry,
)

EXTENSION_POINTS = ("queueSort", "preFilter", "filter", "postFilter",
                    "preScore", "score", "reserve", "permit", "preBind",
                    "bind", "postBind")


class ConfigError(ValueError):
    pass


@dataclass
class ProfileConfig:
    scheduler_name: str = "default-scheduler"
    percentage_of_nodes_to_score: int = 0
    plugins: dict[str, Any] = field(default_factory=dict)
    plugin_config: dict[str, dict] = field(default_factory=dict)


@dataclass
class RemoteSeamPolicy:
    """Deadline/retry/failover policy for the remote TPU worker seam
    (ops/remote.py RemoteTPUBatchBackend, ops/failover.py ladder).

    Configured via the `remoteSeam:` stanza (see load_config); defaults
    reproduce the historical single 120s deadline but add bounded retries.
    Deadlines are per verb: /init covers kernel compilation, /step covers
    one device round trip, /health is a liveness probe and must stay
    small so an open circuit breaker probes cheaply."""

    init_timeout: float = 120.0     # includes worker-side XLA compile
    static_timeout: float = 120.0
    refresh_timeout: float = 120.0
    step_timeout: float = 120.0
    health_timeout: float = 5.0
    max_retries: int = 3            # per logical post, transient errors only
    retry_base: float = 0.05        # exponential backoff: base * 2^(n-1)
    retry_max: float = 2.0
    retry_jitter: float = 0.5       # +/- fraction of the backoff, seeded rng
    resync_attempts: int = 3        # state-lost recoveries per logical post
    failure_threshold: int = 3      # K consecutive failures open the breaker
    probe_interval: float = 5.0     # seconds between half-open health probes
    journal_cap: int = 512          # replayable steps between checkpoints

    def timeout_for(self, verb: str) -> float:
        if verb.startswith("/step"):
            return self.step_timeout
        return {"/init": self.init_timeout, "/static": self.static_timeout,
                "/refresh": self.refresh_timeout,
                "/health": self.health_timeout}.get(verb, self.step_timeout)

    def backoff(self, attempt: int, rng) -> float:
        """Delay before retry `attempt` (1-based): exponential, capped,
        jittered from the caller's seeded rng (deterministic in tests,
        decorrelated across clients in production)."""
        d = min(self.retry_max, self.retry_base * (2 ** max(0, attempt - 1)))
        if self.retry_jitter > 0.0:
            d *= 1.0 - self.retry_jitter / 2.0 + self.retry_jitter * rng.random()
        return d


# remoteSeam YAML key -> RemoteSeamPolicy field
_SEAM_FIELDS = {
    "initTimeoutSeconds": "init_timeout",
    "staticTimeoutSeconds": "static_timeout",
    "refreshTimeoutSeconds": "refresh_timeout",
    "stepTimeoutSeconds": "step_timeout",
    "healthTimeoutSeconds": "health_timeout",
    "maxRetries": "max_retries",
    "retryBaseSeconds": "retry_base",
    "retryMaxSeconds": "retry_max",
    "retryJitter": "retry_jitter",
    "resyncAttempts": "resync_attempts",
    "failureThreshold": "failure_threshold",
    "probeIntervalSeconds": "probe_interval",
    "journalCap": "journal_cap",
}


def _parse_remote_seam(data: dict) -> RemoteSeamPolicy:
    kwargs = {}
    for key, value in (data or {}).items():
        if key not in _SEAM_FIELDS:
            raise ConfigError(f"unknown remoteSeam key {key!r}")
        kwargs[_SEAM_FIELDS[key]] = value
    policy = RemoteSeamPolicy(**kwargs)
    for f in ("init_timeout", "static_timeout", "refresh_timeout",
              "step_timeout", "health_timeout", "retry_base", "retry_max",
              "probe_interval"):
        if getattr(policy, f) <= 0:
            raise ConfigError(f"remoteSeam {f} must be positive")
    if policy.max_retries < 0 or policy.resync_attempts < 0:
        raise ConfigError("remoteSeam retry counts must be >= 0")
    if policy.failure_threshold < 1:
        raise ConfigError("remoteSeam failureThreshold must be >= 1")
    if not 0.0 <= policy.retry_jitter <= 1.0:
        raise ConfigError("remoteSeam retryJitter must be in [0,1]")
    if policy.journal_cap < 1:
        raise ConfigError("remoteSeam journalCap must be >= 1")
    return policy


@dataclass
class BackendPolicy:
    """Device batch-backend selection (`backend:` stanza).

    kind picks the BatchBackend implementation the harness constructs
    (ops/backend.make_batch_backend): "tpu" is the single-chip resident
    kernel, "sharded" the mesh-partitioned shard_map path
    (parallel/backend.py — node tensors live sharded, conflict matrices
    resolve via reduce-scatter), "null" the host-only pipeline with the
    device step nulled.  batchSize/kCap 0 mean "harness default" so the
    stanza can pin just the kind.

    pipeline_depth (the nested `pipeline: {depth: N}` sub-stanza) sets
    how many waves may be in flight at once: 2 (the default) overlaps
    wave N's resolve/bind with wave N+1's device step; 1 is the strictly
    serial arm kept as the bit-parity A/B baseline.  Hot-reloadable via
    SIGHUP — lowering the depth drains excess in-flight waves on the
    next cycle rather than cancelling them."""

    kind: str = "tpu"
    batch_size: int = 0
    k_cap: int = 0
    pipeline_depth: int = 2

    @property
    def selected(self) -> bool:
        return self.kind != "tpu" or bool(self.batch_size or self.k_cap)


# backend YAML key -> BackendPolicy field
_BACKEND_FIELDS = {
    "kind": "kind",
    "batchSize": "batch_size",
    "kCap": "k_cap",
}

BACKEND_KINDS = ("tpu", "sharded", "null")


def _parse_backend(data: dict) -> BackendPolicy:
    kwargs = {}
    for key, value in (data or {}).items():
        if key == "pipeline":
            if not isinstance(value, dict):
                raise ConfigError("backend pipeline must be a mapping")
            for pk, pv in value.items():
                if pk != "depth":
                    raise ConfigError(f"unknown backend pipeline key {pk!r}")
                if pv not in (1, 2):
                    raise ConfigError(
                        f"backend pipeline depth must be 1 or 2; got {pv!r}")
                kwargs["pipeline_depth"] = pv
            continue
        if key not in _BACKEND_FIELDS:
            raise ConfigError(f"unknown backend key {key!r}")
        kwargs[_BACKEND_FIELDS[key]] = value
    policy = BackendPolicy(**kwargs)
    if policy.kind not in BACKEND_KINDS:
        raise ConfigError(
            f"backend kind must be one of {', '.join(BACKEND_KINDS)}; "
            f"got {policy.kind!r}")
    if policy.batch_size < 0 or policy.k_cap < 0:
        raise ConfigError("backend batchSize/kCap must be >= 0")
    return policy


@dataclass
class TracingPolicy:
    """Batch-pipeline trace sampling (component_base/tracing.py).

    Configured via the `tracing:` stanza; the field name mirrors the
    upstream apiserver TracingConfiguration (samplingRatePerMillion).
    Rate 0 (the default) disables tracing entirely — the scheduler never
    attaches a tracer, so the hot path pays nothing."""

    sampling_rate_per_million: int = 0
    max_spans: int = 4096       # flight-recorder span ring bound
    max_traces: int = 256       # /debug/traces trace ring bound

    @property
    def enabled(self) -> bool:
        return self.sampling_rate_per_million > 0


# tracing YAML key -> TracingPolicy field
_TRACING_FIELDS = {
    "samplingRatePerMillion": "sampling_rate_per_million",
    "maxSpans": "max_spans",
    "maxTraces": "max_traces",
}


def _parse_tracing(data: dict) -> TracingPolicy:
    kwargs = {}
    for key, value in (data or {}).items():
        if key not in _TRACING_FIELDS:
            raise ConfigError(f"unknown tracing key {key!r}")
        kwargs[_TRACING_FIELDS[key]] = value
    policy = TracingPolicy(**kwargs)
    if not 0 <= policy.sampling_rate_per_million <= 1_000_000:
        raise ConfigError(
            "tracing samplingRatePerMillion must be in [0, 1000000]")
    if policy.max_spans < 1 or policy.max_traces < 1:
        raise ConfigError("tracing ring bounds must be >= 1")
    return policy


@dataclass
class ProfilingPolicy:
    """Continuous performance observatory (component_base/profiling.py).

    Configured via the `profiling:` stanza; everything defaults OFF so
    an unconfigured scheduler attaches no sampler thread, runs no
    census compile, and pays nothing on the hot path.

      enabled          master switch: starts the process-wide sampling
                       host profiler (sys._current_frames() at
                       1000/sampleIntervalMs Hz) behind /debug/profile
                       and feeds scheduler_host_stage_seconds{stage}.
      census           device cost census: at warmup the backend lowers
                       its compiled step variants and exports
                       tpu_wave_collective_bytes / tpu_wave_flops /
                       tpu_step_hbm_bytes gauges (costs one extra AOT
                       compile per variant, off the hot path).
      sloTargetMs      rolling-window scheduling-latency SLO target fed
                       by submit->bind latencies; p50/p95/p99 and
                       multi-window burn rates export as
                       scheduler_slo_latency_ms / scheduler_slo_burn_rate
                       (the arm/disarm signal for adaptive overload
                       engagement).
      timeline         wave timeline (component_base/timeline.py): every
                       pipeline stage records bounded (wave, stage,
                       start, end, thread) intervals, deriving
                       scheduler_wave_device_idle_share (interval
                       union), per-stage overlap ratios, the per-pod
                       scheduler_pod_latency_ms{segment} decomposition
                       and /debug/timeline (JSON + Chrome trace).
      timelineRing     bounded interval-ring capacity per process."""

    enabled: bool = False
    census: bool = False
    sample_interval_ms: float = 5.0
    max_stacks: int = 512
    slo_target_ms: float = 10.0
    slo_objective: float = 0.99
    burn_windows_s: tuple = (60.0, 300.0, 3600.0)
    timeline: bool = False
    timeline_ring: int = 4096


# profiling YAML key -> ProfilingPolicy field
_PROFILING_FIELDS = {
    "enabled": "enabled",
    "census": "census",
    "sampleIntervalMs": "sample_interval_ms",
    "maxStacks": "max_stacks",
    "sloTargetMs": "slo_target_ms",
    "sloObjective": "slo_objective",
    "burnWindowsSeconds": "burn_windows_s",
    "timeline": "timeline",
    "timelineRing": "timeline_ring",
}


def _parse_profiling(data: dict) -> ProfilingPolicy:
    kwargs = {}
    for key, value in (data or {}).items():
        if key not in _PROFILING_FIELDS:
            raise ConfigError(f"unknown profiling key {key!r}")
        kwargs[_PROFILING_FIELDS[key]] = value
    if "burn_windows_s" in kwargs:
        kwargs["burn_windows_s"] = tuple(
            float(w) for w in kwargs["burn_windows_s"])
    policy = ProfilingPolicy(**kwargs)
    if policy.sample_interval_ms <= 0:
        raise ConfigError("profiling sampleIntervalMs must be positive")
    if policy.max_stacks < 1:
        raise ConfigError("profiling maxStacks must be >= 1")
    if policy.slo_target_ms <= 0:
        raise ConfigError("profiling sloTargetMs must be positive")
    if not 0.0 < policy.slo_objective < 1.0:
        raise ConfigError("profiling sloObjective must be in (0,1)")
    if not policy.burn_windows_s or any(w <= 0
                                        for w in policy.burn_windows_s):
        raise ConfigError("profiling burnWindowsSeconds must be positive")
    if policy.timeline_ring < 1:
        raise ConfigError("profiling timelineRing must be >= 1")
    return policy


@dataclass
class OverloadPolicy:
    """Closed-loop overload protection for the batch pipeline.

    Configured via the `overload:` stanza and ON BY DEFAULT since the
    signal-driven engagement controller landed: an unconfigured
    scheduler carries protective defaults for every layer, but the
    layers only ACT while the engagement state machine (`engagement:
    auto`, scheduler._EngagementController) is engaged — armed by the
    SLO burn-rate breach signal with queue-depth growth as the
    secondary trigger, released with dwell-time hysteresis.  A healthy
    box therefore pays a few branch checks per wave, not the ~3x
    throughput cost the always-on policy used to charge.
    `engagement: always` restores the legacy behavior (every layer
    active whenever its knob is non-zero); `engagement: off` disables
    the stanza entirely.  Four independent layers (in the spirit of
    Borg's overload-tolerant admission and the stability patterns in
    ops/failover.py):

      queue_cap        bounded admission — activeQ depth cap; excess pods
                       are shed lowest-priority-first (youngest first
                       within a priority) into the backoff tier, never
                       dropped.  Pods at/above shed_protect_priority and
                       pods older than shed_protect_age are never shed,
                       so the cap is soft with respect to protected pods
                       and every pod is eventually admitted.
      slo_p99_ms       adaptive wave sizing — AIMD control of the dispatch
                       batch size against this per-wave latency SLO:
                       multiplicative decrease on breach, additive
                       increase while under it and backlogged.
      escape_rate_threshold
                       escape-storm breaker — when a batch's SKIP (escape)
                       rate exceeds this fraction for breaker_threshold
                       consecutive batches, escapes are deferred into the
                       backoff tiers instead of flooding the per-pod
                       oracle; a probe batch every breaker_probe_interval
                       re-closes the breaker once escapes subside.
      wave_deadline    stuck-wave watchdog — a wave whose results have not
                       landed this many seconds after dispatch is
                       cancelled: the backend abandons the wave and the
                       pods requeue through the BackendUnavailableError
                       path."""

    queue_cap: int = 16384              # 0 = unbounded (admission off)
    shed_protect_priority: int = 1000   # >= this priority: never shed
    shed_protect_age: float = 30.0      # queued longer than this: never shed
    slo_p99_ms: float = 250.0           # 0 = adaptive wave sizing off
    wave_min: int = 16                  # AIMD floor for the wave size
    wave_increase: int = 32             # additive increase per good wave
    wave_decrease: float = 0.5          # multiplicative decrease on breach
    escape_rate_threshold: float = 0.5  # 0 = escape-storm breaker off
    escape_min_batch: int = 64          # smaller batches never count as storms
    breaker_threshold: int = 3          # consecutive storm batches to open
    breaker_probe_interval: float = 5.0  # seconds between probe batches
    wave_deadline: float = 120.0        # 0 = stuck-wave watchdog off
    # -- engagement state machine (scheduler._EngagementController) -------
    engagement: str = "auto"            # auto | always | off
    arm_samples: int = 2                # consecutive pressure waves to engage
    engage_dwell: float = 5.0           # min calm seconds before cooling
    cool_dwell: float = 10.0            # cooling seconds before disengaging
    queue_growth_factor: float = 2.0    # depth > factor*wave AND growing

    @property
    def enabled(self) -> bool:
        return (self.engagement != "off"
                and (self.queue_cap > 0 or self.slo_p99_ms > 0
                     or self.escape_rate_threshold > 0
                     or self.wave_deadline > 0))


# overload YAML key -> OverloadPolicy field
_OVERLOAD_FIELDS = {
    "queueCap": "queue_cap",
    "shedProtectPriority": "shed_protect_priority",
    "shedProtectAgeSeconds": "shed_protect_age",
    "sloP99Ms": "slo_p99_ms",
    "waveMin": "wave_min",
    "waveIncrease": "wave_increase",
    "waveDecrease": "wave_decrease",
    "escapeRateThreshold": "escape_rate_threshold",
    "escapeMinBatch": "escape_min_batch",
    "breakerThreshold": "breaker_threshold",
    "breakerProbeIntervalSeconds": "breaker_probe_interval",
    "waveDeadlineSeconds": "wave_deadline",
    "engagement": "engagement",
    "armSamples": "arm_samples",
    "engageDwellSeconds": "engage_dwell",
    "coolDwellSeconds": "cool_dwell",
    "queueGrowthFactor": "queue_growth_factor",
}


def _parse_overload(data: dict) -> OverloadPolicy:
    kwargs = {}
    for key, value in (data or {}).items():
        if key not in _OVERLOAD_FIELDS:
            raise ConfigError(f"unknown overload key {key!r}")
        kwargs[_OVERLOAD_FIELDS[key]] = value
    policy = OverloadPolicy(**kwargs)
    for f in ("queue_cap", "slo_p99_ms", "wave_deadline"):
        if getattr(policy, f) < 0:
            raise ConfigError(f"overload {f} must be >= 0 (0 disables)")
    if policy.shed_protect_age <= 0:
        raise ConfigError("overload shedProtectAgeSeconds must be positive")
    if policy.wave_min < 1 or policy.wave_increase < 1:
        raise ConfigError("overload waveMin/waveIncrease must be >= 1")
    if not 0.0 < policy.wave_decrease < 1.0:
        raise ConfigError("overload waveDecrease must be in (0,1)")
    if not 0.0 <= policy.escape_rate_threshold <= 1.0:
        raise ConfigError("overload escapeRateThreshold must be in [0,1]")
    if policy.escape_min_batch < 1:
        raise ConfigError("overload escapeMinBatch must be >= 1")
    if policy.breaker_threshold < 1:
        raise ConfigError("overload breakerThreshold must be >= 1")
    if policy.breaker_probe_interval <= 0:
        raise ConfigError("overload breakerProbeIntervalSeconds must be positive")
    if policy.engagement not in ("auto", "always", "off"):
        raise ConfigError(
            "overload engagement must be auto, always or off")
    if policy.arm_samples < 1:
        raise ConfigError("overload armSamples must be >= 1")
    if policy.engage_dwell < 0 or policy.cool_dwell < 0:
        raise ConfigError(
            "overload engageDwellSeconds/coolDwellSeconds must be >= 0")
    if policy.queue_growth_factor <= 0:
        raise ConfigError("overload queueGrowthFactor must be positive")
    return policy


@dataclass
class ScaleOutPolicy:
    """Horizontal scale-out: this process is instance `instance_index` of
    `instance_count` cooperating schedulers sharing one store.

    Configured via the `scaleOut:` stanza; instance_count=1 (the default)
    disables the whole layer.  The cluster is partitioned with a
    node-pool ring (scheduler/scaleout.py): node and pod keys hash onto
    `ring_slices` virtual slices, and live instances own slices
    round-robin — when an instance's lease lapses, survivors recompute
    the same map and absorb its slices with no coordination.
    partition_by="namespaceHash" is the fallback for clusters whose node
    names hash unevenly: pods partition by namespace and every instance
    sees all nodes.  Binding stays optimistic either way: ownership only
    reduces contention, the compare-and-bind precondition (kv.bind_many)
    is what prevents double-binds during churn windows."""

    instance_count: int = 1             # 1 = scale-out layer off
    instance_index: int = 0             # this process's identity
    partition_by: str = "nodePoolRing"  # or "namespaceHash"
    ring_slices: int = 64               # virtual slices on the ring
    lease_duration: float = 2.0         # unrenewed this long = dead
    renew_interval: float = 0.5         # lease heartbeat period

    @property
    def enabled(self) -> bool:
        return self.instance_count > 1


# scaleOut YAML key -> ScaleOutPolicy field
_SCALEOUT_FIELDS = {
    "instanceCount": "instance_count",
    "instanceIndex": "instance_index",
    "partitionBy": "partition_by",
    "ringSlices": "ring_slices",
    "leaseDurationSeconds": "lease_duration",
    "renewIntervalSeconds": "renew_interval",
}


def _parse_scaleout(data: dict) -> ScaleOutPolicy:
    kwargs = {}
    for key, value in (data or {}).items():
        if key not in _SCALEOUT_FIELDS:
            raise ConfigError(f"unknown scaleOut key {key!r}")
        kwargs[_SCALEOUT_FIELDS[key]] = value
    policy = ScaleOutPolicy(**kwargs)
    if policy.instance_count < 1:
        raise ConfigError("scaleOut instanceCount must be >= 1")
    if not 0 <= policy.instance_index < policy.instance_count:
        raise ConfigError(
            "scaleOut instanceIndex must be in [0, instanceCount)")
    if policy.partition_by not in ("nodePoolRing", "namespaceHash"):
        raise ConfigError(
            "scaleOut partitionBy must be nodePoolRing or namespaceHash")
    if policy.ring_slices < policy.instance_count:
        raise ConfigError("scaleOut ringSlices must be >= instanceCount")
    if policy.lease_duration <= 0:
        raise ConfigError("scaleOut leaseDurationSeconds must be positive")
    if not 0 < policy.renew_interval < policy.lease_duration:
        raise ConfigError("scaleOut renewIntervalSeconds must be in "
                          "(0, leaseDurationSeconds)")
    return policy


@dataclass
class SchedulerConfig:
    parallelism: int = 16
    percentage_of_nodes_to_score: int = 0
    pod_initial_backoff: float = 1.0
    pod_max_backoff: float = 10.0
    profiles: list[ProfileConfig] = field(default_factory=list)
    extenders: list[dict] = field(default_factory=list)
    remote_seam: RemoteSeamPolicy = field(default_factory=RemoteSeamPolicy)
    backend: BackendPolicy = field(default_factory=BackendPolicy)
    tracing: TracingPolicy = field(default_factory=TracingPolicy)
    overload: OverloadPolicy = field(default_factory=OverloadPolicy)
    scale_out: ScaleOutPolicy = field(default_factory=ScaleOutPolicy)
    profiling: ProfilingPolicy = field(default_factory=ProfilingPolicy)


def load_config(source: str | dict) -> SchedulerConfig:
    """Parse + validate a KubeSchedulerConfiguration (path, YAML text or
    dict).  Mirrors apis/config/validation/."""
    if isinstance(source, str):
        import yaml
        try:
            with open(source) as f:
                data = yaml.safe_load(f)
        except OSError:
            data = yaml.safe_load(source)
    else:
        data = source
    data = data or {}
    kind = data.get("kind", "KubeSchedulerConfiguration")
    if kind != "KubeSchedulerConfiguration":
        raise ConfigError(f"unexpected kind {kind!r}")

    cfg = SchedulerConfig(
        parallelism=data.get("parallelism", 16),
        percentage_of_nodes_to_score=data.get("percentageOfNodesToScore", 0),
        pod_initial_backoff=data.get("podInitialBackoffSeconds", 1.0),
        pod_max_backoff=data.get("podMaxBackoffSeconds", 10.0),
        extenders=data.get("extenders") or [],
        remote_seam=_parse_remote_seam(data.get("remoteSeam")),
        backend=_parse_backend(data.get("backend")),
        tracing=_parse_tracing(data.get("tracing")),
        overload=_parse_overload(data.get("overload")),
        scale_out=_parse_scaleout(data.get("scaleOut")),
        profiling=_parse_profiling(data.get("profiling")),
    )
    if cfg.parallelism <= 0:
        raise ConfigError("parallelism must be positive")
    if not 0 <= cfg.percentage_of_nodes_to_score <= 100:
        raise ConfigError("percentageOfNodesToScore must be in [0,100]")

    known = set(in_tree_registry())
    seen_names: set[str] = set()
    for p in data.get("profiles") or [{}]:
        name = p.get("schedulerName", "default-scheduler")
        if name in seen_names:
            raise ConfigError(f"duplicate profile {name!r}")
        seen_names.add(name)
        plugins = p.get("plugins") or {}
        for point, pset in plugins.items():
            if point not in EXTENSION_POINTS + ("multiPoint",):
                raise ConfigError(f"unknown extension point {point!r}")
            for entry in list((pset or {}).get("enabled") or ()):
                if entry["name"] not in known:
                    raise ConfigError(
                        f"unknown plugin {entry['name']!r} in {point}.enabled")
        plugin_config = {pc["name"]: pc.get("args") or {}
                         for pc in p.get("pluginConfig") or ()}
        cfg.profiles.append(ProfileConfig(
            scheduler_name=name,
            percentage_of_nodes_to_score=p.get(
                "percentageOfNodesToScore",
                cfg.percentage_of_nodes_to_score),
            plugins=plugins, plugin_config=plugin_config))
    return cfg


def _merge_plugin_sets(plugins_cfg: dict
                       ) -> tuple[list[str], dict[str, int],
                                  dict[str, set[str]], dict[str, list[str]]]:
    """Apply the default_plugins.go merge. Returns:
    (base plugin names, score weights, per-plugin disabled points,
     per-point extra plugin names)."""
    weights = dict(DEFAULT_SCORE_WEIGHTS)
    base = list(DEFAULT_PLUGINS)

    mp = plugins_cfg.get("multiPoint") or {}
    disabled = [d["name"] for d in mp.get("disabled") or ()]
    if "*" in disabled:
        base = []
    else:
        base = [n for n in base if n not in disabled]
    for e in mp.get("enabled") or ():
        if e["name"] not in base:
            base.append(e["name"])
        if "weight" in e:
            weights[e["name"]] = e["weight"]

    disabled_points: dict[str, set[str]] = {}
    extra_points: dict[str, list[str]] = {}
    for point in EXTENSION_POINTS:
        pset = plugins_cfg.get(point) or {}
        for d in pset.get("disabled") or ():
            if d["name"] == "*":
                for n in base:
                    disabled_points.setdefault(n, set()).add(point)
            else:
                disabled_points.setdefault(d["name"], set()).add(point)
        for e in pset.get("enabled") or ():
            extra_points.setdefault(point, []).append(e["name"])
            if point == "score" and "weight" in e:
                weights[e["name"]] = e["weight"]
            # point-scoped enable overrides a point-scoped "*" disable
            disabled_points.get(e["name"], set()).discard(point)
    return base, weights, disabled_points, extra_points


def build_framework_from_profile(client, informer_factory,
                                 profile_cfg: ProfileConfig,
                                 out_of_tree_registry=None) -> Framework:
    """profile.NewMap body for one profile (profile/profile.go:48), with
    WithFrameworkOutOfTreeRegistry merge (scheduler.go:180)."""
    registry = in_tree_registry()
    if out_of_tree_registry:
        overlap = set(registry) & set(out_of_tree_registry)
        if overlap:
            raise ConfigError(
                f"out-of-tree plugins shadow in-tree: {sorted(overlap)}")
        registry.update(out_of_tree_registry)

    base, weights, disabled_points, extra_points = _merge_plugin_sets(
        profile_cfg.plugins)
    extra_names = [n for names in extra_points.values() for n in names]
    all_names = base + [n for n in extra_names if n not in base]
    for n in all_names:
        if n not in registry:
            raise ConfigError(f"unknown plugin {n!r}")

    handle = Handle(client=client, informer_factory=informer_factory)
    plugins = [registry[n](profile_cfg.plugin_config.get(n), handle)
               for n in all_names]

    extra_only = {n for n in extra_names if n not in base}

    def point_filter(name: str, point: str) -> bool:
        if point in disabled_points.get(name, ()):
            return False
        if name in extra_only:
            # enabled only at the points that named it
            return name in extra_points.get(point, ())
        return True

    return Framework(profile_cfg.scheduler_name, plugins,
                     score_weights=weights, handle=handle,
                     point_filter=point_filter)


def scheduler_from_config(client, informer_factory, cfg: SchedulerConfig,
                          out_of_tree_registry=None):
    """Setup (cmd/kube-scheduler/app/server.go:307): config -> Scheduler."""
    from .queue import SchedulingQueue  # noqa: F401  (backoff knobs below)
    from .scheduler import Profile, Scheduler

    profiles = {}
    for pc in cfg.profiles or [ProfileConfig()]:
        fw = build_framework_from_profile(client, informer_factory, pc,
                                          out_of_tree_registry)
        profiles[pc.scheduler_name] = Profile(
            fw, percentage_of_nodes_to_score=pc.percentage_of_nodes_to_score)
    sched = Scheduler(client, informer_factory, profiles,
                      extenders=build_extenders(cfg.extenders))
    sched.queue._initial_backoff = cfg.pod_initial_backoff
    sched.queue._max_backoff = cfg.pod_max_backoff
    # backends are constructed by the harness (bench/perf/tests), not
    # here: hang the seam policy off the scheduler so whoever wires a
    # RemoteTPUBatchBackend into a profile picks up the configured
    # deadlines/retry budget instead of the hard-coded defaults
    sched.remote_seam_policy = cfg.remote_seam
    # same contract for the device backend: the stanza records WHICH
    # backend the harness should build (ops/backend.make_batch_backend),
    # construction stays with bench/perf/tests
    sched.backend_policy = cfg.backend
    sched.pipeline_depth = max(1, cfg.backend.pipeline_depth)
    if cfg.overload.enabled:
        sched.configure_overload(cfg.overload)
    if cfg.scale_out.enabled:
        sched.configure_scaleout(cfg.scale_out)
        # deterministic per-instance relist offset: when every instance
        # restarts its watch at once (store compaction, apiserver blip),
        # the LISTs arrive index-staggered instead of as one herd
        if hasattr(informer_factory, "set_relist_stagger"):
            informer_factory.set_relist_stagger(
                0.1 * cfg.scale_out.instance_index)
    if cfg.tracing.enabled:
        # the process-wide provider backs /debug/traces on the apiserver's
        # HTTP mux; tests that want isolation construct their own provider
        # and call configure_tracing directly
        from ..component_base import tracing
        tracing.default_tracer_provider.configure(
            sampling_rate_per_million=cfg.tracing.sampling_rate_per_million,
            max_spans=cfg.tracing.max_spans,
            max_traces=cfg.tracing.max_traces)
        sched.configure_tracing(tracing.default_tracer_provider)
    if (cfg.profiling.enabled or cfg.profiling.census
            or cfg.profiling.timeline):
        # the process-wide profiler backs /debug/profile on the apiserver
        # and device-worker muxes (tracing's default-provider pattern);
        # tests wanting isolation construct their own HostProfiler and
        # call configure_profiling directly.  Default-off: this branch is
        # the ONLY place the sampler starts, the census arms, or the
        # wave timeline's default ring is enabled.
        from ..component_base import profiling
        from ..component_base import timeline as cb_timeline
        profiler = None
        if cfg.profiling.enabled:
            profiler = profiling.default_host_profiler
            profiler.interval = cfg.profiling.sample_interval_ms / 1000.0
            profiler.max_stacks = cfg.profiling.max_stacks
            profiler.start()
        timeline = None
        if cfg.profiling.timeline:
            timeline = cb_timeline.default_timeline
            timeline.configure(enabled=True,
                               ring=cfg.profiling.timeline_ring)
        slo = profiling.SLOTracker(
            target_ms=cfg.profiling.slo_target_ms,
            objective=cfg.profiling.slo_objective,
            windows=cfg.profiling.burn_windows_s)
        sched.configure_profiling(profiler, slo, census=cfg.profiling.census,
                                  timeline=timeline)
    return sched
