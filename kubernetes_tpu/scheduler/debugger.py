"""Scheduler cache debugger: dump + cache-vs-apiserver comparer.

Reference: pkg/scheduler/internal/cache/debugger/ — on SIGUSR2 the
scheduler logs a dump of the cache and queue (dumper.go) and compares the
cached nodes/pods against the apiserver's view (comparer.go), reporting
discrepancies that would otherwise poison snapshots silently.
"""

from __future__ import annotations

import logging
import signal

from ..api import meta
from ..client.clientset import NODES, PODS, Client

logger = logging.getLogger(__name__)


class CacheDebugger:
    def __init__(self, scheduler, client: Client | None = None):
        self.scheduler = scheduler
        self.client = client or scheduler.client

    # -- dumper.go --------------------------------------------------------

    def dump(self) -> dict:
        """Cache + queue snapshot (dumper.go DumpAll shape)."""
        return {
            "cache": self.scheduler.cache.dump(),
            "queue": self.scheduler.queue.stats(),
        }

    # -- comparer.go ------------------------------------------------------

    def compare(self) -> dict:
        """Diff the scheduler cache against the apiserver.

        Returns {"nodes": {"missing": [...], "extra": [...]},
                 "pods": {"missing": [...], "extra": [...]}} — missing =
        in apiserver but not cached; extra = cached but gone upstream
        (assumed-but-unconfirmed pods are expected extras and excluded)."""
        api_nodes = {meta.name(n) for n in self.client.list(NODES)[0]}
        api_pods = {meta.namespaced_name(p)
                    for p in self.client.list(PODS)[0]
                    if meta.pod_node_name(p)}
        cached_nodes, cached_pods, assumed = \
            self.scheduler.cache.comparison_snapshot()
        return {
            "nodes": {"missing": sorted(api_nodes - cached_nodes),
                      "extra": sorted(cached_nodes - api_nodes)},
            "pods": {"missing": sorted(api_pods - cached_pods),
                     "extra": sorted(cached_pods - api_pods - assumed)},
        }

    def log_all(self, *_signal_args) -> None:
        """SIGUSR2 handler body (debugger.go ListenForSignal)."""
        logger.info("scheduler cache dump: %s", self.dump())
        diff = self.compare()
        clean = not any(v for side in diff.values() for v in side.values())
        if clean:
            logger.info("cache comparer: cache is in sync with apiserver")
        else:
            logger.warning("cache comparer: DISCREPANCIES %s", diff)

    def listen_for_signal(self) -> None:
        """Install the SIGUSR2 handler (main thread only)."""
        signal.signal(signal.SIGUSR2, self.log_all)
