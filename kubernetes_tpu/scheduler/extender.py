"""Scheduler extender — out-of-process extension over HTTP+JSON.

Reference: pkg/scheduler/extender.go (HTTPExtender) and the Extender
interface at pkg/scheduler/framework/extender.go:27-67; wire types from
staging/src/k8s.io/kube-scheduler/extender/v1.  Semantics reproduced:
  * Filter POSTs ExtenderArgs {pod, nodenames} and gets back the surviving
    node names plus failed / failed-and-unresolvable maps (extender.go
    Filter; nodeCacheCapable decides names-vs-full-objects on the wire).
  * Prioritize returns a host->score list that the scheduler multiplies by
    the extender's weight and adds to the plugin score sum
    (schedule_one.go:733 prioritizeNodes extender fan-out).
  * Bind delegates the binding POST to the extender when configured
    (extender.go Bind; used instead of the framework's Bind plugins).
  * is_interested gates all of it on the pod requesting at least one
    managed resource (extender.go IsInterested).
  * ignorable extenders are skipped on error instead of failing the cycle
    (extender.go IsIgnorable, schedule_one.go:613 findNodesThatPassExtenders).

This HTTP+JSON webhook is the reference's own precedent for shipping
scheduling work out of process — the TPU batch backend (ops/backend.py) is
the same seam with tensors instead of JSON.
"""

from __future__ import annotations

import json
import logging
import urllib.request

from ..api import meta
from ..api.meta import Obj
from .types import NodeInfo, PodInfo

logger = logging.getLogger(__name__)

DEFAULT_EXTENDER_TIMEOUT = 5.0


class ExtenderError(Exception):
    pass


class Extender:
    """framework/extender.go:27 Extender interface."""

    def name(self) -> str:
        raise NotImplementedError

    def is_ignorable(self) -> bool:
        return False

    def is_binder(self) -> bool:
        return False

    def is_interested(self, pod: Obj) -> bool:
        raise NotImplementedError

    def filter(self, pod: Obj, nodes: list[NodeInfo]
               ) -> tuple[list[NodeInfo], dict[str, str], dict[str, str]]:
        """Returns (feasible, failed, failed_and_unresolvable)."""
        raise NotImplementedError

    def prioritize(self, pod: Obj, nodes: list[NodeInfo]
                   ) -> tuple[dict[str, int], int]:
        """Returns (host->score, weight)."""
        raise NotImplementedError

    def bind(self, pod: Obj, node_name: str) -> None:
        raise NotImplementedError


class HTTPExtender(Extender):
    """pkg/scheduler/extender.go HTTPExtender."""

    def __init__(self, url_prefix: str, filter_verb: str = "",
                 prioritize_verb: str = "", bind_verb: str = "",
                 weight: int = 1, node_cache_capable: bool = False,
                 managed_resources: list[str] | None = None,
                 ignorable: bool = False,
                 timeout: float = DEFAULT_EXTENDER_TIMEOUT):
        self.url_prefix = url_prefix.rstrip("/")
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.bind_verb = bind_verb
        self.weight = weight
        self.node_cache_capable = node_cache_capable
        self.managed_resources = set(managed_resources or ())
        self.ignorable = ignorable
        self.timeout = timeout

    def name(self) -> str:
        return self.url_prefix

    def is_ignorable(self) -> bool:
        return self.ignorable

    def is_binder(self) -> bool:
        return bool(self.bind_verb)

    def is_interested(self, pod: Obj) -> bool:
        """extender.go IsInterested: no managed resources -> always."""
        if not self.managed_resources:
            return True
        spec = pod.get("spec") or {}
        for c in list(spec.get("containers") or ()) + list(
                spec.get("initContainers") or ()):
            res = c.get("resources") or {}
            for section in ("requests", "limits"):
                for rname in (res.get(section) or {}):
                    if rname in self.managed_resources:
                        return True
        return False

    def _post(self, verb: str, payload: dict) -> dict:
        req = urllib.request.Request(
            f"{self.url_prefix}/{verb}",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            from ..apiserver.egress import CLUSTER, default_selector
            with default_selector.open(CLUSTER, req, self.timeout) as resp:
                return json.loads(resp.read().decode())
        except Exception as e:
            raise ExtenderError(f"extender {self.url_prefix}/{verb}: {e}") from e

    def filter(self, pod, nodes):
        if not self.filter_verb:
            return nodes, {}, {}
        args: dict = {"pod": pod}
        if self.node_cache_capable:
            args["nodenames"] = [n.name for n in nodes]
        else:
            args["nodes"] = {"items": [n.node for n in nodes]}
        result = self._post(self.filter_verb, args)
        if result.get("error"):
            raise ExtenderError(result["error"])
        failed = result.get("failedNodes") or {}
        failed_unresolvable = result.get("failedAndUnresolvableNodes") or {}
        if self.node_cache_capable and result.get("nodenames") is not None:
            keep = set(result["nodenames"])
        elif result.get("nodes") is not None:
            keep = {meta.name(n) for n in result["nodes"].get("items") or ()}
        else:
            keep = {n.name for n in nodes} - set(failed) - set(failed_unresolvable)
        return ([n for n in nodes if n.name in keep], dict(failed),
                dict(failed_unresolvable))

    def prioritize(self, pod, nodes):
        if not self.prioritize_verb:
            return {}, 0
        args: dict = {"pod": pod}
        if self.node_cache_capable:
            args["nodenames"] = [n.name for n in nodes]
        else:
            args["nodes"] = {"items": [n.node for n in nodes]}
        result = self._post(self.prioritize_verb, args)
        scores = {e["host"]: int(e["score"])
                  for e in result or () if "host" in e}
        return scores, self.weight

    def bind(self, pod, node_name):
        if not self.bind_verb:
            raise ExtenderError("extender has no bind verb")
        result = self._post(self.bind_verb, {
            "podName": meta.name(pod), "podNamespace": meta.namespace(pod),
            "podUID": meta.uid(pod), "node": node_name})
        if result and result.get("error"):
            raise ExtenderError(result["error"])


def build_extenders(configs: list[dict]) -> list[Extender]:
    """KubeSchedulerConfiguration .extenders -> HTTPExtender list
    (apis/config/types.go Extender struct field names)."""
    out: list[Extender] = []
    for cfg in configs or ():
        out.append(HTTPExtender(
            url_prefix=cfg["urlPrefix"],
            filter_verb=cfg.get("filterVerb", ""),
            prioritize_verb=cfg.get("prioritizeVerb", ""),
            bind_verb=cfg.get("bindVerb", ""),
            weight=cfg.get("weight", 1),
            node_cache_capable=cfg.get("nodeCacheCapable", False),
            managed_resources=[m["name"] for m in
                               cfg.get("managedResources") or ()],
            ignorable=cfg.get("ignorable", False),
            timeout=cfg.get("httpTimeout", DEFAULT_EXTENDER_TIMEOUT)))
    return out
