"""Scheduling framework: plugin contracts + runtime.

Reference: pkg/scheduler/framework/interface.go (the 11 extension points:
QueueSort, PreFilter(+AddPod/RemovePod), Filter, PostFilter, PreScore,
Score(+NormalizeScore), Reserve/Unreserve, Permit, PreBind, Bind, PostBind),
framework/runtime/framework.go (execution + per-point ordering),
framework/cycle_state.go, framework/runtime/waiting_pods_map.go (Permit),
framework/runtime/registry.go.

TPU-native addition: BatchExtensions — a plugin may implement
batch_filter_scores(ctx) producing (mask[P,N], scores[P,N]) for a whole batch
of pods at once; the batch scheduler (scheduler.py) uses it in place of
per-pod Filter/Score when every enabled plugin supports it.  Per-pod
semantics remain the fallback and the oracle.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, Sequence

from ..api import meta
from ..api.meta import Obj
from .cache import Snapshot
from .types import (
    ERROR, SKIP, SUCCESS, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, WAIT,
    _CODE_NAMES, ClusterEvent, Diagnosis, NodeInfo, PodInfo, Status, is_success,
)

MAX_NODE_SCORE = 100  # framework/interface.go MaxNodeScore
MIN_NODE_SCORE = 0

def _status_label(out: Any) -> str:
    """Map a runner's return value to a status label for metrics."""
    status = out
    if isinstance(out, tuple):
        status = next((x for x in reversed(out) if isinstance(x, Status)), None)
    if status is None:
        return "Success"
    if isinstance(status, Status):
        return _CODE_NAMES.get(status.code, str(status.code))
    return "Success"


class CycleState:
    """Per-scheduling-cycle typed KV store (framework/cycle_state.go)."""

    def __init__(self) -> None:
        self._data: dict[str, Any] = {}
        self.skip_filter_plugins: set[str] = set()
        self.skip_score_plugins: set[str] = set()

    def read(self, key: str) -> Any:
        return self._data.get(key)

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        c._data = dict(self._data)
        c.skip_filter_plugins = set(self.skip_filter_plugins)
        c.skip_score_plugins = set(self.skip_score_plugins)
        return c


class PreFilterResult:
    """interface.go:633 — a PreFilter may pin the feasible set of nodes."""

    __slots__ = ("node_names",)

    def __init__(self, node_names: set[str] | None):
        self.node_names = node_names  # None = all nodes

    def all_nodes(self) -> bool:
        return self.node_names is None

    def merge(self, other: "PreFilterResult | None") -> "PreFilterResult":
        if other is None or other.all_nodes():
            return self
        if self.all_nodes():
            return other
        return PreFilterResult(self.node_names & other.node_names)


class Plugin:
    """Base plugin. `name` must be unique within a profile."""

    name: str = "Plugin"

    def events_to_register(self) -> list[ClusterEvent]:
        """EnqueueExtensions (interface.go:327): cluster events that may make
        a pod rejected by this plugin schedulable again."""
        return [ClusterEvent("*", "*")]


class QueueSortPlugin(Plugin):
    def sort_key(self, qpi) -> tuple:
        raise NotImplementedError


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod_info: PodInfo,
                   snapshot: Snapshot) -> tuple[PreFilterResult | None, Status | None]:
        raise NotImplementedError

    # AddPod/RemovePod extensions (used by preemption dry-runs)
    def add_pod(self, state: CycleState, pod_info: PodInfo,
                to_add: PodInfo, node_info: NodeInfo) -> Status | None:
        return None

    def remove_pod(self, state: CycleState, pod_info: PodInfo,
                   to_remove: PodInfo, node_info: NodeInfo) -> Status | None:
        return None


class FilterPlugin(Plugin):
    def filter(self, state: CycleState, pod_info: PodInfo,
               node_info: NodeInfo) -> Status | None:
        raise NotImplementedError


class PostFilterPlugin(Plugin):
    def post_filter(self, state: CycleState, pod_info: PodInfo,
                    filtered_node_status_map: dict[str, Status]
                    ) -> tuple[str | None, Status]:
        """Returns (nominated_node_name, status)."""
        raise NotImplementedError


class PreScorePlugin(Plugin):
    def pre_score(self, state: CycleState, pod_info: PodInfo,
                  nodes: list[NodeInfo]) -> Status | None:
        raise NotImplementedError


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod_info: PodInfo,
              node_info: NodeInfo) -> tuple[int, Status | None]:
        raise NotImplementedError

    def normalize_scores(self, state: CycleState, pod_info: PodInfo,
                         scores: dict[str, int]) -> Status | None:
        return None


class ReservePlugin(Plugin):
    def reserve(self, state: CycleState, pod_info: PodInfo,
                node_name: str) -> Status | None:
        return None

    def unreserve(self, state: CycleState, pod_info: PodInfo,
                  node_name: str) -> None:
        pass


class PermitPlugin(Plugin):
    def permit(self, state: CycleState, pod_info: PodInfo,
               node_name: str) -> tuple[Status | None, float]:
        """Returns (status, wait_timeout_seconds). Status WAIT pauses binding."""
        return None, 0.0


class PreBindPlugin(Plugin):
    def pre_bind(self, state: CycleState, pod_info: PodInfo,
                 node_name: str) -> Status | None:
        return None


class BindPlugin(Plugin):
    def bind(self, state: CycleState, pod_info: PodInfo,
             node_name: str) -> Status | None:
        raise NotImplementedError


class PostBindPlugin(Plugin):
    def post_bind(self, state: CycleState, pod_info: PodInfo,
                  node_name: str) -> None:
        pass


class BatchExtensions:
    """TPU-native batch contract (no reference equivalent — this is the seam
    where the per-pod loop becomes a tensor program).

    A plugin implementing this exposes its Filter as a boolean mask and its
    Score as a float matrix over (batch_pods x nodes), computed on device.
    ops/plugins_tpu.py provides implementations backed by ops/flatten.py
    tensors; scheduler.py composes them under jit.
    """

    def batch_supported(self) -> bool:
        return True


class WaitingPod:
    """A pod paused at Permit (runtime/waiting_pods_map.go)."""

    def __init__(self, pod_info: PodInfo, plugin_timeouts: dict[str, float]):
        self.pod_info = pod_info
        self._pending = set(plugin_timeouts)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._status: Status | None = None
        self._deadline = time.monotonic() + (max(plugin_timeouts.values())
                                             if plugin_timeouts else 0)

    def allow(self, plugin: str) -> None:
        with self._lock:
            self._pending.discard(plugin)
            if not self._pending and self._status is None:
                self._status = Status(SUCCESS)
                self._event.set()

    def reject(self, plugin: str, msg: str = "") -> None:
        with self._lock:
            if self._status is None:
                self._status = Status(UNSCHEDULABLE, msg or f"rejected by {plugin}",
                                      plugin=plugin)
                self._event.set()

    def wait(self) -> Status:
        remaining = self._deadline - time.monotonic()
        if remaining > 0:
            self._event.wait(remaining)
        with self._lock:
            if self._status is None:
                self._status = Status(UNSCHEDULABLE, "timed out waiting on permit")
            return self._status


class Handle:
    """What plugins get to touch (interface.go:587 Handle)."""

    def __init__(self, client=None, informer_factory=None, nominator=None):
        self.client = client
        self.informer_factory = informer_factory
        self.nominator = nominator
        self.waiting_pods: dict[str, WaitingPod] = {}
        self._waiting_lock = threading.Lock()

    def get_waiting_pod(self, uid_or_key: str) -> WaitingPod | None:
        with self._waiting_lock:
            return self.waiting_pods.get(uid_or_key)

    def iterate_waiting_pods(self) -> list[WaitingPod]:
        with self._waiting_lock:
            return list(self.waiting_pods.values())

    def _add_waiting(self, wp: WaitingPod) -> None:
        with self._waiting_lock:
            self.waiting_pods[wp.pod_info.key] = wp

    def _remove_waiting(self, key: str) -> None:
        with self._waiting_lock:
            self.waiting_pods.pop(key, None)


# plugin factory registry (runtime/registry.go)
Registry = dict[str, Callable[[dict, Handle], Plugin]]


class Framework:
    """A configured profile: ordered plugins per extension point
    (runtime/framework.go frameworkImpl)."""

    def __init__(self, profile_name: str, plugins: Sequence[Plugin],
                 score_weights: dict[str, int] | None = None,
                 handle: Handle | None = None,
                 point_filter: Callable[[str, str], bool] | None = None):
        """point_filter(plugin_name, point) gates which extension points a
        plugin is registered at — this is how the component config's
        per-extension-point enable/disable (apis/config types.go Plugins)
        maps onto the isinstance-based distribution below.  None = all."""
        self.profile_name = profile_name
        self.handle = handle or Handle()
        score_weights = score_weights or {}
        allow = point_filter or (lambda name, point: True)
        self.queue_sort: QueueSortPlugin | None = None
        self.pre_filter: list[PreFilterPlugin] = []
        self.filter: list[FilterPlugin] = []
        self.post_filter: list[PostFilterPlugin] = []
        self.pre_score: list[PreScorePlugin] = []
        self.score: list[tuple[ScorePlugin, int]] = []
        self.reserve: list[ReservePlugin] = []
        self.permit: list[PermitPlugin] = []
        self.pre_bind: list[PreBindPlugin] = []
        self.bind: list[BindPlugin] = []
        self.post_bind: list[PostBindPlugin] = []
        self.all_plugins: list[Plugin] = list(plugins)
        # host-side gates the BATCH path must honor: the device kernel
        # covers resource/affinity semantics but not group-membership
        # gates like Coscheduling's minMember PreFilter — without this,
        # an incomplete gang cycles assume -> Permit-wait -> timeout ->
        # Unreserve forever, starving competitors between cycles
        self.batch_gates: list[Plugin] = [
            p for p in plugins
            if getattr(p, "supports_batch_gate", False)
            and allow(p.name, "preFilter")]  # the gate IS the PreFilter
        for p in plugins:
            if isinstance(p, QueueSortPlugin) and allow(p.name, "queueSort"):
                self.queue_sort = p
            if isinstance(p, PreFilterPlugin) and allow(p.name, "preFilter"):
                self.pre_filter.append(p)
            if isinstance(p, FilterPlugin) and allow(p.name, "filter"):
                self.filter.append(p)
            if isinstance(p, PostFilterPlugin) and allow(p.name, "postFilter"):
                self.post_filter.append(p)
            if isinstance(p, PreScorePlugin) and allow(p.name, "preScore"):
                self.pre_score.append(p)
            if isinstance(p, ScorePlugin) and allow(p.name, "score"):
                self.score.append((p, score_weights.get(p.name, 1)))
            if isinstance(p, ReservePlugin) and allow(p.name, "reserve"):
                self.reserve.append(p)
            if isinstance(p, PermitPlugin) and allow(p.name, "permit"):
                self.permit.append(p)
            if isinstance(p, PreBindPlugin) and allow(p.name, "preBind"):
                self.pre_bind.append(p)
            if isinstance(p, BindPlugin) and allow(p.name, "bind"):
                self.bind.append(p)
            if isinstance(p, PostBindPlugin) and allow(p.name, "postBind"):
                self.post_bind.append(p)
        for p in plugins:  # late-bind plugins that need the framework itself
            if hasattr(p, "set_framework"):
                p.set_framework(self)
        # metrics_recorder(extension_point, status_code_str, seconds) — set by
        # the Scheduler; records framework_extension_point_duration_seconds
        # (runtime/framework.go records this around each RunXPlugins).
        self.metrics_recorder = None
        self._instrument_extension_points()

    _TIMED_POINTS = (
        ("PreFilter", "run_pre_filter_plugins"),
        ("PostFilter", "run_post_filter_plugins"),
        ("PreScore", "run_pre_score_plugins"),
        ("Score", "run_score_plugins"),
        ("Reserve", "run_reserve_plugins"),
        ("Permit", "run_permit_plugins"),
        ("PreBind", "run_pre_bind_plugins"),
        ("Bind", "run_bind_plugins"),
    )

    def _instrument_extension_points(self) -> None:
        """Wrap once-per-cycle runners with timing.  Filter is deliberately
        excluded: it runs per node (hot loop); its cost is covered by
        scheduling_algorithm_duration and the TPU device histograms."""
        for point, name in self._TIMED_POINTS:
            orig = getattr(self, name)

            def wrapper(*a, __orig=orig, __point=point, **kw):
                rec = self.metrics_recorder
                if rec is None:
                    return __orig(*a, **kw)
                t0 = time.perf_counter()
                out = __orig(*a, **kw)
                rec(__point, _status_label(out), time.perf_counter() - t0)
                return out

            setattr(self, name, wrapper)

    def cluster_event_map(self) -> dict[str, list[ClusterEvent]]:
        return {p.name: p.events_to_register() for p in self.all_plugins}

    # -- extension-point runners (runtime/framework.go) -------------------

    def run_pre_filter_plugins(self, state: CycleState, pod_info: PodInfo,
                               snapshot: Snapshot
                               ) -> tuple[PreFilterResult | None, Status | None]:
        result: PreFilterResult | None = None
        for p in self.pre_filter:
            r, s = p.pre_filter(state, pod_info, snapshot)
            if s is not None and s.is_skip():
                state.skip_filter_plugins.add(p.name)
                continue
            if not is_success(s):
                s.plugin = s.plugin or p.name
                return None, s
            if r is not None:
                result = r.merge(result) if result is not None else r
                if result.node_names is not None and not result.node_names:
                    return result, Status(
                        UNSCHEDULABLE_AND_UNRESOLVABLE,
                        "node(s) didn't satisfy plugin " + p.name, plugin=p.name)
        return result, None

    def run_filter_plugins(self, state: CycleState, pod_info: PodInfo,
                           node_info: NodeInfo) -> Status | None:
        for p in self.filter:
            if p.name in state.skip_filter_plugins:
                continue
            s = p.filter(state, pod_info, node_info)
            if not is_success(s):
                s.plugin = s.plugin or p.name
                return s
        return None

    def run_filter_plugins_with_nominated_pods(
            self, state: CycleState, pod_info: PodInfo,
            node_info: NodeInfo) -> Status | None:
        """schedule_one.go:455 + runtime/framework.go addNominatedPods:
        filter twice when higher-priority nominated pods exist on the node."""
        nominator = self.handle.nominator
        nominated = (nominator.nominated_pods_for_node(node_info.name)
                     if nominator else [])
        relevant = [pi for pi in nominated
                    if pi.priority >= pod_info.priority and pi.key != pod_info.key]
        if relevant:
            ni2 = node_info.clone()
            state2 = state.clone()
            for pi in relevant:
                ni2.add_pod(pi)
                for p in self.pre_filter:
                    p.add_pod(state2, pod_info, pi, ni2)
            s = self.run_filter_plugins(state2, pod_info, ni2)
            if not is_success(s):
                return s
        return self.run_filter_plugins(state, pod_info, node_info)

    def run_post_filter_plugins(self, state: CycleState, pod_info: PodInfo,
                                statuses: dict[str, Status]
                                ) -> tuple[str | None, Status]:
        best: str | None = None
        last = Status(UNSCHEDULABLE)
        for p in self.post_filter:
            nominated, s = p.post_filter(state, pod_info, statuses)
            if s is not None and s.code == SUCCESS:
                return nominated, s
            if s is not None and s.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                return None, s
            if s is not None:
                last = s
        return best, last

    def run_pre_score_plugins(self, state: CycleState, pod_info: PodInfo,
                              nodes: list[NodeInfo]) -> Status | None:
        for p in self.pre_score:
            s = p.pre_score(state, pod_info, nodes)
            if s is not None and s.is_skip():
                state.skip_score_plugins.add(p.name)
                continue
            if not is_success(s):
                s.plugin = s.plugin or p.name
                return s
        return None

    def run_score_plugins(self, state: CycleState, pod_info: PodInfo,
                          nodes: list[NodeInfo]
                          ) -> tuple[dict[str, int], Status | None]:
        """Returns total weighted score per node name (framework.go:903)."""
        totals: dict[str, int] = {ni.name: 0 for ni in nodes}
        for p, weight in self.score:
            if p.name in state.skip_score_plugins:
                continue
            scores: dict[str, int] = {}
            for ni in nodes:
                val, s = p.score(state, pod_info, ni)
                if not is_success(s):
                    s.plugin = s.plugin or p.name
                    return {}, s
                scores[ni.name] = val
            s = p.normalize_scores(state, pod_info, scores)
            if not is_success(s):
                return {}, s
            for name, val in scores.items():
                totals[name] += val * weight
        return totals, None

    def run_reserve_plugins(self, state: CycleState, pod_info: PodInfo,
                            node_name: str) -> Status | None:
        for i, p in enumerate(self.reserve):
            s = p.reserve(state, pod_info, node_name)
            if not is_success(s):
                for q in self.reserve[:i + 1]:
                    q.unreserve(state, pod_info, node_name)
                s.plugin = s.plugin or p.name
                return s
        return None

    def run_unreserve_plugins(self, state: CycleState, pod_info: PodInfo,
                              node_name: str) -> None:
        for p in reversed(self.reserve):
            p.unreserve(state, pod_info, node_name)

    def run_permit_plugins(self, state: CycleState, pod_info: PodInfo,
                           node_name: str) -> Status | None:
        timeouts: dict[str, float] = {}
        for p in self.permit:
            s, timeout = p.permit(state, pod_info, node_name)
            if s is not None and s.is_wait():
                timeouts[p.name] = timeout
            elif not is_success(s):
                s.plugin = s.plugin or p.name
                return s
        if timeouts:
            wp = WaitingPod(pod_info, timeouts)
            self.handle._add_waiting(wp)
            return Status(WAIT)
        return None

    def wait_on_permit(self, pod_info: PodInfo) -> Status | None:
        wp = self.handle.get_waiting_pod(pod_info.key)
        if wp is None:
            return None
        try:
            return wp.wait()
        finally:
            self.handle._remove_waiting(pod_info.key)

    def run_pre_bind_plugins(self, state: CycleState, pod_info: PodInfo,
                             node_name: str) -> Status | None:
        for p in self.pre_bind:
            s = p.pre_bind(state, pod_info, node_name)
            if not is_success(s):
                s.plugin = s.plugin or p.name
                return s
        return None

    def run_bind_plugins(self, state: CycleState, pod_info: PodInfo,
                         node_name: str) -> Status | None:
        if not self.bind:
            return Status(ERROR, "no bind plugin configured")
        for p in self.bind:
            s = p.bind(state, pod_info, node_name)
            if s is not None and s.is_skip():
                continue
            if not is_success(s):
                s.plugin = s.plugin or p.name
            return s
        return Status(ERROR, "all bind plugins skipped")

    def batch_tail_trivial(self) -> bool:
        """True when the Reserve/Permit/WaitOnPermit/PreBind/PostBind hooks
        are PROVABLY no-ops for a pod whose CycleState is empty — every
        plugin at those points is `state_gated` (acts only on state written
        by its own PreFilter, which the batch path never runs) and no
        Permit plugin exists (so nothing can ever be in the waiting map).
        The batch bind tail uses this to skip the per-pod hook loops
        wholesale; adding e.g. Coscheduling (Permit) or any non-gated
        reserve plugin turns the full path back on automatically."""
        return (not self.permit
                and all(getattr(p, "state_gated", False) for p in self.reserve)
                and all(getattr(p, "state_gated", False) for p in self.pre_bind)
                and all(getattr(p, "state_gated", False)
                        for p in self.post_bind))

    def run_post_bind_plugins(self, state: CycleState, pod_info: PodInfo,
                              node_name: str) -> None:
        for p in self.post_bind:
            p.post_bind(state, pod_info, node_name)
