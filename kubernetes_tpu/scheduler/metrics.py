"""Scheduler metrics.

Reference: pkg/scheduler/metrics/metrics.go:30-200 — the full named metric
set, registered on the shared component-base registry with the same
stability levels and the same exponential latency buckets
(ExponentialBuckets(0.001, 2, 15), metrics.go:58-65).  The queue exposes
pending_pods{queue=active|backoff|unschedulable} and the framework runtime
records per-extension-point / per-plugin duration histograms.
"""

from __future__ import annotations

from ..component_base import metrics as cbm

SCHEDULER_SUBSYSTEM = "scheduler"

# SLO-boundary fix: the upstream exponential ladder straddles the paper's
# 10 ms target between 0.008 and 0.016, so "p99 < 10ms" could not be read
# off the histogram — the 0.010 boundary is inserted explicitly.
_LATENCY_BUCKETS = sorted(cbm.exponential_buckets(0.001, 2, 15) + [0.010])


class Metrics:
    """One bundle per scheduler process (tests get isolated registries)."""

    def __init__(self, registry: cbm.Registry | None = None):
        self.registry = registry or cbm.Registry()
        r = self.registry
        self.schedule_attempts = cbm.Counter(
            "scheduler_schedule_attempts_total",
            "Number of attempts to schedule pods, by result.",
            labels=("result", "profile"), stability=cbm.STABLE)
        self.scheduling_attempt_duration = cbm.Histogram(
            "scheduler_scheduling_attempt_duration_seconds",
            "Scheduling attempt latency (algorithm + binding).",
            labels=("result", "profile"), buckets=_LATENCY_BUCKETS,
            stability=cbm.STABLE)
        self.scheduling_algorithm_duration = cbm.Histogram(
            "scheduler_scheduling_algorithm_duration_seconds",
            "Scheduling algorithm latency.",
            labels=("profile",), buckets=_LATENCY_BUCKETS)
        self.pod_scheduling_duration = cbm.Histogram(
            "scheduler_pod_scheduling_duration_seconds",
            "E2e pod scheduling latency, from first attempt to bound.",
            labels=("attempts",),
            buckets=cbm.exponential_buckets(0.001, 2, 20),
            stability=cbm.STABLE)
        self.pod_scheduling_attempts = cbm.Histogram(
            "scheduler_pod_scheduling_attempts",
            "Number of attempts to successfully schedule a pod.",
            buckets=[1, 2, 4, 8, 16], stability=cbm.STABLE)
        self.framework_extension_point_duration = cbm.Histogram(
            "scheduler_framework_extension_point_duration_seconds",
            "Latency for running all plugins of an extension point.",
            labels=("extension_point", "status", "profile"),
            buckets=cbm.exponential_buckets(0.0001, 2, 12))
        self.plugin_execution_duration = cbm.Histogram(
            "scheduler_plugin_execution_duration_seconds",
            "Duration for running a plugin at a specific extension point.",
            labels=("plugin", "extension_point", "status"),
            buckets=cbm.exponential_buckets(0.00001, 1.5, 20))
        self.pending_pods = cbm.Gauge(
            "scheduler_pending_pods",
            "Pending pods by queue: active, backoff, unschedulable, gated.",
            labels=("queue",), stability=cbm.STABLE)
        self.queue_incoming_pods = cbm.Counter(
            "scheduler_queue_incoming_pods_total",
            "Pods added to scheduling queues by event and queue.",
            labels=("queue", "event"), stability=cbm.STABLE)
        self.preemption_attempts = cbm.Counter(
            "scheduler_preemption_attempts_total",
            "Total preemption attempts in the cluster.", stability=cbm.STABLE)
        self.preemption_victims = cbm.Histogram(
            "scheduler_preemption_victims",
            "Number of selected preemption victims.",
            buckets=cbm.linear_buckets(5, 5, 10), stability=cbm.STABLE)
        self.cache_size = cbm.Gauge(
            "scheduler_scheduler_cache_size",
            "Number of nodes, pods, and assumed pods in the cache.",
            labels=("type",))
        self.unschedulable_reasons = cbm.Gauge(
            "scheduler_unschedulable_pods",
            "Pods the scheduler found unschedulable, by plugin and profile.",
            labels=("plugin", "profile"))
        self.goroutines = cbm.Gauge(
            "scheduler_goroutines",
            "Number of running binding goroutines.", labels=("operation",))
        # TPU-path additions (no upstream analogue): batch shape + device time
        self.tpu_batch_size = cbm.Histogram(
            "scheduler_tpu_batch_size",
            "Pods per TPU assignment batch.",
            buckets=[1, 8, 32, 64, 128, 256, 512, 1024])
        self.tpu_device_duration = cbm.Histogram(
            "scheduler_tpu_device_duration_seconds",
            "Device time per TPU assignment batch.",
            buckets=_LATENCY_BUCKETS)
        # remote-seam resilience (ops/remote.py + ops/failover.py): the
        # scheduler loop pushes batch-failure events; the backend's own
        # cumulative counters (retries/resyncs/failovers) are snapshotted
        # into the _state gauge at expose time (Scheduler.expose_metrics)
        self.tpu_seam_events = cbm.Counter(
            "scheduler_tpu_seam_events_total",
            "Remote TPU seam events observed by the scheduling loop "
            "(batch_failures, requeued_pods).",
            labels=("event",))
        self.tpu_seam_state = cbm.Gauge(
            "scheduler_tpu_seam_state",
            "Cumulative remote-seam resilience counters (retries, resyncs, "
            "state_lost, failovers, recloses...), snapshotted from the "
            "batch backend at expose time.",
            labels=("counter",))
        self.tpu_seam_breaker = cbm.Gauge(
            "scheduler_tpu_seam_breaker_open",
            "Circuit-breaker state per backend rung (1 = open/failed over).",
            labels=("rung",))
        # batch-telemetry additions (observability PR): WHY pods leave the
        # device batch path, and how selective each batch was.  The escape
        # counter is drained from the backend's per-batch reason tallies in
        # _finish_batch (Counter is inc-only, so the scheduler applies
        # deltas, never snapshots).
        self.tpu_escape_total = cbm.Counter(
            "scheduler_tpu_escape_total",
            "Pods escaped from the TPU batch path to the per-pod oracle, "
            "by owning plugin and escape reason (e.g. namespace_selector).",
            labels=("plugin", "reason"))
        self.tpu_mask_density = cbm.Gauge(
            "scheduler_tpu_mask_density",
            "Fraction of batch pods carrying an active constraint mask for "
            "a plugin family, from the most recent dispatched batch.",
            labels=("plugin",))
        self.tpu_feasible_nodes = cbm.Histogram(
            "scheduler_tpu_feasible_nodes",
            "Schedulable node rows per dispatched batch (the device "
            "feasibility domain before per-pod filter masks).",
            buckets=cbm.exponential_buckets(1, 4, 10))
        self.tpu_batch_waves = cbm.Histogram(
            "scheduler_tpu_batch_waves",
            "Device assignment-solver waves per batch.",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256, 512])
        self.tpu_victim_occupancy = cbm.Gauge(
            "scheduler_tpu_victim_occupancy",
            "Fraction of per-node victim tensor slots (v_cap) holding a "
            "resident pod, from the most recent victim-tensor refresh.")
        # overload-protection additions (overload: stanza): bounded
        # admission sheds, escape-storm deferrals, watchdog cancels, and
        # the AIMD wave-size / breaker state gauges.  Shed tallies
        # accumulate inside the queue and are drained at expose time
        # (same drain discipline as the escape counter above).
        self.queue_shed_total = cbm.Counter(
            "scheduler_queue_shed_total",
            "Pods shed from activeQ to the backoff tier by bounded "
            "admission, by shed reason and pod priority band.",
            labels=("reason", "priority_band"))
        self.overload_deferred_total = cbm.Counter(
            "scheduler_overload_deferred_total",
            "Escaped pods deferred to the backoff tier by the open "
            "escape-storm breaker instead of the per-pod oracle, by "
            "dominant escape reason.",
            labels=("reason",))
        self.overload_wave_cancel_total = cbm.Counter(
            "scheduler_overload_wave_cancel_total",
            "Waves cancelled by the stuck-wave watchdog, by reason.",
            labels=("reason",))
        self.overload_wave_size = cbm.Gauge(
            "scheduler_overload_wave_size",
            "Current AIMD-controlled dispatch wave size.")
        self.overload_breaker_open = cbm.Gauge(
            "scheduler_overload_breaker_open",
            "Escape-storm breaker state (1 = open: escapes deferred).")
        # signal-driven engagement (overload: engagement): the hysteresis
        # state machine that decides WHEN the four layers above act.
        # Transitions are counted at the edge (scheduler loop thread is
        # the only writer); the gauge is refreshed at expose time.
        self.overload_engaged = cbm.Gauge(
            "scheduler_overload_engaged",
            "Overload engagement state (1 = engaged or cooling: the "
            "admission/AIMD/breaker/watchdog layers are active; 0 = "
            "disengaged or arming: quiescent).")
        self.overload_transition_total = cbm.Counter(
            "scheduler_overload_transition_total",
            "Engagement state-machine transitions, by from/to state and "
            "trigger reason (slo_burn, queue_growth, blip, calm, "
            "re_pressure, cooled, config).",
            labels=("from", "to", "reason"))
        # scale-out additions (scaleOut: stanza): optimistic-bind races
        # between cooperating scheduler instances, resolved at commit time
        # (Omega shared-state model).  The loser classifies each conflicted
        # pod into an outcome: requeued / lost_to_peer /
        # already_bound_same_node / fenced.
        self.bind_conflict_total = cbm.Counter(
            "scheduler_bind_conflict_total",
            "Pods whose bind was rejected because a peer scheduler "
            "instance claimed them first (or this instance lost its "
            "lease), by conflict outcome.",
            labels=("outcome",))
        self.informer_relist_total = cbm.Counter(
            "informer_relist_total",
            "Informer list/watch restarts, by resource and reason "
            "(too_old = watch window expired, error = list/watch failed).",
            labels=("resource", "reason"))
        # performance-observatory additions (profiling: stanza): the
        # device cost census commits the offline collective-census tool's
        # numbers as gauges (set once per census run, at warmup), the
        # host profiler drains per-stage host seconds at expose time
        # (inc-only deltas, same drain discipline as the escape counter),
        # and the SLO tracker publishes rolling-window latency quantiles
        # + multi-window burn rates — the arm/disarm signal for adaptive
        # overload engagement.
        self.tpu_wave_collective_bytes = cbm.Gauge(
            "tpu_wave_collective_bytes",
            "ICI-collective bytes PER WAVE in the compiled scheduling "
            "step (collectives inside the wave loop), by collective op "
            "and backend-variant — the runtime twin of "
            "tools/collective_census.py, bit-identical at equal shapes.",
            labels=("collective", "backend"))
        self.tpu_step_collective_bytes = cbm.Gauge(
            "tpu_step_collective_bytes",
            "ICI-collective bytes ONCE PER STEP in the compiled "
            "scheduling step (outside the wave loop), by collective op "
            "and backend-variant.",
            labels=("collective", "backend"))
        self.tpu_wave_flops = cbm.Gauge(
            "tpu_wave_flops",
            "XLA cost-analysis flops of one compiled scheduling step, "
            "by backend and kernel variant.",
            labels=("backend", "variant"))
        self.tpu_step_hbm_bytes = cbm.Gauge(
            "tpu_step_hbm_bytes",
            "XLA cost-analysis bytes accessed (HBM traffic proxy) of one "
            "compiled scheduling step, by backend and kernel variant.",
            labels=("backend", "variant"))
        self.host_stage_seconds = cbm.Counter(
            "scheduler_host_stage_seconds",
            "Sampled host CPU-attribution seconds per pipeline stage "
            "(informer, submitter, resolver, binder, queue...), drained "
            "from the sampling profiler at expose time.",
            labels=("stage",))
        self.slo_latency_ms = cbm.Gauge(
            "scheduler_slo_latency_ms",
            "Rolling-window submit-to-bind scheduling latency quantiles "
            "against the SLO target, in milliseconds.",
            labels=("quantile",))
        self.slo_burn_rate = cbm.Gauge(
            "scheduler_slo_burn_rate",
            "SLO error-budget burn rate per lookback window (1.0 = "
            "budget consumed exactly at the sustainable rate; the "
            "multi-window AND arms overload engagement).",
            labels=("window",))
        # incremental-flatten additions (tensor-maintenance PR): how each
        # dispatched wave synced the resident device tensors (patched in
        # place vs full re-flatten/refresh — the perf headline), plus the
        # row-slot allocator's occupancy/tombstone pressure, snapshotted
        # from the backend's maintenance counters at expose time (waves
        # are inc-only deltas, occupancy is a point-in-time gauge).
        self.tpu_tensor_waves = cbm.Counter(
            "scheduler_tpu_tensor_waves_total",
            "Dispatched device waves by tensor-maintenance mode: patched "
            "(targeted row patches / event patches / no-op) vs "
            "reflattened (full snapshot re-encode + state refresh).",
            labels=("mode",))
        self.tpu_tensor_occupancy = cbm.Gauge(
            "scheduler_tpu_tensor_occupancy",
            "Fraction of node-tensor row slots (n_cap) bound to a live "
            "node in the resident ClusterTensors row allocator.")
        self.tpu_tensor_tombstones = cbm.Gauge(
            "scheduler_tpu_tensor_tombstones",
            "Node-tensor row slots released by node deletion but not yet "
            "reclaimed by compaction (tombstoned rows).")
        # zero-downtime-operations additions: config hot-reload outcomes
        # (SIGHUP / supervisor RPC re-reading the dynamic stanzas; a
        # rejected reload keeps the old config live)
        self.config_reload_total = cbm.Counter(
            "scheduler_config_reload_total",
            "Config hot-reload attempts, by result (applied = dynamic "
            "stanzas installed, rejected = validation failed and the old "
            "config stayed live).",
            labels=("result",))
        # wave-timeline additions (profiling.timeline): interval-union
        # derived views of the pipeline — idle share and overlap are
        # point-in-time gauges recomputed from the interval ring at
        # expose time; the per-pod decomposition histogram is observed
        # at bind-commit (and therefore only when the timeline is on).
        self.wave_device_idle_share = cbm.Gauge(
            "scheduler_wave_device_idle_share",
            "Wall-clock fraction of the recent timeline window during "
            "which NO device stage (h2d/device-step/d2h) was in flight, "
            "computed by interval union over the stage-interval ring — "
            "correct under wave pipelining, unlike 1 - sum(stages)/wall.")
        self.stage_overlap_ratio = cbm.Gauge(
            "scheduler_stage_overlap_ratio",
            "Per pipeline stage: fraction of the stage's own busy time "
            "(interval union) during which at least one OTHER stage was "
            "also in flight. 0 = fully serial; pipelining drives the "
            "device stages toward 1.",
            labels=("stage",))
        self.pod_latency_ms = cbm.Histogram(
            "scheduler_pod_latency_ms",
            "Per-pod e2e latency decomposition in milliseconds, by "
            "telescoped segment (queue/form/device/resolve/bind/watch): "
            "segment boundaries are wave-timeline wall marks, so the "
            "segments of one pod sum to its e2e by construction.",
            labels=("segment",),
            buckets=cbm.exponential_buckets(0.25, 2, 16))
        r.must_register(
            self.schedule_attempts, self.scheduling_attempt_duration,
            self.scheduling_algorithm_duration, self.pod_scheduling_duration,
            self.pod_scheduling_attempts,
            self.framework_extension_point_duration,
            self.plugin_execution_duration, self.pending_pods,
            self.queue_incoming_pods, self.preemption_attempts,
            self.preemption_victims, self.cache_size,
            self.unschedulable_reasons, self.goroutines,
            self.tpu_batch_size, self.tpu_device_duration,
            self.tpu_seam_events, self.tpu_seam_state,
            self.tpu_seam_breaker, self.tpu_escape_total,
            self.tpu_mask_density, self.tpu_feasible_nodes,
            self.tpu_batch_waves, self.tpu_victim_occupancy,
            self.queue_shed_total, self.overload_deferred_total,
            self.overload_wave_cancel_total, self.overload_wave_size,
            self.overload_breaker_open, self.overload_engaged,
            self.overload_transition_total, self.bind_conflict_total,
            self.informer_relist_total, self.tpu_wave_collective_bytes,
            self.tpu_step_collective_bytes, self.tpu_wave_flops,
            self.tpu_step_hbm_bytes, self.host_stage_seconds,
            self.slo_latency_ms, self.slo_burn_rate,
            self.tpu_tensor_waves, self.tpu_tensor_occupancy,
            self.tpu_tensor_tombstones, self.config_reload_total,
            self.wave_device_idle_share, self.stage_overlap_ratio,
            self.pod_latency_ms)

    def expose(self) -> str:
        return self.registry.expose()
