"""In-tree plugins + registry.

Reference: pkg/scheduler/framework/plugins/registry.go:47-80 (all plugins),
apis/config/v1/default_plugins.go:28-56 (default enablement + weights).
"""

from __future__ import annotations

from ..framework import Handle, Plugin, Registry
from .coscheduling import Coscheduling
from .defaultbinder import DefaultBinder
from .defaultpreemption import DefaultPreemption
from .interpodaffinity import InterPodAffinity
from .nodebasic import (
    ImageLocality, NodeAffinity, NodeName, NodePorts, NodeUnschedulable,
    TaintToleration,
)
from .noderesources import NodeResourcesBalancedAllocation, NodeResourcesFit
from .nodevolumelimits import NodeVolumeLimits
from .podtopologyspread import PodTopologySpread
from .queuesort import PrioritySort
from .selectorspread import SelectorSpread
from .volumebinding import VolumeBinding
from .volumerestrictions import VolumeRestrictions
from .volumezone import VolumeZone

# default score weights (default_plugins.go: NodeResourcesBalancedAllocation 1,
# ImageLocality 1, InterPodAffinity 1, NodeResourcesFit 1, NodeAffinity 1,
# PodTopologySpread 2, TaintToleration 1)
DEFAULT_SCORE_WEIGHTS = {
    "NodeResourcesBalancedAllocation": 1,
    "ImageLocality": 1,
    "InterPodAffinity": 1,
    "NodeResourcesFit": 1,
    "NodeAffinity": 1,
    "PodTopologySpread": 2,
    "TaintToleration": 1,
}


def in_tree_registry() -> Registry:
    """Name -> factory(args, handle) (runtime/registry.go)."""
    return {
        "PrioritySort": lambda args, h: PrioritySort(),
        "NodeName": lambda args, h: NodeName(),
        "NodePorts": lambda args, h: NodePorts(),
        "NodeUnschedulable": lambda args, h: NodeUnschedulable(),
        "NodeAffinity": lambda args, h: NodeAffinity(),
        "TaintToleration": lambda args, h: TaintToleration(),
        "ImageLocality": lambda args, h: ImageLocality(),
        "NodeResourcesFit": lambda args, h: NodeResourcesFit(**(args or {})),
        "NodeResourcesBalancedAllocation":
            lambda args, h: NodeResourcesBalancedAllocation(**(args or {})),
        "PodTopologySpread": lambda args, h: PodTopologySpread(),
        "InterPodAffinity": lambda args, h: InterPodAffinity(h),
        "DefaultBinder": lambda args, h: DefaultBinder(h.client),
        "DefaultPreemption": lambda args, h: DefaultPreemption(h.client),
        "Coscheduling": lambda args, h: Coscheduling(h.client, h),
        "VolumeBinding":
            lambda args, h: VolumeBinding(h.client, h.informer_factory),
        "VolumeRestrictions":
            lambda args, h: VolumeRestrictions(h.informer_factory),
        "VolumeZone": lambda args, h: VolumeZone(h.informer_factory),
        "NodeVolumeLimits":
            lambda args, h: NodeVolumeLimits(h.informer_factory),
        "SelectorSpread": lambda args, h: SelectorSpread(h.informer_factory),
    }


DEFAULT_PLUGINS = [
    "PrioritySort",
    "NodeUnschedulable",
    "NodeName",
    "NodePorts",
    "NodeAffinity",
    "NodeResourcesFit",
    "TaintToleration",
    "PodTopologySpread",
    "InterPodAffinity",
    "NodeResourcesBalancedAllocation",
    "ImageLocality",
    "VolumeBinding",
    "VolumeRestrictions",
    "VolumeZone",
    "NodeVolumeLimits",
    "DefaultPreemption",
    "DefaultBinder",
]
# SelectorSpread is registered but not default-enabled (default_plugins.go:
# PodTopologySpread subsumed it in v1.25+).


def build_default_plugins(handle: Handle, enabled: list[str] | None = None,
                          plugin_args: dict[str, dict] | None = None) -> list[Plugin]:
    registry = in_tree_registry()
    plugin_args = plugin_args or {}
    return [registry[name](plugin_args.get(name), handle)
            for name in (enabled or DEFAULT_PLUGINS)]
