"""Coscheduling (gang scheduling) plugin.

Reference: the kubernetes-sigs/scheduler-plugins Coscheduling plugin —
out-of-tree in the reference ecosystem (SURVEY.md §2.2 note), built on the
in-tree Permit/WaitOnPermit machinery (framework/interface.go:482-491,
runtime/waiting_pods_map.go), which this framework reproduces.

Model: a PodGroup object ("podgroups" resource) declares spec.minMember;
pods join a group via the label `scheduling.x-k8s.io/pod-group`.  A pod of
a group reaching Permit WAITs until minMember of its group are bound or
waiting; the threshold crossing allows the whole gang at once (all-or-
nothing binding).  PreFilter rejects pods whose group hasn't even been
created at minMember size yet, so partial gangs never hold resources.
"""

from __future__ import annotations

import time

from ...api import meta
from ...client.clientset import PODGROUPS, PODS
from ..framework import CycleState, PermitPlugin, PostBindPlugin, PreFilterPlugin
from ..types import (
    SKIP, SUCCESS, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, WAIT,
    ClusterEvent, PodInfo, Status,
)

POD_GROUP_LABEL = "scheduling.x-k8s.io/pod-group"
DEFAULT_WAIT_TIME = 60.0


def pod_group_name(pod_info: PodInfo) -> str | None:
    return pod_info.labels.get(POD_GROUP_LABEL)


class Coscheduling(PreFilterPlugin, PermitPlugin, PostBindPlugin):
    name = "Coscheduling"
    # the device batch path must run this plugin's membership gate on
    # the host before encoding (scheduler._dispatch_batch): an
    # incomplete gang that reaches Permit live-locks through
    # assume/wait/timeout/Unreserve cycles, starving competitors
    supports_batch_gate = True

    def __init__(self, client=None, handle=None):
        self.client = client
        self.handle = handle

    def batch_gate(self, pod_info: PodInfo, cache: dict | None = None):
        """Cheap host gate for the batch path: ~one dict lookup for
        non-gang pods; the PreFilter membership check ONCE PER GROUP
        per batch (`cache` is the dispatcher's per-batch memo — the
        membership scan is O(total pods) and identical for every
        member of a group in the same batch)."""
        group = pod_group_name(pod_info)
        if group is None:
            return None
        key = (self.name, meta.namespace(pod_info.pod), group)
        if cache is not None and key in cache:
            return cache[key]
        _result, status = self.pre_filter(CycleState(), pod_info, None)
        if status is not None and status.is_skip():
            status = None
        if cache is not None:
            cache[key] = status
        return status

    def events_to_register(self):
        return [ClusterEvent("Pod", "Add"), ClusterEvent("AssignedPod", "Add"),
                ClusterEvent("PodGroup", "*")]

    def _group(self, pod_info: PodInfo):
        name = pod_group_name(pod_info)
        if not name:
            return None, None
        try:
            pg = self.client.get(PODGROUPS, meta.namespace(pod_info.pod), name)
        except Exception:  # noqa: BLE001 - group object missing
            return name, None
        return name, pg

    def _member_pods(self, namespace: str, group: str) -> list:
        items, _ = self.client.list(PODS, namespace)
        return [p for p in items
                if (meta.labels(p).get(POD_GROUP_LABEL) == group
                    and not meta.pod_is_terminal(p))]

    # -- PreFilter -------------------------------------------------------

    def pre_filter(self, state: CycleState, pod_info: PodInfo, snapshot):
        name, pg = self._group(pod_info)
        if name is None:
            return None, Status(SKIP)
        if pg is None:
            return None, Status(
                UNSCHEDULABLE_AND_UNRESOLVABLE,
                f"pod group {name!r} does not exist", plugin=self.name)
        min_member = (pg.get("spec") or {}).get("minMember", 1)
        members = self._member_pods(meta.namespace(pod_info.pod), name)
        if len(members) < min_member:
            return None, Status(
                UNSCHEDULABLE,
                f"pod group {name!r} has {len(members)} pods, needs {min_member}",
                plugin=self.name)
        return None, None

    # -- Permit (the gang barrier) --------------------------------------

    def permit(self, state: CycleState, pod_info: PodInfo,
               node_name: str) -> tuple[Status | None, float]:
        name, pg = self._group(pod_info)
        if name is None:
            return None, 0.0
        min_member = ((pg.get("spec") or {}).get("minMember", 1)
                      if pg else 1)
        timeout = ((pg.get("spec") or {}).get("scheduleTimeoutSeconds",
                                              DEFAULT_WAIT_TIME)
                   if pg else DEFAULT_WAIT_TIME)
        ns = meta.namespace(pod_info.pod)
        bound = sum(1 for p in self._member_pods(ns, name)
                    if meta.pod_node_name(p))
        waiting = [wp for wp in self.handle.iterate_waiting_pods()
                   if pod_group_name(wp.pod_info) == name
                   and meta.namespace(wp.pod_info.pod) == ns]
        # +1 for this pod, which isn't in the waiting map yet
        if bound + len(waiting) + 1 >= min_member:
            for wp in waiting:
                wp.allow(self.name)
            return Status(SUCCESS), 0.0
        return Status(WAIT), float(timeout)

    # -- PostBind cleanup ------------------------------------------------

    def post_bind(self, state: CycleState, pod_info: PodInfo,
                  node_name: str) -> None:
        name, pg = self._group(pod_info)
        if name is None or pg is None:
            return
        try:
            def bump(g):
                st = g.setdefault("status", {})
                st["scheduled"] = st.get("scheduled", 0) + 1
                if st["scheduled"] >= (g.get("spec") or {}).get("minMember", 1):
                    st["phase"] = "Scheduled"
                return g
            self.client.guaranteed_update(
                PODGROUPS, meta.namespace(pod_info.pod), name, bump)
        except Exception:  # noqa: BLE001
            pass
