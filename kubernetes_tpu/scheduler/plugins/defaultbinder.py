"""DefaultBinder — writes the Binding (sets spec.nodeName via the client).

Reference: pkg/scheduler/framework/plugins/defaultbinder/default_binder.go:62
(POST pods/{name}/binding subresource).
"""

from __future__ import annotations

from ...store import kv
from ..framework import BindPlugin, CycleState
from ..types import ERROR, NodeInfo, PodInfo, Status


class DefaultBinder(BindPlugin):
    name = "DefaultBinder"
    # marks the scheduler's bulk-bind fast path as semantically equivalent
    is_default_binder = True

    def __init__(self, client=None):
        self.client = client

    def bind(self, state: CycleState, pod_info: PodInfo,
             node_name: str) -> Status | None:
        try:
            self.client.bind(pod_info.pod, node_name)
        except kv.BindConflict:
            # lost the optimistic race to a peer scheduler instance: the
            # typed error must reach the binding cycle intact so the pod
            # is Forgotten + reclassified, not blamed as a plain error
            raise
        except kv.StoreError as e:
            return Status(ERROR, f"binding rejected: {e}")
        return None
