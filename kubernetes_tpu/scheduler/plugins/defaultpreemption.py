"""DefaultPreemption PostFilter plugin.

Reference: pkg/scheduler/framework/plugins/defaultpreemption/
default_preemption.go:83 — wraps preemption.Evaluator; on success the pod
is nominated onto the chosen node (status.nominatedNodeName) and requeued;
victim deletion events re-activate it.
"""

from __future__ import annotations

from ...api import meta
from ...client.clientset import PODS
from ..framework import CycleState, PostFilterPlugin
from ..preemption import Evaluator
from ..types import SUCCESS, UNSCHEDULABLE, ClusterEvent, PodInfo, Status


class DefaultPreemption(PostFilterPlugin):
    name = "DefaultPreemption"

    def __init__(self, client=None, framework=None, snapshot_getter=None):
        self.client = client
        self._framework = framework
        self._snapshot_getter = snapshot_getter or (lambda: None)
        self._evaluator: Evaluator | None = None
        # set by the Scheduler: observer(victim_count) for preemption metrics
        self.preemption_observer = None

    def set_framework(self, fw) -> None:
        self._framework = fw

    def events_to_register(self):
        return [ClusterEvent("AssignedPod", "Delete"), ClusterEvent("Pod", "Delete")]

    def evaluator(self) -> Evaluator:
        """The (lazily built) evaluator — shared with the batched TPU
        preemption path so both run identical victim selection."""
        if self._evaluator is None:
            self._evaluator = Evaluator(
                self._framework, self.client,
                observer=lambda n: (self.preemption_observer(n)
                                    if self.preemption_observer else None))
        return self._evaluator

    def persist_nomination(self, pod_info: PodInfo, nominated: str) -> None:
        """Patch status.nominatedNodeName (handleSchedulingFailure)."""
        try:
            def patch(p):
                p.setdefault("status", {})["nominatedNodeName"] = nominated
                return p
            self.client.guaranteed_update(
                PODS, meta.namespace(pod_info.pod), meta.name(pod_info.pod),
                patch)
        except Exception:  # noqa: BLE001
            pass

    def post_filter(self, state: CycleState, pod_info: PodInfo,
                    filtered_node_status_map: dict[str, Status]
                    ) -> tuple[str | None, Status]:
        self.evaluator()
        snapshot = self._snapshot_getter()
        if snapshot is None:
            return None, Status(UNSCHEDULABLE, "no snapshot for preemption")
        nominated, status = self._evaluator.preempt(
            state, pod_info, filtered_node_status_map, snapshot)
        if nominated:
            # persist the nomination (schedule_one.go handleSchedulingFailure
            # patches status.nominatedNodeName via the API)
            self.persist_nomination(pod_info, nominated)
            return nominated, Status(SUCCESS)
        return None, status
