"""InterPodAffinity plugin.

Reference: pkg/scheduler/framework/plugins/interpodaffinity/
  filtering.go:90-150  preFilterState: three (topologyKey,value)->count maps
    - existing_anti: counts of existing pods whose REQUIRED anti-affinity
      terms match the incoming pod (scanned from
      nodeInfo.pods_with_required_anti_affinity, :155)
    - affinity: counts of existing pods matching each of the incoming pod's
      required affinity terms (:187)
    - anti_affinity: counts of existing pods matching the incoming pod's
      required anti-affinity terms
  filtering.go:367 Filter — a node passes iff
    (1) no existing pod's anti-affinity matches the incoming pod in the
        node's topology domains,
    (2) every incoming affinity term has a match in the node's domain (with
        the self-match bootstrap exception, :439), and
    (3) no incoming anti-affinity term has a match in the node's domain.
  scoring.go:232 Score (weighted preferred-term matches, both directions),
  :254 NormalizeScore (shift negatives, scale to 0..100).

On the TPU path these become label-match boolean matrices x topology one-hot
segment sums (ops/predicates.py interpod_*).
"""

from __future__ import annotations

from ...api import meta
from ..framework import (
    MAX_NODE_SCORE, CycleState, FilterPlugin, PreFilterPlugin, PreScorePlugin,
    ScorePlugin,
)
from ..types import (
    SKIP, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE,
    AffinityTerm, ClusterEvent, NodeInfo, PodInfo, Status,
)

_STATE_KEY = "PreFilterInterPodAffinity"
_SCORE_STATE_KEY = "PreScoreInterPodAffinity"

TPCounts = dict[tuple[str, str], int]


class _PreFilterState:
    __slots__ = ("existing_anti", "affinity_counts", "anti_affinity_counts",
                 "pod_info", "ns_labels", "anti_keys")

    def __init__(self) -> None:
        self.existing_anti: TPCounts = {}
        # one count-map per required affinity term of the incoming pod
        self.affinity_counts: list[TPCounts] = []
        self.anti_affinity_counts: TPCounts = {}
        self.pod_info: PodInfo | None = None
        self.ns_labels: dict | None = None
        # distinct topology KEYS present in existing_anti: Filter does
        # one node-label lookup per KEY + one dict get, instead of
        # scanning every (key,value) entry per node — with hostname
        # anti-affinity the map holds one entry PER NODE and the scan
        # made Filter O(nodes) per node (measured: the NSSelector
        # workload spent its entire wall in that loop)
        self.anti_keys: tuple = ()


def _topo(node, key: str) -> str | None:
    return meta.labels(node).get(key)


def _count_existing_anti(pod_info: PodInfo, nodes: list[NodeInfo],
                         ns_labels=None) -> TPCounts:
    """getExistingAntiAffinityCounts (:155): existing pods whose required
    anti-affinity matches the incoming pod, keyed by their node's topology."""
    counts: TPCounts = {}
    for ni in nodes:
        if ni.node is None:
            continue
        for pi in ni.pods_with_required_anti_affinity:
            for term in pi.required_anti_affinity_terms:
                val = _topo(ni.node, term.topology_key)
                if val is None:
                    continue
                if term.matches(pod_info.pod, pod_info.labels, ns_labels):
                    counts[(term.topology_key, val)] = \
                        counts.get((term.topology_key, val), 0) + 1
    return counts


def _count_incoming(pod_info: PodInfo, nodes: list[NodeInfo],
                    ns_labels=None) -> tuple[list[TPCounts], TPCounts]:
    """getIncomingAffinityAntiAffinityCounts (:187)."""
    affinity = [dict() for _ in pod_info.required_affinity_terms]
    anti: TPCounts = {}
    if not pod_info.required_affinity_terms and not pod_info.required_anti_affinity_terms:
        return affinity, anti
    for ni in nodes:
        if ni.node is None:
            continue
        for pi in ni.pods:
            for i, term in enumerate(pod_info.required_affinity_terms):
                if term.matches(pi.pod, pi.labels, ns_labels):
                    val = _topo(ni.node, term.topology_key)
                    if val is not None:
                        affinity[i][(term.topology_key, val)] = \
                            affinity[i].get((term.topology_key, val), 0) + 1
            for term in pod_info.required_anti_affinity_terms:
                if term.matches(pi.pod, pi.labels, ns_labels):
                    val = _topo(ni.node, term.topology_key)
                    if val is not None:
                        anti[(term.topology_key, val)] = \
                            anti.get((term.topology_key, val), 0) + 1
    return affinity, anti


class InterPodAffinity(PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin):
    name = "InterPodAffinity"

    def __init__(self, handle=None):
        self._handle = handle

    def _ns_labels(self) -> dict | None:
        """A FRESH namespace-label snapshot (reference:
        GetNamespaceLabelsSnapshot per scheduling cycle — a TTL cache
        was tried and could resolve a just-relabeled namespace stale,
        letting a binding violate required anti-affinity; the store
        list is a cheap local read)."""
        if self._handle is None or self._handle.client is None:
            return None
        try:
            items, _rv = self._handle.client.list("namespaces", None)
        except Exception:  # noqa: BLE001 - no namespace store
            return None
        return {meta.name(o): (o["metadata"].get("labels") or {})
                for o in items}

    @staticmethod
    def _any_ns_selector(pod_info: PodInfo, nodes,
                         scoring: bool = False) -> bool:
        """Does anything in this cycle need namespace resolution?  O(1)
        per pod via the precomputed PodInfo flag; the node scan checks
        one bool per anti pod (and, for scoring, per affinity-carrying
        pod — existing pods' PREFERRED ns-selector terms score too)."""
        if pod_info.has_ns_selector_terms:
            return True
        if any(pi.has_ns_selector_terms
               for ni in nodes
               for pi in ni.pods_with_required_anti_affinity):
            return True
        if scoring:
            return any(pi.has_ns_selector_terms
                       for ni in nodes
                       for pi in ni.pods_with_affinity)
        return False

    def events_to_register(self):
        return [ClusterEvent("Pod", "*"), ClusterEvent("AssignedPod", "*"),
                ClusterEvent("Node", "Add"), ClusterEvent("Node", "Update")]

    # -- filtering -------------------------------------------------------

    def pre_filter(self, state: CycleState, pod_info: PodInfo, snapshot):
        st = _PreFilterState()
        st.pod_info = pod_info
        have_anti_nodes = snapshot.have_pods_with_required_anti_affinity_list
        ns_labels = (self._ns_labels()
                     if self._any_ns_selector(pod_info, have_anti_nodes)
                     else None)
        st.ns_labels = ns_labels
        st.existing_anti = _count_existing_anti(pod_info, have_anti_nodes,
                                                ns_labels)
        st.anti_keys = tuple({k for (k, _v) in st.existing_anti})
        if pod_info.required_affinity_terms or pod_info.required_anti_affinity_terms:
            # reference scans allNodes here (filtering.go:187) — the incoming
            # pod's terms match against every existing pod, affine or not
            st.affinity_counts, st.anti_affinity_counts = _count_incoming(
                pod_info, snapshot.list(), ns_labels)
        if (not st.existing_anti and not pod_info.required_affinity_terms
                and not pod_info.required_anti_affinity_terms):
            return None, Status(SKIP)
        state.write(_STATE_KEY, st)
        return None, None

    def add_pod(self, state, pod_info, to_add: PodInfo, node_info: NodeInfo):
        self._update(state, pod_info, to_add, node_info, +1)
        return None

    def remove_pod(self, state, pod_info, to_remove: PodInfo, node_info: NodeInfo):
        self._update(state, pod_info, to_remove, node_info, -1)
        return None

    def _update(self, state, pod_info: PodInfo, other: PodInfo,
                node_info: NodeInfo, delta: int) -> None:
        st: _PreFilterState | None = state.read(_STATE_KEY)
        if st is None or node_info.node is None:
            return
        node = node_info.node
        ns_labels = st.ns_labels
        for term in other.required_anti_affinity_terms:
            if term.matches(pod_info.pod, pod_info.labels, ns_labels):
                val = _topo(node, term.topology_key)
                if val is not None:
                    k = (term.topology_key, val)
                    st.existing_anti[k] = st.existing_anti.get(k, 0) + delta
                    if term.topology_key not in st.anti_keys:
                        st.anti_keys = st.anti_keys + (term.topology_key,)
        for i, term in enumerate(pod_info.required_affinity_terms):
            if term.matches(other.pod, other.labels, ns_labels):
                val = _topo(node, term.topology_key)
                if val is not None:
                    k = (term.topology_key, val)
                    st.affinity_counts[i][k] = st.affinity_counts[i].get(k, 0) + delta
        for term in pod_info.required_anti_affinity_terms:
            if term.matches(other.pod, other.labels, ns_labels):
                val = _topo(node, term.topology_key)
                if val is not None:
                    k = (term.topology_key, val)
                    st.anti_affinity_counts[k] = st.anti_affinity_counts.get(k, 0) + delta

    def filter(self, state: CycleState, pod_info: PodInfo,
               node_info: NodeInfo) -> Status | None:
        st: _PreFilterState | None = state.read(_STATE_KEY)
        if st is None:
            return None
        node = node_info.node

        # (1) existing pods' required anti-affinity must not match incoming
        # — one lookup per distinct topology key (filtering.go:367 indexes
        # by topologyPair the same way)
        for key in st.anti_keys:
            val = _topo(node, key)
            if val is not None and st.existing_anti.get((key, val), 0) > 0:
                return Status(UNSCHEDULABLE,
                              "node(s) had pods with anti-affinity rules "
                              "matching the incoming pod")

        # (3) incoming pod's anti-affinity must find no match in node's domains
        for term in pod_info.required_anti_affinity_terms:
            val = _topo(node, term.topology_key)
            if val is not None and st.anti_affinity_counts.get(
                    (term.topology_key, val), 0) > 0:
                return Status(UNSCHEDULABLE,
                              "node(s) didn't satisfy pod anti-affinity rules")

        # (2) every incoming affinity term must match in node's domain
        if pod_info.required_affinity_terms:
            all_match = True
            for i, term in enumerate(pod_info.required_affinity_terms):
                val = _topo(node, term.topology_key)
                if val is None or st.affinity_counts[i].get(
                        (term.topology_key, val), 0) <= 0:
                    all_match = False
                    break
            if not all_match:
                # bootstrap exception (filtering.go:439): if NO pod anywhere
                # matches any term but the pod matches its own terms, allow it
                # so the first pod of a self-affine group can schedule.
                cluster_empty = all(
                    sum(c.values()) == 0 for c in st.affinity_counts)
                self_match = all(
                    term.matches(pod_info.pod, pod_info.labels,
                                 st.ns_labels)
                    for term in pod_info.required_affinity_terms)
                if not (cluster_empty and self_match):
                    return Status(UNSCHEDULABLE,
                                  "node(s) didn't satisfy pod affinity rules")
        return None

    # -- scoring (scoring.go) -------------------------------------------

    def pre_score(self, state: CycleState, pod_info: PodInfo, nodes):
        has_preferred = bool(pod_info.preferred_affinity_terms
                             or pod_info.preferred_anti_affinity_terms)
        # existing pods' preferred terms toward the incoming pod also score
        scores: dict[str, int] = {}
        any_term = has_preferred
        if not any_term:
            # check existing pods for preferred terms (hasPreferredAffinityConstraints)
            any_term = any(pi.preferred_affinity_terms or pi.preferred_anti_affinity_terms
                           for ni in nodes for pi in ni.pods_with_affinity)
        if not any_term:
            return Status(SKIP)
        ns_labels = (self._ns_labels()
                     if self._any_ns_selector(pod_info, nodes,
                                              scoring=True) else None)
        counts: TPCounts = {}

        def bump(term: AffinityTerm, node, w: int) -> None:
            val = _topo(node, term.topology_key)
            if val is not None:
                counts[(term.topology_key, val)] = \
                    counts.get((term.topology_key, val), 0) + w

        for ni in nodes:
            if ni.node is None:
                continue
            for pi in ni.pods:
                # incoming pod's preferred (anti-)affinity vs existing pod
                for term in pod_info.preferred_affinity_terms:
                    if term.matches(pi.pod, pi.labels, ns_labels):
                        bump(term, ni.node, term.weight)
                for term in pod_info.preferred_anti_affinity_terms:
                    if term.matches(pi.pod, pi.labels, ns_labels):
                        bump(term, ni.node, -term.weight)
                # existing pod's preferred (anti-)affinity vs incoming pod
                for term in pi.preferred_affinity_terms:
                    if term.matches(pod_info.pod, pod_info.labels,
                                    ns_labels):
                        bump(term, ni.node, term.weight)
                for term in pi.preferred_anti_affinity_terms:
                    if term.matches(pod_info.pod, pod_info.labels,
                                    ns_labels):
                        bump(term, ni.node, -term.weight)
        state.write(_SCORE_STATE_KEY, counts)
        return None

    def score(self, state: CycleState, pod_info: PodInfo,
              node_info: NodeInfo) -> tuple[int, Status | None]:
        counts: TPCounts | None = state.read(_SCORE_STATE_KEY)
        if not counts:
            return 0, None
        node = node_info.node
        total = 0
        for (key, val), w in counts.items():
            if _topo(node, key) == val:
                total += w
        return total, None

    def normalize_scores(self, state, pod_info, scores):
        if not scores:
            return None
        mx, mn = max(scores.values()), min(scores.values())
        spread = mx - mn
        for k in scores:
            scores[k] = (MAX_NODE_SCORE * (scores[k] - mn) // spread
                         if spread else 0)
        return None
