"""Simple per-node plugins: NodeName, NodePorts, NodeUnschedulable,
NodeAffinity, TaintToleration, ImageLocality.

Reference: pkg/scheduler/framework/plugins/{nodename,nodeports,
nodeunschedulable,nodeaffinity,tainttoleration,imagelocality}/
"""

from __future__ import annotations

from ...api import meta
from ..framework import (
    MAX_NODE_SCORE, CycleState, FilterPlugin, PreFilterPlugin, PreFilterResult,
    PreScorePlugin, ScorePlugin,
)
from ..types import (
    SKIP, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE,
    ClusterEvent, NodeInfo, PodInfo, Status, node_selector_terms_match,
)


class NodeName(FilterPlugin):
    """nodename/node_name.go — .spec.nodeName must equal the node, if set."""

    name = "NodeName"

    def filter(self, state, pod_info, node_info):
        want = (pod_info.pod.get("spec") or {}).get("nodeName")
        if want and want != node_info.name:
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE, "node didn't match Spec.NodeName")
        return None


class NodePorts(PreFilterPlugin, FilterPlugin):
    """nodeports/node_ports.go — requested host ports must be free."""

    name = "NodePorts"

    def pre_filter(self, state, pod_info, snapshot):
        if not pod_info.host_ports:
            return None, Status(SKIP)
        return None, None

    def filter(self, state, pod_info, node_info):
        for proto, ip, port in pod_info.host_ports:
            for uproto, uip, uport in node_info.used_ports:
                if port == uport and proto == uproto and (
                        ip == "0.0.0.0" or uip == "0.0.0.0" or ip == uip):
                    return Status(UNSCHEDULABLE, "node(s) didn't have free ports")
        return None


class NodeUnschedulable(FilterPlugin):
    """nodeunschedulable/node_unschedulable.go — .spec.unschedulable nodes
    only admit pods tolerating the unschedulable taint."""

    name = "NodeUnschedulable"

    def filter(self, state, pod_info, node_info):
        node = node_info.node
        if node and (node.get("spec") or {}).get("unschedulable"):
            tolerated = any(
                t.get("key") == "node.kubernetes.io/unschedulable"
                and t.get("effect") in (None, "", "NoSchedule")
                for t in pod_info.tolerations)
            if not tolerated:
                return Status(UNSCHEDULABLE_AND_UNRESOLVABLE,
                              "node(s) were unschedulable")
        return None


class NodeAffinity(FilterPlugin, PreScorePlugin, ScorePlugin):
    """nodeaffinity/node_affinity.go — nodeSelector + node affinity terms.

    Filter: .spec.nodeSelector labels must all match AND required node
    affinity terms (OR over terms) must match.
    Score: sum of weights of matching preferred terms, normalized.
    """

    name = "NodeAffinity"

    def events_to_register(self):
        return [ClusterEvent("Node", "Add"), ClusterEvent("Node", "Update")]

    def filter(self, state, pod_info, node_info):
        node = node_info.node
        labels = meta.labels(node)
        for k, v in pod_info.node_selector.items():
            if labels.get(k) != v:
                return Status(UNSCHEDULABLE_AND_UNRESOLVABLE,
                              "node(s) didn't match Pod's node affinity/selector")
        if not node_selector_terms_match(pod_info.node_affinity_required, node):
            return Status(UNSCHEDULABLE_AND_UNRESOLVABLE,
                          "node(s) didn't match Pod's node affinity/selector")
        return None

    def pre_score(self, state, pod_info, nodes):
        if not pod_info.node_affinity_preferred:
            return Status(SKIP)
        return None

    def score(self, state, pod_info, node_info):
        total = 0
        for weight, (lab, fields) in pod_info.node_affinity_preferred:
            node_labels = meta.labels(node_info.node)
            node_fields = {"metadata.name": node_info.name}
            if lab.matches(node_labels) and fields.matches(node_fields):
                total += weight
        return total, None

    def normalize_scores(self, state, pod_info, scores):
        mx = max(scores.values(), default=0)
        if mx > 0:
            for k in scores:
                scores[k] = scores[k] * MAX_NODE_SCORE // mx
        return None


def toleration_tolerates_taint(tol: dict, taint: dict) -> bool:
    """v1 helper ToleratesTaint (apimachinery/../v1/toleration.go)."""
    if tol.get("effect") and tol["effect"] != taint.get("effect"):
        return False
    if tol.get("key") and tol["key"] != taint.get("key"):
        return False
    op = tol.get("operator", "Equal")
    if op == "Exists":
        return True
    return tol.get("value", "") == taint.get("value", "")


def find_untolerated_taint(taints: list[dict], tolerations: list[dict],
                           effects: tuple[str, ...]) -> dict | None:
    for taint in taints:
        if taint.get("effect") not in effects:
            continue
        if not any(toleration_tolerates_taint(t, taint) for t in tolerations):
            return taint
    return None


class TaintToleration(FilterPlugin, PreScorePlugin, ScorePlugin):
    """tainttoleration/taint_toleration.go — Filter on NoSchedule/NoExecute;
    Score counts intolerable PreferNoSchedule taints (fewer = better)."""

    name = "TaintToleration"

    def events_to_register(self):
        return [ClusterEvent("Node", "Add"), ClusterEvent("Node", "Update")]

    def filter(self, state, pod_info, node_info):
        taints = (node_info.node.get("spec") or {}).get("taints") or []
        taint = find_untolerated_taint(taints, pod_info.tolerations,
                                       ("NoSchedule", "NoExecute"))
        if taint is not None:
            return Status(
                UNSCHEDULABLE_AND_UNRESOLVABLE,
                f"node(s) had untolerated taint {{{taint.get('key')}: "
                f"{taint.get('value', '')}}}")
        return None

    def pre_score(self, state, pod_info, nodes):
        return None

    def score(self, state, pod_info, node_info):
        taints = (node_info.node.get("spec") or {}).get("taints") or []
        count = sum(
            1 for t in taints
            if t.get("effect") == "PreferNoSchedule"
            and not any(toleration_tolerates_taint(tol, t)
                        for tol in pod_info.tolerations))
        return count, None

    def normalize_scores(self, state, pod_info, scores):
        # fewer intolerable taints -> higher score (reverse + scale)
        mx = max(scores.values(), default=0)
        for k in scores:
            scores[k] = ((mx - scores[k]) * MAX_NODE_SCORE // mx) if mx else MAX_NODE_SCORE
        return None


# imagelocality/image_locality.go thresholds
_MIN_THRESHOLD = 23 * 1024 * 1024
_MAX_CONTAINER_THRESHOLD = 1024 * 1024 * 1024


class ImageLocality(ScorePlugin):
    """imagelocality/image_locality.go — prefer nodes that already have the
    pod's images, scaled by how widely each image is spread."""

    name = "ImageLocality"

    def __init__(self, total_nodes_getter=None):
        self._total_nodes = total_nodes_getter or (lambda: 1)

    def score(self, state, pod_info, node_info):
        containers = (pod_info.pod.get("spec") or {}).get("containers") or []
        if not containers:
            return 0, None
        total_nodes = max(self._total_nodes(), 1)
        sum_scores = 0.0
        for c in containers:
            img = c.get("image", "")
            size = node_info.image_sizes.get(img, 0)
            if size:
                # spread factor omitted node-count bookkeeping: approximate 1
                sum_scores += size
        max_threshold = _MAX_CONTAINER_THRESHOLD * len(containers)
        if sum_scores < _MIN_THRESHOLD:
            return 0, None
        score = int((min(sum_scores, max_threshold) - _MIN_THRESHOLD) * MAX_NODE_SCORE
                    / (max_threshold - _MIN_THRESHOLD))
        return score, None
