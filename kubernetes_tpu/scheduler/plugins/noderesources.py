"""NodeResourcesFit + NodeResourcesBalancedAllocation.

Reference: pkg/scheduler/framework/plugins/noderesources/
  fit.go:160 computePodResourceRequest (done in api/resources.py)
  fit.go:253-335 fitsRequest: pod count, CPU, memory, ephemeral storage and
    scalar resources checked against Allocatable - Requested
  least_allocated.go / most_allocated.go / requested_to_capacity_ratio.go
    score strategies
  balanced_allocation.go: std-dev of per-resource utilization

These are pure arithmetic over NodeInfo aggregates — exactly what the TPU
path turns into one broadcast compare / ratio matmul (ops/predicates.py).
"""

from __future__ import annotations

from ...api.resources import Resource
from ..framework import (
    MAX_NODE_SCORE, CycleState, FilterPlugin, PreFilterPlugin, PreFilterResult,
    ScorePlugin,
)
from ..types import (
    UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE,
    ClusterEvent, NodeInfo, PodInfo, Status,
)

_STATE_KEY = "PreFilterNodeResourcesFit"

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
REQUESTED_TO_CAPACITY_RATIO = "RequestedToCapacityRatio"


def insufficient_resources(pod_info: PodInfo, node_info: NodeInfo) -> list[str]:
    """fitsRequest (fit.go:253): returns list of insufficient resource names."""
    out: list[str] = []
    if len(node_info.pods) + 1 > node_info.allocatable.allowed_pod_number:
        out.append("Too many pods")
    req = pod_info.request
    if (req.milli_cpu == 0 and req.memory == 0 and req.ephemeral_storage == 0
            and not req.scalar):
        return out
    alloc, used = node_info.allocatable, node_info.requested
    if req.milli_cpu > alloc.milli_cpu - used.milli_cpu:
        out.append("Insufficient cpu")
    if req.memory > alloc.memory - used.memory:
        out.append("Insufficient memory")
    if req.ephemeral_storage > alloc.ephemeral_storage - used.ephemeral_storage:
        out.append("Insufficient ephemeral-storage")
    for name, v in req.scalar.items():
        if v > alloc.scalar.get(name, 0) - used.scalar.get(name, 0):
            out.append(f"Insufficient {name}")
    return out


class NodeResourcesFit(PreFilterPlugin, FilterPlugin, ScorePlugin):
    name = "NodeResourcesFit"

    def __init__(self, strategy: str = LEAST_ALLOCATED,
                 resource_weights: dict[str, int] | None = None,
                 shape: list[tuple[float, float]] | None = None):
        self.strategy = strategy
        # utilization shape points for RequestedToCapacityRatio:
        # [(utilization 0..1, score 0..MAX)], linear interpolation
        self.shape = shape or [(0.0, 0.0), (1.0, float(MAX_NODE_SCORE))]
        self.resource_weights = resource_weights or {"cpu": 1, "memory": 1}

    def events_to_register(self):
        return [ClusterEvent("Pod", "Delete"), ClusterEvent("Node", "Add"),
                ClusterEvent("Node", "Update")]

    def pre_filter(self, state: CycleState, pod_info: PodInfo, snapshot):
        state.write(_STATE_KEY, pod_info.request)
        return None, None

    def filter(self, state: CycleState, pod_info: PodInfo,
               node_info: NodeInfo) -> Status | None:
        missing = insufficient_resources(pod_info, node_info)
        if missing:
            return Status(UNSCHEDULABLE, *missing)
        return None

    # -- scoring ---------------------------------------------------------

    def _utilizations(self, pod_info: PodInfo, node_info: NodeInfo) -> list[tuple[float, int]]:
        """[(requested_fraction, weight)] per resource, after placing the pod."""
        req = pod_info.request_nonzero
        alloc, used = node_info.allocatable, node_info.non_zero_requested
        out: list[tuple[float, int]] = []
        for rname, w in self.resource_weights.items():
            if rname == "cpu":
                want, have = used.milli_cpu + req.milli_cpu, alloc.milli_cpu
            elif rname == "memory":
                want, have = used.memory + req.memory, alloc.memory
            elif rname == "ephemeral-storage":
                want, have = (used.ephemeral_storage + req.ephemeral_storage,
                              alloc.ephemeral_storage)
            else:
                want = used.scalar.get(rname, 0) + req.scalar.get(rname, 0)
                have = alloc.scalar.get(rname, 0)
            out.append((min(want / have, 1.0) if have > 0 else 1.0, w))
        return out

    def score(self, state: CycleState, pod_info: PodInfo,
              node_info: NodeInfo) -> tuple[int, Status | None]:
        utils = self._utilizations(pod_info, node_info)
        total_w = sum(w for _, w in utils) or 1
        if self.strategy == LEAST_ALLOCATED:
            # least_allocated.go:29 — score = sum_r w_r * (1-util) * 100 / sum_w
            s = sum(w * (1.0 - u) * MAX_NODE_SCORE for u, w in utils) / total_w
        elif self.strategy == MOST_ALLOCATED:
            s = sum(w * u * MAX_NODE_SCORE for u, w in utils) / total_w
        else:  # RequestedToCapacityRatio: piecewise-linear shape per resource
            s = sum(w * self._shape_score(u) for u, w in utils) / total_w
        return int(s), None

    def _shape_score(self, util: float) -> float:
        pts = self.shape
        if util <= pts[0][0]:
            return pts[0][1]
        for (x0, y0), (x1, y1) in zip(pts, pts[1:]):
            if util <= x1:
                if x1 == x0:
                    return y1
                return y0 + (y1 - y0) * (util - x0) / (x1 - x0)
        return pts[-1][1]


class NodeResourcesBalancedAllocation(ScorePlugin):
    """balanced_allocation.go — favors nodes where per-resource utilization
    is balanced: score = (1 - std(utilizations)) * 100."""

    name = "NodeResourcesBalancedAllocation"

    def __init__(self, resources: list[str] | None = None):
        self.resources = resources or ["cpu", "memory"]

    def score(self, state: CycleState, pod_info: PodInfo,
              node_info: NodeInfo) -> tuple[int, Status | None]:
        req = pod_info.request_nonzero
        alloc, used = node_info.allocatable, node_info.non_zero_requested
        utils: list[float] = []
        for rname in self.resources:
            if rname == "cpu":
                want, have = used.milli_cpu + req.milli_cpu, alloc.milli_cpu
            elif rname == "memory":
                want, have = used.memory + req.memory, alloc.memory
            else:
                want = used.scalar.get(rname, 0) + req.scalar.get(rname, 0)
                have = alloc.scalar.get(rname, 0)
            utils.append(min(want / have, 1.0) if have > 0 else 1.0)
        mean = sum(utils) / len(utils)
        var = sum((u - mean) ** 2 for u in utils) / len(utils)
        return int((1.0 - var ** 0.5) * MAX_NODE_SCORE), None
