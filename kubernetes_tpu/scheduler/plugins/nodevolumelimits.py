"""NodeVolumeLimits — attachable-volume count limits per node.

Reference: pkg/scheduler/framework/plugins/nodevolumelimits/ (977 LoC;
csi.go is the modern path, non_csi.go the legacy EBS/GCE-PD/AzureDisk
filters).  Semantics reproduced (CSI path, which the legacy plugins migrate
to via CSINode):
  * per-driver limits come from the node's CSINode object
    (csinode.spec.drivers[].allocatable.count, csi.go getVolumeLimits);
    without a CSINode entry the driver is uncounted (no limit known).
  * the filter counts unique volumes already attached (existing pods'
    PVC-backed volumes, resolved to their driver) plus the incoming pod's
    new unique volumes, and rejects when any driver would exceed its limit
    (csi.go Filter).
  * legacy in-tree volume types count against well-known defaults when no
    CSINode is present (non_csi.go: EBS 39, GCE-PD 16, AzureDisk 16).
"""

from __future__ import annotations

from ...api import meta
from ...client.clientset import CSINODES, PVCS, PVS
from ..framework import FilterPlugin, PreFilterPlugin
from ..types import SKIP, UNSCHEDULABLE, ClusterEvent, Status
from .volumebinding import pod_pvc_names

LEGACY_LIMITS = {  # non_csi.go default limits
    "kubernetes.io/aws-ebs": 39,
    "kubernetes.io/gce-pd": 16,
    "kubernetes.io/azure-disk": 16,
}


def _inline_driver(v: dict) -> tuple[str, str] | None:
    """(driver, unique volume handle) for inline in-tree volumes."""
    if v.get("awsElasticBlockStore"):
        return "kubernetes.io/aws-ebs", v["awsElasticBlockStore"].get("volumeID")
    if v.get("gcePersistentDisk"):
        return "kubernetes.io/gce-pd", v["gcePersistentDisk"].get("pdName")
    if v.get("azureDisk"):
        return "kubernetes.io/azure-disk", v["azureDisk"].get("diskName")
    if v.get("csi"):
        return v["csi"].get("driver"), v["csi"].get("volumeHandle")
    return None


class NodeVolumeLimits(PreFilterPlugin, FilterPlugin):
    name = "NodeVolumeLimits"

    def __init__(self, informer_factory=None):
        self.factory = informer_factory

    def events_to_register(self):
        return [ClusterEvent("CSINode", "*"), ClusterEvent("Pod", "Delete"),
                ClusterEvent("PersistentVolumeClaim", "*")]

    def _pod_volumes(self, pod: dict) -> set[tuple[str, str]]:
        """Unique (driver, handle) pairs a pod attaches."""
        out: set[tuple[str, str]] = set()
        ns = meta.namespace(pod)
        for v in (pod.get("spec") or {}).get("volumes") or ():
            inline = _inline_driver(v)
            if inline and inline[1]:
                out.add(inline)
                continue
            claim = (v.get("persistentVolumeClaim") or {}).get("claimName")
            if not claim or self.factory is None:
                continue
            pvc = self.factory.informer(PVCS).get(ns, claim)
            pv_name = ((pvc or {}).get("spec") or {}).get("volumeName")
            pv = self.factory.informer(PVS).get("", pv_name) if pv_name else None
            if pv is None:
                continue
            spec = pv.get("spec") or {}
            if spec.get("csi"):
                out.add((spec["csi"].get("driver"),
                         spec["csi"].get("volumeHandle") or pv_name))
            else:
                for key in ("awsElasticBlockStore", "gcePersistentDisk",
                            "azureDisk"):
                    inline = _inline_driver({key: spec.get(key)}) \
                        if spec.get(key) else None
                    if inline and inline[1]:
                        out.add(inline)
        return out

    def _limits_for(self, node_name: str) -> dict[str, int]:
        """driver -> attachable count (CSINode allocatable, else legacy)."""
        limits = dict(LEGACY_LIMITS)
        if self.factory is not None:
            csinode = self.factory.informer(CSINODES).get("", node_name)
            for d in ((csinode or {}).get("spec") or {}).get("drivers") or ():
                count = (d.get("allocatable") or {}).get("count")
                if count is not None:
                    limits[d.get("name")] = int(count)
        return limits

    def pre_filter(self, state, pod_info, snapshot):
        if not self._pod_volumes(pod_info.pod) and \
                not pod_pvc_names(pod_info.pod):
            return None, Status(SKIP)
        return None, None

    def filter(self, state, pod_info, node_info):
        new_vols = self._pod_volumes(pod_info.pod)
        if not new_vols:
            return None
        limits = self._limits_for(node_info.name)
        if not limits:
            return None
        attached: dict[str, set[str]] = {}
        for pi in node_info.pods:
            for driver, handle in self._pod_volumes(pi.pod):
                attached.setdefault(driver, set()).add(handle)
        for driver, handle in new_vols:
            attached.setdefault(driver, set()).add(handle)
        for driver, handles in attached.items():
            limit = limits.get(driver)
            if limit is not None and len(handles) > limit:
                return Status(UNSCHEDULABLE,
                              "node(s) exceed max volume count")
        return None
