"""PodTopologySpread plugin.

Reference: pkg/scheduler/framework/plugins/podtopologyspread/
  filtering.go:40-51  preFilterState: per-constraint TpPairToMatchNum +
    per-topology-key minimum match (the "critical paths" 2-min trick at
    :109-118 lets AddPod/RemovePod updates avoid full rescans; we keep the
    plain min and recompute on mutation — same semantics, simpler)
  filtering.go:238 calPreFilterState; :334 Filter:
    matchNum + selfMatch - minMatch  must be <= maxSkew
  scoring.go:195 Score + :231 NormalizeScore for ScheduleAnyway constraints

On the TPU path these per-(key,value) match counts are segment-sums over the
node axis (ops/predicates.py topology_spread_*).
"""

from __future__ import annotations

from ...api import meta
from ...api.labels import Selector, selector_from_dict
from ..framework import (
    MAX_NODE_SCORE, CycleState, FilterPlugin, PreFilterPlugin, PreScorePlugin,
    ScorePlugin,
)
from ..types import (
    SKIP, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE,
    ClusterEvent, NodeInfo, PodInfo, Status, node_selector_terms_match,
)

_STATE_KEY = "PreFilterPodTopologySpread"
_SCORE_STATE_KEY = "PreScorePodTopologySpread"

DO_NOT_SCHEDULE = "DoNotSchedule"
SCHEDULE_ANYWAY = "ScheduleAnyway"


class _Constraint:
    __slots__ = ("max_skew", "topology_key", "when_unsatisfiable", "selector",
                 "min_domains")

    def __init__(self, c: dict, default_ns: str):
        self.max_skew = c.get("maxSkew", 1)
        self.topology_key = c["topologyKey"]
        self.when_unsatisfiable = c.get("whenUnsatisfiable", DO_NOT_SCHEDULE)
        self.selector = selector_from_dict(c.get("labelSelector"))
        self.min_domains = c.get("minDomains")


def _compile(pod_info: PodInfo, action: str) -> list[_Constraint]:
    ns = meta.namespace(pod_info.pod)
    return [_Constraint(c, ns) for c in pod_info.topology_spread_constraints
            if c.get("whenUnsatisfiable", DO_NOT_SCHEDULE) == action]


def _node_matches_pod_node_affinity(pod_info: PodInfo, node) -> bool:
    """Spread counts only nodes the pod could land on per nodeSelector/affinity
    (filtering.go:261 nodeLabelsMatchSpreadConstraints precondition)."""
    labels = meta.labels(node)
    for k, v in pod_info.node_selector.items():
        if labels.get(k) != v:
            return False
    return node_selector_terms_match(pod_info.node_affinity_required, node)


class _PreFilterState:
    __slots__ = ("constraints", "tp_pair_to_match_num", "tp_key_min_match")

    def __init__(self) -> None:
        self.constraints: list[_Constraint] = []
        # (topologyKey, value) -> count of matching pods in that domain
        self.tp_pair_to_match_num: dict[tuple[str, str], int] = {}
        # topologyKey -> min match count across domains
        self.tp_key_min_match: dict[str, int] = {}


def _cal_state(pod_info: PodInfo, nodes: list[NodeInfo],
               constraints: list[_Constraint]) -> _PreFilterState:
    st = _PreFilterState()
    st.constraints = constraints
    ns = meta.namespace(pod_info.pod)
    for c in constraints:
        domains: dict[str, int] = {}
        for ni in nodes:
            node = ni.node
            if node is None:
                continue
            labels = meta.labels(node)
            if c.topology_key not in labels:
                continue
            if not _node_matches_pod_node_affinity(pod_info, node):
                continue
            val = labels[c.topology_key]
            count = domains.get(val, 0)
            for pi in ni.pods:
                if (meta.namespace(pi.pod) == ns and not meta.deletion_timestamp(pi.pod)
                        and c.selector.matches(pi.labels)):
                    count += 1
            domains[val] = count
        for val, count in domains.items():
            st.tp_pair_to_match_num[(c.topology_key, val)] = count
        if domains:
            st.tp_key_min_match[c.topology_key] = min(domains.values())
    return st


class PodTopologySpread(PreFilterPlugin, FilterPlugin, PreScorePlugin, ScorePlugin):
    name = "PodTopologySpread"

    def events_to_register(self):
        return [ClusterEvent("Pod", "*"), ClusterEvent("Node", "Add"),
                ClusterEvent("Node", "Update")]

    # -- filtering -------------------------------------------------------

    def pre_filter(self, state: CycleState, pod_info: PodInfo, snapshot):
        constraints = _compile(pod_info, DO_NOT_SCHEDULE)
        if not constraints:
            return None, Status(SKIP)
        st = _cal_state(pod_info, snapshot.list(), constraints)
        state.write(_STATE_KEY, st)
        return None, None

    def add_pod(self, state, pod_info, to_add: PodInfo, node_info: NodeInfo):
        self._update(state, pod_info, to_add, node_info, +1)
        return None

    def remove_pod(self, state, pod_info, to_remove: PodInfo, node_info: NodeInfo):
        self._update(state, pod_info, to_remove, node_info, -1)
        return None

    def _update(self, state, pod_info, other: PodInfo, node_info: NodeInfo,
                delta: int) -> None:
        st: _PreFilterState | None = state.read(_STATE_KEY)
        if st is None or node_info.node is None:
            return
        ns = meta.namespace(pod_info.pod)
        if meta.namespace(other.pod) != ns:
            return
        labels = meta.labels(node_info.node)
        for c in st.constraints:
            val = labels.get(c.topology_key)
            if val is None or not c.selector.matches(other.labels):
                continue
            pair = (c.topology_key, val)
            st.tp_pair_to_match_num[pair] = st.tp_pair_to_match_num.get(pair, 0) + delta
            # recompute min for the key (reference keeps 2 critical paths;
            # recompute is O(domains) and semantically identical)
            vals = [v for (k, _), v in st.tp_pair_to_match_num.items()
                    if k == c.topology_key]
            if vals:
                st.tp_key_min_match[c.topology_key] = min(vals)

    def filter(self, state: CycleState, pod_info: PodInfo,
               node_info: NodeInfo) -> Status | None:
        st: _PreFilterState | None = state.read(_STATE_KEY)
        if st is None:
            return None
        node = node_info.node
        labels = meta.labels(node)
        for c in st.constraints:
            val = labels.get(c.topology_key)
            if val is None:
                return Status(UNSCHEDULABLE_AND_UNRESOLVABLE,
                              "node(s) didn't match pod topology spread constraints "
                              "(missing required label)")
            self_match = 1 if c.selector.matches(pod_info.labels) else 0
            match_num = st.tp_pair_to_match_num.get((c.topology_key, val), 0)
            min_match = st.tp_key_min_match.get(c.topology_key, 0)
            if match_num + self_match - min_match > c.max_skew:
                return Status(UNSCHEDULABLE,
                              "node(s) didn't match pod topology spread constraints")
        return None

    # -- scoring (scoring.go) -------------------------------------------

    def pre_score(self, state: CycleState, pod_info: PodInfo, nodes):
        constraints = _compile(pod_info, SCHEDULE_ANYWAY)
        if not constraints:
            return Status(SKIP)
        st = _cal_state(pod_info, nodes, constraints)
        state.write(_SCORE_STATE_KEY, st)
        return None

    def score(self, state: CycleState, pod_info: PodInfo,
              node_info: NodeInfo) -> tuple[int, Status | None]:
        st: _PreFilterState | None = state.read(_SCORE_STATE_KEY)
        if st is None:
            return 0, None
        labels = meta.labels(node_info.node)
        total = 0
        for c in st.constraints:
            val = labels.get(c.topology_key)
            if val is None:
                continue
            total += st.tp_pair_to_match_num.get((c.topology_key, val), 0)
        return total, None

    def normalize_scores(self, state, pod_info, scores):
        # scoring.go:231 — fewer matching pods in the node's domains = better
        if not scores:
            return None
        mx, mn = max(scores.values()), min(scores.values())
        spread = mx - mn
        for k in scores:
            scores[k] = (MAX_NODE_SCORE * (mx - scores[k]) // spread
                         if spread else MAX_NODE_SCORE)
        return None
