"""PrioritySort queue-sort plugin.

Reference: pkg/scheduler/framework/plugins/queuesort/priority_sort.go —
higher .spec.priority first, earlier queue-entry time breaks ties.
"""

from __future__ import annotations

from ..framework import QueueSortPlugin
from ..types import QueuedPodInfo


class PrioritySort(QueueSortPlugin):
    name = "PrioritySort"
    # marker for SchedulingQueue: this sort is exactly priority-then-FIFO,
    # so the O(1) bucket queue implements it (queue.py _BucketQueue)
    priority_fifo = True

    def sort_key(self, qpi: QueuedPodInfo) -> tuple:
        return (-qpi.pod_info.priority, qpi.timestamp)
