"""SelectorSpread — spread pods of a service/controller across nodes/zones.

Reference: pkg/scheduler/framework/plugins/selectorspread/ (234 LoC,
non-default since PodTopologySpread subsumed it, but still registered):
  * PreScore collects the label selectors of every Service, ReplicaSet,
    ReplicationController and StatefulSet that selects the incoming pod
    (selector_spread.go PreScore via helper.DefaultSelector).
  * Score counts existing pods on the node matched by ANY of those
    selectors (selector_spread.go Score).
  * NormalizeScore inverts counts to favor emptier nodes and blends in a
    zone-level count with a 2/3 zone weight when nodes carry zone labels
    (selector_spread.go NormalizeScore, zoneWeighting=2.0/3.0).
"""

from __future__ import annotations

from ...api import meta
from ...api.labels import Selector, selector_from_dict, selector_from_match_labels
from ...client.clientset import (
    REPLICASETS, REPLICATIONCONTROLLERS, SERVICES, STATEFULSETS,
)
from ..framework import MAX_NODE_SCORE, PreScorePlugin, ScorePlugin
from ..types import SKIP, ClusterEvent, Status

_STATE_KEY = "SelectorSpread/selectors"
ZONE_LABEL = "topology.kubernetes.io/zone"
ZONE_WEIGHT = 2.0 / 3.0


class SelectorSpread(PreScorePlugin, ScorePlugin):
    name = "SelectorSpread"

    def __init__(self, informer_factory=None):
        self.factory = informer_factory

    def events_to_register(self):
        return [ClusterEvent("Pod", "*"), ClusterEvent("Node", "*"),
                ClusterEvent("Service", "*"), ClusterEvent("ReplicaSet", "*")]

    def _selectors_for(self, pod: dict) -> list[Selector]:
        """helper.DefaultSelector: selectors of every object selecting pod."""
        if self.factory is None:
            return []
        ns = meta.namespace(pod)
        labels = meta.labels(pod) or {}
        out: list[Selector] = []
        for svc in self.factory.informer(SERVICES).list(ns):
            sel = selector_from_match_labels(
                (svc.get("spec") or {}).get("selector"))
            if not sel.is_empty() and sel.matches(labels):
                out.append(sel)
        for rc in self.factory.informer(REPLICATIONCONTROLLERS).list(ns):
            sel = selector_from_match_labels(
                (rc.get("spec") or {}).get("selector"))
            if not sel.is_empty() and sel.matches(labels):
                out.append(sel)
        for res in (REPLICASETS, STATEFULSETS):
            for obj in self.factory.informer(res).list(ns):
                sel = selector_from_dict((obj.get("spec") or {}).get("selector"))
                if not sel.is_empty() and sel.matches(labels):
                    out.append(sel)
        return out

    def pre_score(self, state, pod_info, nodes):
        selectors = self._selectors_for(pod_info.pod)
        if not selectors:
            return Status(SKIP)
        state.write(_STATE_KEY, selectors)
        return None

    def score(self, state, pod_info, node_info):
        selectors: list[Selector] | None = state.read(_STATE_KEY)
        if not selectors:
            return 0, None
        ns = meta.namespace(pod_info.pod)
        count = 0
        for pi in node_info.pods:
            if meta.namespace(pi.pod) != ns:
                continue
            labels = meta.labels(pi.pod) or {}
            if any(s.matches(labels) for s in selectors):
                count += 1
        return count, None

    def normalize_scores(self, state, pod_info, scores):
        selectors: list[Selector] | None = state.read(_STATE_KEY)
        if not selectors:
            return None
        # raw scores are match counts; fold in zone counts then invert
        zones: dict[str, int] = {}
        node_zone: dict[str, str] = {}
        if self.factory is not None:
            for node in self.factory.informer("nodes").list():
                zone = (meta.labels(node) or {}).get(ZONE_LABEL)
                if zone:
                    node_zone[meta.name(node)] = zone
        for name, cnt in scores.items():
            zone = node_zone.get(name)
            if zone:
                zones[zone] = zones.get(zone, 0) + cnt
        max_node = max(scores.values(), default=0)
        max_zone = max(zones.values(), default=0)
        for name in scores:
            node_score = (MAX_NODE_SCORE * (max_node - scores[name]) / max_node
                          if max_node > 0 else MAX_NODE_SCORE)
            zone = node_zone.get(name)
            if zone and max_zone > 0:
                zone_score = MAX_NODE_SCORE * (max_zone - zones[zone]) / max_zone
                node_score = (1 - ZONE_WEIGHT) * node_score + \
                    ZONE_WEIGHT * zone_score
            scores[name] = int(node_score)
        return None
