"""VolumeBinding — PVC/PV matching and dynamic-provisioning gating.

Reference: pkg/scheduler/framework/plugins/volumebinding/ (2,310 LoC; the
largest in-tree plugin).  Semantics reproduced:
  * PreFilter collects the pod's PVCs and classifies them bound /
    unbound-delayed (StorageClass WaitForFirstConsumer) / unbound-immediate
    (volume_binding.go PreFilter + binder.go GetPodVolumeClaims).
  * a pod with unbound IMMEDIATE-binding PVCs is unschedulable until the PV
    controller binds them (volume_binding.go:227).
  * Filter checks bound PVs' node affinity against the node and, for
    delayed-binding PVCs, finds a matching available PV (size, class,
    access modes, node affinity, unclaimed) or accepts the node if the
    class can dynamically provision (binder.go FindPodVolumes).
  * Reserve assumes the chosen PV bindings in an in-memory cache
    (binder.go AssumePodVolumes); Unreserve drops them.
  * PreBind writes the bindings through the API — PV.claimRef +
    PVC.volumeName for static matches, the selected-node annotation for
    dynamic provisioning (binder.go BindPodVolumes).

The tpu-batch path routes pods with PVCs through this per-pod oracle path
(they are rare in scheduling-throughput terms and deeply stateful).
"""

from __future__ import annotations

import threading

from ...api import meta
from ...api.quantity import parse_quantity
from ...client.clientset import PVCS, PVS, STORAGECLASSES
from ..framework import (
    FilterPlugin, PreBindPlugin, PreFilterPlugin, ReservePlugin,
)
from ..types import (
    ERROR, SKIP, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE,
    ClusterEvent, Status, _compile_node_selector_term,
    node_selector_terms_match,
)

SELECTED_NODE_ANNOTATION = "volume.kubernetes.io/selected-node"
NO_PROVISIONER = "kubernetes.io/no-provisioner"

_STATE_KEY = "VolumeBinding/state"


class _PodVolumeState:
    __slots__ = ("bound_pvcs", "delayed_pvcs", "bindings_by_node")

    def __init__(self):
        self.bound_pvcs: list[dict] = []
        self.delayed_pvcs: list[dict] = []
        # node -> list of (pvc, pv_or_None)  (None => dynamic provisioning)
        self.bindings_by_node: dict[str, list[tuple[dict, dict | None]]] = {}


def pod_pvc_names(pod: dict) -> list[str]:
    out = []
    for v in (pod.get("spec") or {}).get("volumes") or ():
        claim = (v.get("persistentVolumeClaim") or {}).get("claimName")
        if claim:
            out.append(claim)
    return out


def pv_node_affinity_matches(pv: dict, node: dict) -> bool:
    """pv.spec.nodeAffinity.required vs node labels (volume_binding checks
    via CheckNodeAffinity, k8s.io/component-helpers)."""
    affinity = ((pv.get("spec") or {}).get("nodeAffinity") or {}).get("required")
    if not affinity:
        return True
    terms = [_compile_node_selector_term(t)
             for t in affinity.get("nodeSelectorTerms") or ()]
    return node_selector_terms_match(terms, node)


def _pvc_request(pvc: dict) -> float:
    req = (((pvc.get("spec") or {}).get("resources") or {})
           .get("requests") or {}).get("storage", "0")
    return parse_quantity(req)


def _pv_capacity(pv: dict) -> float:
    cap = ((pv.get("spec") or {}).get("capacity") or {}).get("storage", "0")
    return parse_quantity(cap)


def _access_modes_ok(pvc: dict, pv: dict) -> bool:
    want = set((pvc.get("spec") or {}).get("accessModes") or ())
    have = set((pv.get("spec") or {}).get("accessModes") or ())
    return want.issubset(have)


class VolumeBinding(PreFilterPlugin, FilterPlugin, ReservePlugin, PreBindPlugin):
    name = "VolumeBinding"
    # Reserve/PreBind act ONLY on CycleState written by this plugin's own
    # PreFilter (st is None -> immediate no-op).  The batch tail uses this
    # to prove the whole hook loop is skippable for batch-path pods, whose
    # CycleState is always empty (scheduler.Framework.batch_tail_trivial).
    state_gated = True

    def __init__(self, client=None, informer_factory=None,
                 bind_timeout: float = 30.0):
        self.client = client
        self.factory = informer_factory
        # binder.go bindTimeout: how long PreBind waits for the PV
        # controller / provisioner to complete the bindings it requested
        self.bind_timeout = bind_timeout
        self._lock = threading.Lock()
        # pv name -> pvc key it's assumed for (binder.go assumed cache)
        self._assumed: dict[str, str] = {}

    def events_to_register(self):
        return [ClusterEvent("PersistentVolumeClaim", "*"),
                ClusterEvent("PersistentVolume", "*"),
                ClusterEvent("StorageClass", "*"),
                ClusterEvent("Node", "*")]

    # -- listers -----------------------------------------------------------

    def _get(self, resource: str, namespace: str, name: str) -> dict | None:
        if self.factory is not None:
            return self.factory.informer(resource).get(namespace, name)
        if self.client is not None:
            try:
                return self.client.get(resource, namespace, name)
            except Exception:
                return None
        return None

    def _list(self, resource: str) -> list[dict]:
        if self.factory is not None:
            return self.factory.informer(resource).list()
        if self.client is not None:
            try:
                return self.client.list(resource)[0]
            except Exception:
                return []
        return []

    def _is_delayed_binding(self, pvc: dict) -> bool:
        cls_name = (pvc.get("spec") or {}).get("storageClassName")
        if not cls_name:
            return False
        cls = self._get(STORAGECLASSES, "", cls_name)
        if cls is None:
            return False
        return cls.get("volumeBindingMode") == "WaitForFirstConsumer"

    def _can_provision(self, pvc: dict) -> bool:
        cls_name = (pvc.get("spec") or {}).get("storageClassName")
        if not cls_name:
            return False
        cls = self._get(STORAGECLASSES, "", cls_name)
        if cls is None:
            return False
        return (cls.get("provisioner") or NO_PROVISIONER) != NO_PROVISIONER

    # -- extension points --------------------------------------------------

    def pre_filter(self, state, pod_info, snapshot):
        names = pod_pvc_names(pod_info.pod)
        if not names:
            return None, Status(SKIP)
        ns = meta.namespace(pod_info.pod)
        st = _PodVolumeState()
        for name in names:
            pvc = self._get(PVCS, ns, name)
            if pvc is None:
                return None, Status(
                    UNSCHEDULABLE_AND_UNRESOLVABLE,
                    f'persistentvolumeclaim "{name}" not found')
            if meta.deletion_timestamp(pvc):
                return None, Status(
                    UNSCHEDULABLE_AND_UNRESOLVABLE,
                    f'persistentvolumeclaim "{name}" is being deleted')
            if (pvc.get("spec") or {}).get("volumeName"):
                st.bound_pvcs.append(pvc)
            elif self._is_delayed_binding(pvc):
                st.delayed_pvcs.append(pvc)
            else:
                # immediate binding is the PV controller's job; wait for it
                return None, Status(
                    UNSCHEDULABLE,
                    "pod has unbound immediate PersistentVolumeClaims")
        state.write(_STATE_KEY, st)
        return None, None

    def filter(self, state, pod_info, node_info):
        st: _PodVolumeState | None = state.read(_STATE_KEY)
        if st is None:
            return None
        node = node_info.node
        for pvc in st.bound_pvcs:
            pv_name = (pvc.get("spec") or {}).get("volumeName")
            pv = self._get(PVS, "", pv_name)
            if pv is None:
                return Status(UNSCHEDULABLE,
                              f'persistentvolume "{pv_name}" not found')
            if not pv_node_affinity_matches(pv, node):
                return Status(
                    UNSCHEDULABLE,
                    "node(s) had volume node affinity conflict")
        if st.delayed_pvcs:
            bindings = self._find_bindings(st.delayed_pvcs, node)
            if bindings is None:
                return Status(UNSCHEDULABLE,
                              "node(s) didn't find available persistent"
                              " volumes to bind")
            st.bindings_by_node[node_info.name] = bindings
        return None

    def _find_bindings(self, pvcs: list[dict], node: dict
                       ) -> list[tuple[dict, dict | None]] | None:
        """binder.go FindPodVolumes: match each delayed PVC to an available
        PV on this node, else fall back to dynamic provisioning."""
        pvs = self._list(PVS)
        with self._lock:
            assumed = dict(self._assumed)
        taken: set[str] = set()
        out: list[tuple[dict, dict | None]] = []
        for pvc in pvcs:
            want_class = (pvc.get("spec") or {}).get("storageClassName")
            need = _pvc_request(pvc)
            best = None
            for pv in pvs:
                nm = meta.name(pv)
                if nm in taken or nm in assumed:
                    continue
                spec = pv.get("spec") or {}
                if spec.get("claimRef"):
                    continue
                if (spec.get("storageClassName") or "") != (want_class or ""):
                    continue
                if not _access_modes_ok(pvc, pv):
                    continue
                if _pv_capacity(pv) < need:
                    continue
                if not pv_node_affinity_matches(pv, node):
                    continue
                # smallest PV that fits (binder.go uses volume util
                # FindMatchingVolume with the same smallest-fit rule)
                if best is None or _pv_capacity(pv) < _pv_capacity(best):
                    best = pv
            if best is not None:
                taken.add(meta.name(best))
                out.append((pvc, best))
            elif self._can_provision(pvc):
                out.append((pvc, None))
            else:
                return None
        return out

    def reserve(self, state, pod_info, node_name):
        st: _PodVolumeState | None = state.read(_STATE_KEY)
        if st is None:
            return None
        with self._lock:
            for pvc, pv in st.bindings_by_node.get(node_name, ()):
                if pv is not None:
                    self._assumed[meta.name(pv)] = meta.namespaced_name(pvc)
        return None

    def unreserve(self, state, pod_info, node_name):
        st: _PodVolumeState | None = state.read(_STATE_KEY)
        if st is None:
            return
        with self._lock:
            for pvc, pv in st.bindings_by_node.get(node_name, ()):
                if pv is not None:
                    self._assumed.pop(meta.name(pv), None)

    def pre_bind(self, state, pod_info, node_name):
        st: _PodVolumeState | None = state.read(_STATE_KEY)
        if st is None or self.client is None:
            return None
        for pvc, pv in st.bindings_by_node.get(node_name, ()):
            ns, name = meta.namespace(pvc), meta.name(pvc)
            try:
                if pv is not None:
                    # static binding: PV.claimRef then PVC.volumeName.
                    # Never stomp a claimRef someone else won — the wait
                    # below detects the mismatch and fails this binding
                    # (the reference's bindAPIUpdate loses the same race
                    # to the PV controller's own binds)
                    def set_claim_ref(obj, pvc=pvc):
                        ref = (obj.get("spec") or {}).get("claimRef") or {}
                        if ref and (ref.get("namespace"),
                                    ref.get("name")) != (
                                meta.namespace(pvc), meta.name(pvc)):
                            return obj
                        obj.setdefault("spec", {})["claimRef"] = {
                            "namespace": meta.namespace(pvc),
                            "name": meta.name(pvc), "uid": meta.uid(pvc)}
                        obj.setdefault("status", {})["phase"] = "Bound"
                        return obj

                    def set_volume_name(obj, pv=pv):
                        obj.setdefault("spec", {})["volumeName"] = meta.name(pv)
                        obj.setdefault("status", {})["phase"] = "Bound"
                        return obj

                    self.client.guaranteed_update(PVS, "", meta.name(pv),
                                                  set_claim_ref)
                    self.client.guaranteed_update(PVCS, ns, name,
                                                  set_volume_name)
                    with self._lock:
                        self._assumed.pop(meta.name(pv), None)
                else:
                    # dynamic provisioning: tell the provisioner where
                    def annotate(obj, node_name=node_name):
                        obj.setdefault("metadata", {}).setdefault(
                            "annotations", {})[SELECTED_NODE_ANNOTATION] = node_name
                        return obj

                    self.client.guaranteed_update(PVCS, ns, name, annotate)
            except Exception as e:  # pragma: no cover - API failure path
                return Status(ERROR, f"binding volumes: {e}")
        # the writes above only REQUEST bindings; the PV controller (and,
        # for dynamic claims, the provisioner) must finish them before the
        # pod may bind (binder.go BindPodVolumes -> checkBindings poll)
        status = self._wait_for_bindings(st, node_name)
        if status is not None:
            self._rollback(st, node_name)
        return status

    def _wait_for_bindings(self, st: "_PodVolumeState",
                           node_name: str) -> Status | None:
        """checkBindings (binder.go:1002): poll until every requested
        binding reports Bound and each PV's claimRef still points at our
        PVC; detect conflicts (someone else took the PV) immediately."""
        import time
        bindings = st.bindings_by_node.get(node_name, ())
        if not bindings:
            return None
        deadline = time.monotonic() + self.bind_timeout
        while True:
            done = True
            for pvc, pv in bindings:
                ns, name = meta.namespace(pvc), meta.name(pvc)
                try:
                    cur = self.client.get(PVCS, ns, name)
                except Exception:
                    return Status(ERROR,
                                  f"pvc {ns}/{name} vanished while binding")
                vol = (cur.get("spec") or {}).get("volumeName")
                phase = (cur.get("status") or {}).get("phase")
                if pv is not None:
                    # static: the PV must still reference our claim
                    try:
                        cur_pv = self.client.get(PVS, "", meta.name(pv))
                    except Exception:
                        return Status(ERROR,
                                      f"pv {meta.name(pv)} vanished "
                                      "while binding")
                    ref = (cur_pv.get("spec") or {}).get("claimRef") or {}
                    if ref and (ref.get("namespace"), ref.get("name")) != \
                            (ns, name):
                        return Status(ERROR,
                                      f"pv {meta.name(pv)} was bound to a "
                                      "different claim")
                if not vol or phase != "Bound":
                    done = False
                    break
            if done:
                return None
            if time.monotonic() >= deadline:
                return Status(ERROR,
                              "timed out waiting for volume binding")
            time.sleep(0.05)

    def _rollback(self, st: "_PodVolumeState", node_name: str) -> None:
        """Failed/timed-out binding: revert what THIS plugin wrote so a
        retry can choose freely (reference: RevertAssumedPodVolumes plus
        leaving durable recovery to the PV controller; we additionally
        clear a still-unbound claim's selected-node annotation so a
        reschedule isn't pinned to the failed node).  Writes guarded by
        ownership checks — a binding that completed meanwhile is left
        alone."""
        for pvc, pv in st.bindings_by_node.get(node_name, ()):
            ns, name = meta.namespace(pvc), meta.name(pvc)
            try:
                if pv is not None:
                    def clear_ref(obj, pvc=pvc):
                        ref = (obj.get("spec") or {}).get("claimRef") or {}
                        if (ref.get("namespace"), ref.get("name")) == \
                                (meta.namespace(pvc), meta.name(pvc)):
                            obj["spec"].pop("claimRef", None)
                            obj.setdefault("status", {})["phase"] = \
                                "Available"
                        return obj
                    self.client.guaranteed_update(PVS, "", meta.name(pv),
                                                  clear_ref)

                    def clear_vol(obj, pv=pv):
                        # we wrote volumeName (and the Bound phase) for
                        # this static binding, so it is ours to revert;
                        # the wait already proved the PV does NOT
                        # reference this claim
                        spec = obj.setdefault("spec", {})
                        if spec.get("volumeName") == meta.name(pv):
                            spec.pop("volumeName", None)
                            obj.setdefault("status", {})["phase"] = "Pending"
                        return obj
                    self.client.guaranteed_update(PVCS, ns, name, clear_vol)
                else:
                    def deannotate(obj, node_name=node_name):
                        if (obj.get("status") or {}).get("phase") == "Bound":
                            return obj  # provisioning completed: keep it
                        anns = (obj.get("metadata") or {}).get(
                            "annotations") or {}
                        if anns.get(SELECTED_NODE_ANNOTATION) == node_name:
                            anns.pop(SELECTED_NODE_ANNOTATION, None)
                        return obj
                    self.client.guaranteed_update(PVCS, ns, name, deannotate)
            except Exception:  # noqa: BLE001 — rollback is best effort
                pass
        with self._lock:
            for pvc, pv in st.bindings_by_node.get(node_name, ()):
                if pv is not None:
                    self._assumed.pop(meta.name(pv), None)
