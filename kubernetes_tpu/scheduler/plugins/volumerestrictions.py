"""VolumeRestrictions — inline-volume conflicts and ReadWriteOncePod.

Reference: pkg/scheduler/framework/plugins/volumerestrictions/ (215 LoC):
  * two pods on one node may not use the same GCEPersistentDisk unless both
    mount it read-only; same for AWS EBS (also rejects any double use) and
    AzureDisk; ISCSI same-target conflicts unless both read-only
    (volume_restrictions.go isVolumeConflict).
  * a PVC with the ReadWriteOncePod access mode may be used by at most one
    pod in the cluster; PreFilter rejects the pod if any existing pod
    already uses the claim (volume_restrictions.go CheckReadWriteOncePod).
"""

from __future__ import annotations

from ...api import meta
from ...client.clientset import PVCS
from ..framework import FilterPlugin, PreFilterPlugin
from ..types import (
    SKIP, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, ClusterEvent, Status,
)
from .volumebinding import pod_pvc_names

READ_WRITE_ONCE_POD = "ReadWriteOncePod"

_RWOP_STATE_KEY = "VolumeRestrictions/rwop"


def _gce_pd(v: dict):
    d = v.get("gcePersistentDisk")
    return (d.get("pdName"), bool(d.get("readOnly"))) if d else None


def _aws_ebs(v: dict):
    d = v.get("awsElasticBlockStore")
    return (d.get("volumeID"), bool(d.get("readOnly"))) if d else None


def _azure_disk(v: dict):
    d = v.get("azureDisk")
    return (d.get("diskName"), bool(d.get("readOnly"))) if d else None


def _iscsi(v: dict):
    d = v.get("iscsi")
    if not d:
        return None
    return (f"{d.get('targetPortal')}/{d.get('iqn')}/{d.get('lun')}",
            bool(d.get("readOnly")))


def is_volume_conflict(v: dict, existing: dict) -> bool:
    """volume_restrictions.go isVolumeConflict, per volume pair."""
    for extract, ro_allowed in ((_gce_pd, True), (_aws_ebs, False),
                                (_azure_disk, False), (_iscsi, True)):
        a, b = extract(v), extract(existing)
        if a and b and a[0] == b[0]:
            if ro_allowed and a[1] and b[1]:
                continue  # both read-only: GCE PD / ISCSI allow sharing
            return True
    return False


class VolumeRestrictions(PreFilterPlugin, FilterPlugin):
    name = "VolumeRestrictions"

    def __init__(self, informer_factory=None):
        self.factory = informer_factory

    def events_to_register(self):
        return [ClusterEvent("Pod", "Delete"),
                ClusterEvent("PersistentVolumeClaim", "*")]

    def _rwop_claims(self, pod: dict) -> set[str]:
        """Namespaced keys of the pod's PVCs that are ReadWriteOncePod."""
        if self.factory is None:
            return set()
        ns = meta.namespace(pod)
        out = set()
        for name in pod_pvc_names(pod):
            pvc = self.factory.informer(PVCS).get(ns, name)
            if pvc and READ_WRITE_ONCE_POD in (
                    (pvc.get("spec") or {}).get("accessModes") or ()):
                out.add(f"{ns}/{name}")
        return out

    def pre_filter(self, state, pod_info, snapshot):
        has_inline = any(
            _gce_pd(v) or _aws_ebs(v) or _azure_disk(v) or _iscsi(v)
            for v in (pod_info.pod.get("spec") or {}).get("volumes") or ())
        rwop = self._rwop_claims(pod_info.pod)
        if rwop:
            # cluster-wide uniqueness: any existing pod using the claim wins
            for ni in snapshot.node_info_list:
                for key in rwop:
                    if ni.pvc_ref_counts.get(key, 0) > 0:
                        return None, Status(
                            UNSCHEDULABLE,
                            "pod uses a ReadWriteOncePod"
                            " PersistentVolumeClaim that is already in use")
        if not has_inline:
            return None, Status(SKIP)
        return None, None

    def filter(self, state, pod_info, node_info):
        volumes = (pod_info.pod.get("spec") or {}).get("volumes") or ()
        for existing_pi in node_info.pods:
            for ev in (existing_pi.pod.get("spec") or {}).get("volumes") or ():
                for v in volumes:
                    if is_volume_conflict(v, ev):
                        return Status(
                            UNSCHEDULABLE_AND_UNRESOLVABLE,
                            "node has conflicting volumes in use")
        return None
