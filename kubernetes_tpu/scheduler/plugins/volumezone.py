"""VolumeZone — bound PVs must live in the node's zone/region.

Reference: pkg/scheduler/framework/plugins/volumezone/ (206 LoC):
for each of the pod's bound PVCs, the PV's zone/region labels (both the GA
topology.kubernetes.io/* and legacy failure-domain.beta.kubernetes.io/*
keys) must be satisfied by the node's labels; zone label values may be
comma-separated sets (volume_zone.go Filter).
"""

from __future__ import annotations

from ...api import meta
from ...client.clientset import PVCS, PVS
from ..framework import FilterPlugin, PreFilterPlugin
from ..types import SKIP, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE, ClusterEvent, Status
from .volumebinding import pod_pvc_names

ZONE_LABELS = (
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
)


class VolumeZone(PreFilterPlugin, FilterPlugin):
    name = "VolumeZone"

    def __init__(self, informer_factory=None):
        self.factory = informer_factory

    def events_to_register(self):
        return [ClusterEvent("PersistentVolumeClaim", "*"),
                ClusterEvent("PersistentVolume", "*"),
                ClusterEvent("Node", "*")]

    def pre_filter(self, state, pod_info, snapshot):
        if not pod_pvc_names(pod_info.pod):
            return None, Status(SKIP)
        return None, None

    def filter(self, state, pod_info, node_info):
        if self.factory is None:
            return None
        ns = meta.namespace(pod_info.pod)
        node_labels = meta.labels(node_info.node) or {}
        for claim in pod_pvc_names(pod_info.pod):
            pvc = self.factory.informer(PVCS).get(ns, claim)
            if pvc is None:
                return Status(UNSCHEDULABLE,
                              f'persistentvolumeclaim "{claim}" not found')
            pv_name = (pvc.get("spec") or {}).get("volumeName")
            if not pv_name:
                continue  # unbound: VolumeBinding's problem, not ours
            pv = self.factory.informer(PVS).get("", pv_name)
            if pv is None:
                return Status(UNSCHEDULABLE,
                              f'persistentvolume "{pv_name}" not found')
            for key, val in (meta.labels(pv) or {}).items():
                if key not in ZONE_LABELS:
                    continue
                # PV zone values may be comma-separated sets (volume_zone.go)
                allowed = {z.strip() for z in val.split(",")}
                if node_labels.get(key) not in allowed:
                    return Status(
                        UNSCHEDULABLE_AND_UNRESOLVABLE,
                        "node(s) had no available volume zone")
        return None
