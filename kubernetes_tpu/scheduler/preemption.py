"""Preemption evaluator.

Reference: pkg/scheduler/framework/preemption/preemption.go
  Evaluator.Preempt (:146): eligibility -> findCandidates (:206) ->
  SelectCandidate (:307) -> prepareCandidate (evict victims, nominate).
  DryRunPreemption (:579): per candidate node, remove lower-priority pods
  until the pod fits, then re-add as many victims as possible
  (highest-priority first) while it still fits — minimizing disruption.
  Candidate order: fewest PDB violations, then highest victim priority
  lowest, then smallest priority sum, then fewest victims
  (pickOneNodeForPreemption).

PodDisruptionBudget accounting is the minimal faithful subset: a victim
covered by a PDB with disruptionsAllowed <= 0 counts as a violation.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

from ..api import meta
from ..api.labels import selector_from_dict
from ..api.meta import Obj
from ..client.clientset import PDBS, PODS, Client
from .cache import Snapshot
from .framework import CycleState, Framework
from .types import (
    SUCCESS, UNSCHEDULABLE, UNSCHEDULABLE_AND_UNRESOLVABLE,
    NodeInfo, PodInfo, Status, is_success,
)

logger = logging.getLogger(__name__)


@dataclass
class Candidate:
    node_name: str
    victims: list[PodInfo] = field(default_factory=list)
    num_pdb_violations: int = 0


def evict_victims(client: Client, victims: list[PodInfo],
                  preemptor_key: str, node_name: str) -> None:
    """THE eviction site (prepareCandidate's delete+event loop).  Both
    the per-pod Evaluator and the batched bulk-commit path route here —
    a static check (tests/test_verify_static.py) pins that no other
    scheduler code issues pod deletes, so preemption accounting
    (events, metrics, victim dedup) can never fork."""
    for v in victims:
        try:
            client.delete(PODS, meta.namespace(v.pod), meta.name(v.pod))
            client.create_event(
                v.pod, "Preempted",
                f"Preempted by {preemptor_key} on node {node_name}")
        except Exception as e:  # noqa: BLE001 - victim may be gone already
            logger.info("preemption: victim %s delete failed: %s", v.key, e)


class Evaluator:
    def __init__(self, framework: Framework, client: Client,
                 observer=None):
        self.fw = framework
        self.client = client
        # observer(victim_count) — feeds preemption_attempts_total /
        # preemption_victims (metrics.go preemption counters)
        self.observer = observer

    # -- entry (preemption.go:146) ---------------------------------------

    def preempt(self, state: CycleState, pod_info: PodInfo,
                node_statuses: dict[str, Status], snapshot: Snapshot
                ) -> tuple[str | None, Status]:
        if not self._pod_eligible(pod_info, snapshot):
            return None, Status(UNSCHEDULABLE, "pod is not eligible for preemption")
        candidates = self.find_candidates(state, pod_info, node_statuses, snapshot)
        if not candidates:
            return None, Status(UNSCHEDULABLE, "no preemption candidates")
        best = self.select_candidate(candidates)
        status = self._prepare_candidate(best, pod_info)
        if not is_success(status):
            return None, status
        if self.observer is not None:
            self.observer(len(best.victims))
        return best.node_name, Status(SUCCESS)

    def preempt_among(self, state: CycleState, pod_info: PodInfo,
                      node_infos: list[NodeInfo], snapshot: Snapshot
                      ) -> tuple[str | None, Status]:
        """preempt() restricted to a pre-filtered candidate node list —
        the host tail of the batched TPU preemption path (the device
        already proved these nodes resource-feasible after victim
        removal; the exact reprieve/PDB dry-run still runs here, so
        victim selection semantics match the per-pod path)."""
        if not self._pod_eligible(pod_info, snapshot):
            return None, Status(UNSCHEDULABLE, "pod is not eligible for preemption")
        pdbs = self._list_pdbs(meta.namespace(pod_info.pod))
        candidates = []
        for ni in node_infos:
            cand = self._dry_run_on_node(state, pod_info, ni, pdbs)
            if cand is not None:
                candidates.append(cand)
        if not candidates:
            return None, Status(UNSCHEDULABLE, "no preemption candidates")
        best = self.select_candidate(candidates)
        status = self._prepare_candidate(best, pod_info)
        if not is_success(status):
            return None, status
        if self.observer is not None:
            self.observer(len(best.victims))
        return best.node_name, Status(SUCCESS)

    def _pod_eligible(self, pod_info: PodInfo, snapshot: Snapshot) -> bool:
        """podEligibleToPreemptOthers: if the pod already nominated a node
        and a victim there is still terminating, wait instead of preempting
        again."""
        nom = pod_info.nominated_node_name
        if nom:
            ni = snapshot.get(nom)
            if ni is not None:
                for pi in ni.pods:
                    if (meta.deletion_timestamp(pi.pod) is not None
                            and pi.priority < pod_info.priority):
                        return False
        preemption_policy = (pod_info.pod.get("spec") or {}).get(
            "preemptionPolicy", "PreemptLowerPriority")
        return preemption_policy != "Never"

    # -- candidates (preemption.go:206,579) ------------------------------

    def find_candidates(self, state: CycleState, pod_info: PodInfo,
                        node_statuses: dict[str, Status],
                        snapshot: Snapshot) -> list[Candidate]:
        pdbs = self._list_pdbs(meta.namespace(pod_info.pod))
        out: list[Candidate] = []
        for ni in snapshot.list():
            st = node_statuses.get(ni.name)
            # nodes that failed UnschedulableAndUnresolvable can't be fixed
            # by preemption (:225 nodesWherePreemptionMightHelp)
            if st is not None and st.code == UNSCHEDULABLE_AND_UNRESOLVABLE:
                continue
            cand = self._dry_run_on_node(state, pod_info, ni, pdbs)
            if cand is not None:
                out.append(cand)
        return out

    def _dry_run_on_node(self, state: CycleState, pod_info: PodInfo,
                         ni: NodeInfo, pdbs: list[tuple]) -> Candidate | None:
        """selectVictimsOnNode: remove ALL lower-priority pods, check fit,
        then re-add (highest priority first, PDB-violating last) while the
        pod still fits."""
        node_copy = ni.clone()
        state_copy = state.clone()
        potential = [pi for pi in ni.pods if pi.priority < pod_info.priority]
        if not potential:
            return None
        for v in potential:
            self._remove_pod(state_copy, pod_info, v, node_copy)
        # the fit checks run WITH nominated pods (defaultpreemption.go
        # SelectVictimsOnNode -> RunFilterPluginsWithNominatedPods):
        # an equal-or-higher-priority pod already nominated onto this
        # node claims its capacity, so two preemptors in one failed
        # batch cannot both nominate the same slot and cascade into
        # repeat preemption rounds (observed: 3x preemption attempts
        # per pod and a 37% escape storm before this)
        filter_fn = self.fw.run_filter_plugins_with_nominated_pods
        if not is_success(filter_fn(state_copy, pod_info, node_copy)):
            return None

        violating, non_violating = [], []
        for v in potential:
            (violating if self._violates_pdb(v, pdbs) else non_violating).append(v)
        victims: list[PodInfo] = []
        num_violations = 0

        def reprieve(v: PodInfo, counts_violation: bool) -> None:
            nonlocal num_violations
            self._add_pod(state_copy, pod_info, v, node_copy)
            if is_success(filter_fn(state_copy, pod_info, node_copy)):
                return  # pod still fits with v back -> v is spared
            self._remove_pod(state_copy, pod_info, v, node_copy)
            victims.append(v)
            if counts_violation:
                num_violations += 1

        for v in sorted(violating, key=lambda p: -p.priority):
            reprieve(v, True)
        for v in sorted(non_violating, key=lambda p: -p.priority):
            reprieve(v, False)
        if not victims:
            return None
        return Candidate(ni.name, victims, num_violations)

    def _remove_pod(self, state, pod_info, victim, node_info):
        node_info.remove_pod(victim.pod)
        for p in self.fw.pre_filter:
            p.remove_pod(state, pod_info, victim, node_info)

    def _add_pod(self, state, pod_info, victim, node_info):
        node_info.add_pod(victim)
        for p in self.fw.pre_filter:
            p.add_pod(state, pod_info, victim, node_info)

    # -- selection (preemption.go:307 pickOneNodeForPreemption) ----------

    @staticmethod
    def select_candidate(candidates: list[Candidate]) -> Candidate:
        def key(c: Candidate):
            highest = max((v.priority for v in c.victims), default=0)
            prio_sum = sum(v.priority for v in c.victims)
            return (c.num_pdb_violations, highest, prio_sum, len(c.victims))
        return min(candidates, key=key)

    # -- prepare (evict + nominate) --------------------------------------

    def _prepare_candidate(self, cand: Candidate, pod_info: PodInfo) -> Status:
        evict_victims(self.client, cand.victims, pod_info.key, cand.node_name)
        return Status(SUCCESS)

    # -- PDBs ------------------------------------------------------------

    def _list_pdbs(self, namespace: str) -> list[tuple]:
        try:
            items, _ = self.client.list(PDBS, namespace)
        except Exception:  # noqa: BLE001
            return []
        out = []
        for pdb in items:
            spec = pdb.get("spec") or {}
            sel = selector_from_dict(spec.get("selector") or {})
            allowed = (pdb.get("status") or {}).get("disruptionsAllowed", 0)
            out.append((sel, allowed))
        return out

    @staticmethod
    def _violates_pdb(victim: PodInfo, pdbs: list[tuple]) -> bool:
        return any(sel.matches(victim.labels) and allowed <= 0
                   for sel, allowed in pdbs)
