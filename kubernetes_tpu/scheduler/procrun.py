"""Process-true scale-out: supervisor for N scheduler OS processes.

Reference analog: cmd/kube-scheduler as a separate binary per replica with
--leader-elect=false (the Omega-style multi-scheduler deployment), plus
test/integration/util's StartApiserver — separate processes wired only
through the apiserver, never through shared memory.

PR 7 built the scale-out layer (scheduler/scaleout.py: node-pool-ring
partition, store leases, optimistic compare-and-bind) and PR 9 benched it
— but with every instance in ONE interpreter, so the GIL serialized the
host work and 4 instances bought 1.32x.  This module makes the topology
process-true:

  ProcCluster   spawns `python -m kubernetes_tpu.cmd.apiserver` plus N
                scheduler children (`python -m
                kubernetes_tpu.scheduler.procrun --child`), each a FULL
                scheduler: its own informers over HTTP, its own backend,
                its own Lease — configured purely through the existing
                `scaleOut:` stanza.  Readiness is a stdout handshake
                (KTPU_SCHED_READY line) + a per-child /readyz (503 while
                draining or lease-fenced); /healthz is pure process
                liveness.  rolling_restart() composes drain/respawn/
                readiness into the zero-downtime upgrade.
  child_main    the child entrypoint: SIGTERM triggers a graceful drain
                (retire the lease -> fence binds -> flush/requeue ->
                exit 0); SIGKILL is the crash path the churn chaos uses
                (ops/faults.ProcessChurner).
  WireBindLedger  the cross-process double-bind detector: tails the
                apiserver's pod watch from rv=0 and records every
                nodeName a pod key has EVER carried.

bench.py --processes N drives ProcCluster and federates the children's
/metrics text (component_base/profiling.federate_texts) into one
BENCH_SCALEOUT_PROC row.
"""

from __future__ import annotations

import argparse
import http.server
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

logger = logging.getLogger(__name__)

READY_PREFIX = "KTPU_SCHED_READY"
_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# -- child ----------------------------------------------------------------


class _ChildHTTP(http.server.BaseHTTPRequestHandler):
    """Per-child observability endpoint: /metrics (Prometheus text the
    supervisor federates), /healthz (pure liveness: the process is up
    and serving — restart probes key off this) and /readyz (readiness:
    503 while draining or lease-fenced, so a rolling upgrade skips the
    instance without a liveness probe killing it mid-drain)."""

    sched = None  # class attribute, set per server instance below

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        sched = self.server.sched  # type: ignore[attr-defined]
        if self.path == "/metrics":
            body = sched.expose_metrics().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
        elif self.path == "/healthz":
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
        elif self.path.startswith("/debug/timeline"):
            # per-child wave timeline (the supervisor federates these);
            # ?format=chrome serves a Perfetto-loadable trace
            import json as _json

            from ..component_base import timeline as cb_timeline
            tl = cb_timeline.default_timeline
            body = (_json.dumps(tl.to_chrome_trace())
                    if "chrome" in self.path
                    else tl.debug_json()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        elif self.path == "/readyz":
            so = sched.scaleout
            draining = getattr(self.server, "draining", False)
            fenced = so is not None and not so.self_live
            ok = not draining and not fenced
            body = (b"ok" if ok
                    else b"draining" if draining else b"fenced")
            self.send_response(200 if ok else 503)
            self.send_header("Content-Type", "text/plain")
            # engagement posture rides a header, not the body: probes
            # and the rolling upgrade pin the (status, body) contract
            self.send_header("X-Overload-Engagement",
                             getattr(sched, "overload_engagement", "off"))
        else:
            body = b"not found"
            self.send_response(404)
            self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # pragma: no cover - silence per-request spam
        pass


def _install_race_probes(client) -> None:
    """Test-only bind shims, armed by env (see tests/test_scaleout.py
    cross-process conflict taxonomy):

      KTPU_PROC_BIND_HOLD=<seconds>  delay this child's FIRST bind write,
          opening a compute-before-peer-commit / commit-after window.
      KTPU_PROC_BIND_DIVERT=<node>   rewrite this child's FIRST bind to
          <node> — the peer acting on a divergent partition view.

    Both wrap the live HTTP client, so the raced commit still travels the
    real wire path: bulk 409 rehydration, conflict re-fetch, taxonomy."""
    hold = float(os.environ.get("KTPU_PROC_BIND_HOLD", "0") or 0)
    divert = os.environ.get("KTPU_PROC_BIND_DIVERT", "")
    if not hold and not divert:
        return
    fired: list[bool] = []
    real_bind, real_bind_many = client.bind, client.bind_many

    def bind(pod, node_name, expect_rv=None):
        if not fired:
            fired.append(True)
            if hold:
                time.sleep(hold)
            if divert:
                node_name = divert
        return real_bind(pod, node_name, expect_rv)

    def bind_many(bindings):
        if not fired:
            fired.append(True)
            if hold:
                time.sleep(hold)
            if divert:
                bindings = [(b[0], b[1], divert, *b[3:]) for b in bindings]
        return real_bind_many(bindings)

    client.bind, client.bind_many = bind, bind_many


def child_main(args) -> int:
    """One scheduler instance as an OS process.  Everything it knows
    about the topology comes from the scaleOut: stanza; everything it
    knows about the cluster comes over the wire.

    Zero-downtime hooks: --warm-dir arms checkpointed warm-start (load
    the mirror checkpoint + prime the informers at its resourceVersions
    on boot; write a fresh checkpoint on SIGTERM drain), --config names
    a KubeSchedulerConfiguration whose DYNAMIC stanzas apply at boot and
    re-apply on SIGHUP (Scheduler.reload_config: invalid files are
    rejected with the old config kept live)."""
    from ..client.clientset import NODES, PODS
    from ..client.http_client import HTTPClient
    from ..client.informer import SharedInformerFactory
    from .config import load_config, scheduler_from_config

    logging.basicConfig(
        level=logging.INFO,
        format=f"sched[{args.instance_index}] %(levelname)s %(message)s")
    client = HTTPClient.from_url(args.server, token=args.token or None)
    _install_race_probes(client)
    factory = SharedInformerFactory(client)
    stanza: dict = {"kind": "KubeSchedulerConfiguration",
                    "backend": {"kind": args.backend
                                if args.backend != "none" else "null",
                                "batchSize": args.batch_size}}
    if os.environ.get("KTPU_PROC_TIMELINE") == "1":
        # arm the wave-timeline ring in every child (the supervisor's
        # federated_timeline()/supervisor_metrics_text() read it back
        # over /debug/timeline) — same stanza path a --config file uses
        stanza["profiling"] = {"timeline": True}
    if args.instance_count > 1:
        stanza["scaleOut"] = {
            "instanceCount": args.instance_count,
            "instanceIndex": args.instance_index,
            "ringSlices": max(64, 16 * args.instance_count),
            "leaseDurationSeconds": args.lease_duration,
            "renewIntervalSeconds": args.renew_interval,
        }
    sched = scheduler_from_config(client, factory, load_config(stanza))
    backend = None
    if args.backend != "none":
        # the harness half of the backend: stanza contract — construct
        # the device backend the config named and hang it on the profile
        from ..ops.backend import make_batch_backend
        from ..perf import caps_for_nodes
        backend = make_batch_backend(sched.backend_policy.kind,
                                     caps_for_nodes(max(args.nodes, 256)),
                                     batch_size=args.batch_size)
        backend.warmup()
        profile = next(iter(sched.profiles.values()))
        profile.batch_backend = backend
        profile.batch_size = args.batch_size
        sched.pipeline_depth = 2
    if args.config:
        # boot-time config: same validation as the SIGHUP path; a bad
        # file fails the boot loudly instead of running half-configured
        sched.reload_config(args.config)

    # checkpointed warm-start: install the mirror BEFORE informers start
    # so the primed replay's events land on adoption-pending rows
    warm_path = None
    if args.warm_dir and backend is not None \
            and hasattr(backend, "warm_start"):
        from ..ops.backend import CheckpointError
        warm_path = os.path.join(args.warm_dir,
                                 f"sched-{args.instance_index}.ckpt")
        if os.path.exists(warm_path):
            try:
                warm = backend.warm_start(warm_path)
            except CheckpointError as e:
                logger.warning("checkpoint %s rejected (%s); cold start",
                               warm_path, e)
            else:
                objs = warm.get("objects") or {}
                rvs = warm.get("resource_versions") or {}
                for res in (NODES, PODS):
                    if res in objs and res in rvs:
                        factory.informer(res).prime(objs[res], rvs[res])
                logger.info("warm start: %d rows pending adoption from %s",
                            warm["nodes"], warm_path)

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _ChildHTTP)
    server.sched = sched  # type: ignore[attr-defined]
    server.draining = False  # type: ignore[attr-defined]
    threading.Thread(target=server.serve_forever,
                     name="child-metrics", daemon=True).start()

    stop = threading.Event()
    reload_req = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGHUP, lambda *a: reload_req.set())

    factory.start()
    if not factory.wait_for_cache_sync(60.0):
        logger.error("cache sync timed out; exiting")
        return 1
    if backend is not None and hasattr(backend, "warm_align"):
        # sweep any rows the primed replay's bulk path did not visit
        # (adopt current ones, drop rows whose node died while we were
        # down) so the first wave starts from a fully-reconciled mirror
        backend.warm_align(sched.cache.flatten_view())
    sched.run()
    # readiness handshake: the supervisor tails our stdout for this line
    print(f"{READY_PREFIX} index={args.instance_index} pid={os.getpid()} "
          f"metrics_port={server.server_address[1]}", flush=True)

    while not stop.wait(0.2):
        if reload_req.is_set():
            reload_req.clear()
            if not args.config:
                logger.warning("SIGHUP ignored: no --config file to reload")
                continue
            try:
                outcome = sched.reload_config(args.config)
            except Exception as e:  # noqa: BLE001 - keep old config live
                logger.warning("config reload rejected: %s", e)
            else:
                logger.info("config reloaded: %s", outcome)
    # graceful drain (SIGTERM): fail readiness, then retire the lease
    # FIRST so the bind fence rejects any wave still in flight (nothing
    # new reaches the store), then stop the loop — its shutdown path
    # flushes/requeues in-flight work so peers absorbing our partition
    # find every pod in the store.
    server.draining = True  # type: ignore[attr-defined]
    if sched.scaleout is not None:
        sched.scaleout.retire()
    sched.stop()
    if warm_path is not None:
        # the loop is quiesced and the informers still hold their last
        # applied revisions: cut the warm-start checkpoint the respawned
        # instance resumes from
        try:
            nodes_inf = factory.informer(NODES)
            pods_inf = factory.informer(PODS)
            cut = backend.checkpoint_mirror(
                warm_path, snapshot=sched.cache.flatten_view(),
                resource_versions={NODES: nodes_inf.last_rv,
                                   PODS: pods_inf.last_rv},
                objects={NODES: nodes_inf.list(),
                         PODS: pods_inf.list()})
            logger.info("checkpointed %d rows (%d bytes) to %s",
                        cut["nodes"], cut["bytes"], cut["path"])
        except Exception:  # noqa: BLE001 - drain must still exit 0
            logger.exception("checkpoint write failed; next start is cold")
    factory.stop()
    server.shutdown()
    return 0


# -- supervisor -----------------------------------------------------------


class _Child:
    """One scheduler child: Popen + stdout tail + readiness state."""

    def __init__(self, index: int):
        self.index = index
        self.proc: subprocess.Popen | None = None
        self.metrics_port: int | None = None
        self.ready = threading.Event()
        self.lines: list[str] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def tail(self, n: int = 20) -> list[str]:
        with self._lock:
            return self.lines[-n:]

    def _reader(self, proc: subprocess.Popen) -> None:
        for raw in proc.stdout:  # type: ignore[union-attr]
            line = raw.rstrip("\n")
            with self._lock:
                self.lines.append(line)
                del self.lines[:-200]
            if line.startswith(READY_PREFIX):
                for tok in line.split():
                    if tok.startswith("metrics_port="):
                        self.metrics_port = int(tok.split("=", 1)[1])
                self.ready.set()


class ProcCluster:
    """Supervisor: one apiserver process + N scheduler processes.

    Lifecycle: start() spawns everything and blocks on the readiness
    handshake; kill(i) is the crash path (SIGKILL, no drain — the
    victim's lease lapses and survivors absorb its ring slices);
    drain(i) is the graceful path (SIGTERM -> lease retire -> flush ->
    exit 0); respawn(i) brings an instance back with its old identity.
    rolling_restart() composes them into the zero-downtime upgrade:
    drain -> respawn -> /readyz, one instance at a time, the PR 7 ring
    re-homing each drained instance's slices to survivors meanwhile.
    hot_reload(i) relays SIGHUP (config re-read, requires config_path);
    handoff_apiserver() replaces the apiserver over its WAL (requires
    data_dir).  shutdown() drains every child then the apiserver.
    Context-manager friendly so a failing test can never leak processes
    (tests add the conftest proc_reaper belt on top)."""

    def __init__(self, n_instances: int, *, backend: str = "none",
                 batch_size: int = 1024, nodes: int = 256,
                 lease_duration: float = 1.5, renew_interval: float = 0.25,
                 solo_ownership: bool = False,
                 child_env: dict[int, dict[str, str]] | None = None,
                 ready_timeout: float = 120.0,
                 warm_dir: str | None = None,
                 config_path: str | None = None,
                 data_dir: str | None = None):
        self.n = n_instances
        self.backend = backend
        self.batch_size = batch_size
        self.nodes = nodes
        self.lease_duration = lease_duration
        self.renew_interval = renew_interval
        # solo_ownership: every child runs instanceCount=1 (scale-out
        # layer off) so ALL children own ALL pods — the deliberate-race
        # topology the cross-process conflict tests use
        self.solo = solo_ownership
        self.child_env = child_env or {}
        self.ready_timeout = ready_timeout
        self.warm_dir = warm_dir      # children checkpoint/warm-start here
        self.config_path = config_path  # children re-read this on SIGHUP
        self.data_dir = data_dir      # apiserver WAL dir (handoff needs it)
        self.url: str | None = None
        self.token: str | None = None
        self.drain_escalations = 0  # SIGTERM hangs escalated to SIGKILL
        self._api: subprocess.Popen | None = None
        self._api_port: int | None = None
        self._api_log = None  # captured apiserver stdout/stderr (tempfile)
        self._children: dict[int, _Child] = {}
        self._clients: list = []  # admin HTTPClients handed out

    # -- apiserver --------------------------------------------------------

    def _start_apiserver(self) -> None:
        import secrets

        if self.token is None:
            self.token = secrets.token_urlsafe(16)
        # A fresh start may retry on a new port: _free_port() closes its
        # probe socket before the server rebinds the number, so another
        # process can race it away (EADDRINUSE kills the child before it
        # serves).  A handoff restart gets NO retry — the children hold
        # the old URL, so the replacement must win the same port back.
        fresh = self._api_port is None
        for attempt in range(3 if fresh else 1):
            if fresh:
                self._api_port = _free_port()
            self.url = f"http://127.0.0.1:{self._api_port}"
            # AlwaysAllow + no admission: this supervisor exists to measure
            # the SCHEDULER topology; perf/scheduler_perf.py via_http keeps
            # the RBAC+admission front-door configuration
            argv = [sys.executable, "-m", "kubernetes_tpu.cmd.apiserver",
                    "--secure-port", str(self._api_port),
                    "--token", self.token]
            if self.data_dir:
                argv += ["--data-dir", self.data_dir]
            self._close_api_log()
            self._api_log = tempfile.TemporaryFile(mode="w+",
                                                   encoding="utf-8",
                                                   errors="replace")
            self._api = subprocess.Popen(
                argv, stdout=self._api_log, stderr=subprocess.STDOUT,
                cwd=_REPO_ROOT)
            try:
                self._wait_apiserver_healthy(60.0)
                return
            except RuntimeError:
                died = self._api.poll() is not None
                if not (fresh and died and attempt < 2):
                    self.shutdown()
                    raise
                logger.warning("apiserver died during start (port race?),"
                               " retrying on a fresh port")
                self._api_port = None

    def _api_log_tail(self, limit: int = 2000) -> str:
        log = getattr(self, "_api_log", None)
        if log is None:
            return ""
        try:
            log.seek(0)
            return log.read()[-limit:]
        except (OSError, ValueError):
            return ""

    def _close_api_log(self) -> None:
        log = getattr(self, "_api_log", None)
        if log is not None:
            try:
                log.close()
            except OSError:
                pass
            self._api_log = None

    def _wait_apiserver_healthy(self, timeout: float) -> None:
        from ..client.http_client import HTTPClient
        client = HTTPClient.from_url(self.url, token=self.token)
        deadline = time.monotonic() + timeout
        while True:
            try:
                client._request("GET", "/healthz")
                return
            except Exception:  # noqa: BLE001 - still starting
                died = self._api.poll() is not None
                if died or time.monotonic() > deadline:
                    tail = self._api_log_tail()
                    why = ("apiserver died during start" if died else
                           f"apiserver not healthy after {timeout:.0f}s")
                    if tail:
                        why += f"; last output:\n{tail}"
                    raise RuntimeError(why) from None
                time.sleep(0.1)

    def admin_client(self):
        from ..client.http_client import HTTPClient
        cl = HTTPClient.from_url(self.url, token=self.token)
        self._clients.append(cl)
        return cl

    # -- children ---------------------------------------------------------

    def _spawn(self, index: int) -> _Child:
        child = _Child(index)
        env = dict(os.environ)
        if self.backend in ("none", "null"):
            # host-only children must never touch (or wait on) a device
            env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONUNBUFFERED"] = "1"
        env.update(self.child_env.get(index, {}))
        count = 1 if self.solo else self.n
        argv = [sys.executable, "-m", "kubernetes_tpu.scheduler.procrun",
                "--child", "--server", self.url, "--token", self.token,
                "--instance-index", str(0 if self.solo else index),
                "--instance-count", str(count),
                "--backend", self.backend,
                "--batch-size", str(self.batch_size),
                "--nodes", str(self.nodes),
                "--lease-duration", str(self.lease_duration),
                "--renew-interval", str(self.renew_interval)]
        if self.warm_dir:
            argv += ["--warm-dir", self.warm_dir]
        if self.config_path:
            argv += ["--config", self.config_path]
        child.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, cwd=_REPO_ROOT, env=env)
        threading.Thread(target=child._reader, args=(child.proc,),
                         name=f"child-tail-{index}", daemon=True).start()
        self._children[index] = child
        return child

    def start(self) -> "ProcCluster":
        self._start_apiserver()
        for i in range(self.n):
            self._spawn(i)
        self.wait_ready(range(self.n))
        return self

    def wait_ready(self, indices) -> None:
        deadline = time.monotonic() + self.ready_timeout
        for i in indices:
            child = self._children[i]
            while not child.ready.wait(
                    min(1.0, max(0.0, deadline - time.monotonic()))):
                if child.proc is not None and child.proc.poll() is not None:
                    raise RuntimeError(
                        f"scheduler child {i} exited rc="
                        f"{child.proc.returncode} before READY; tail: "
                        f"{child.tail()}")
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"scheduler child {i} not READY after "
                        f"{self.ready_timeout}s; tail: {child.tail()}")

    def alive(self, index: int) -> bool:
        c = self._children.get(index)
        return (c is not None and c.proc is not None
                and c.proc.poll() is None)

    def live_indices(self) -> list[int]:
        return [i for i in self._children if self.alive(i)]

    def kill(self, index: int) -> None:
        """Crash path: SIGKILL, no drain — the chaos ladder's
        KILL_INSTANCE made process-true."""
        c = self._children.get(index)
        if c is None or c.proc is None:
            return
        try:
            c.proc.kill()
        except OSError:
            pass
        c.proc.wait()
        c.ready.clear()

    def drain(self, index: int, timeout: float = 20.0) -> int | None:
        """Graceful path: SIGTERM -> the child retires its lease, flushes
        in-flight work and exits 0.  Escalates to SIGKILL on a hang —
        recorded in scheduler_proc_drain_escalated_total (see
        supervisor_metrics_text) — so a stuck child can never wedge a
        rolling upgrade: failover proceeds, the victim's lease lapses
        and survivors absorb its slices exactly as on a crash."""
        c = self._children.get(index)
        if c is None or c.proc is None:
            return None
        if c.proc.poll() is None:
            try:
                c.proc.terminate()
            except OSError:
                pass
            try:
                c.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.drain_escalations += 1
                logger.warning(
                    "child %d ignored SIGTERM for %.1fs; escalating to "
                    "SIGKILL (drain_escalations=%d)", index, timeout,
                    self.drain_escalations)
                c.proc.kill()
                c.proc.wait()
        c.ready.clear()
        return c.proc.returncode

    def respawn(self, index: int, wait_ready: bool = True) -> None:
        if self.alive(index):
            return
        self._spawn(index)
        if wait_ready:
            self.wait_ready([index])

    # -- zero-downtime operations ----------------------------------------

    def wait_child_ready(self, index: int, timeout: float = 60.0) -> None:
        """Block until child `index` answers /readyz 200 — the HTTP half
        of readiness on top of the stdout handshake (a fenced or
        draining instance answers 503 there while still live)."""
        import urllib.error
        import urllib.request
        c = self._children[index]
        deadline = time.monotonic() + timeout
        while True:
            if c.metrics_port is not None:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{c.metrics_port}/readyz",
                            timeout=5.0) as resp:
                        if resp.status == 200:
                            return
                except (urllib.error.URLError, OSError):
                    pass
            if not self.alive(index):
                raise RuntimeError(
                    f"child {index} died while waiting for /readyz; "
                    f"tail: {c.tail()}")
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"child {index} not ready after {timeout}s; "
                    f"tail: {c.tail()}")
            time.sleep(0.05)

    def rolling_restart(self, *, drain_timeout: float = 20.0,
                        ready_timeout: float = 60.0) -> list[int]:
        """Zero-downtime upgrade of the scheduler topology: cycle every
        live child through drain -> respawn -> readiness, never taking
        more than one instance out at a time.  During each drain window
        the PR 7 ring re-homes the drained instance's slices to
        survivors (lease retire on SIGTERM), so pods keep binding
        throughout; with warm_dir set each child checkpoints its mirror
        on the way down and warm-starts on the way up.  Returns the
        indices rolled, in order."""
        if self.config_path:
            # Pre-flight the config file: a respawned child fail-fasts on
            # an unparseable --config, so starting the roll would drain a
            # HEALTHY replica and then fail to bring its successor up —
            # the classic bad-config-plus-restart outage.  Refuse before
            # any drain instead (the running children keep their last
            # good config either way).
            from .config import ConfigError, load_config
            try:
                load_config(self.config_path)
            except ConfigError as e:
                raise RuntimeError(
                    f"refusing rolling restart: {self.config_path} would "
                    f"kill respawned children: {e}") from e
        rolled: list[int] = []
        for i in sorted(self._children):
            if not self.alive(i):
                continue
            self.drain(i, timeout=drain_timeout)
            self.respawn(i, wait_ready=True)
            self.wait_child_ready(i, timeout=ready_timeout)
            rolled.append(i)
        return rolled

    def hot_reload(self, index: int | None = None) -> list[int]:
        """Relay SIGHUP to one child (or every live child): each re-reads
        config_path and applies the dynamic stanzas without restarting;
        an invalid file is rejected child-side with the old config kept
        live.  Returns the indices signalled."""
        if not self.config_path:
            raise RuntimeError("hot_reload requires config_path")
        targets = ([index] if index is not None
                   else [i for i in sorted(self._children) if self.alive(i)])
        signalled = []
        for i in targets:
            c = self._children.get(i)
            if c is None or c.proc is None or c.proc.poll() is not None:
                continue
            c.proc.send_signal(signal.SIGHUP)
            signalled.append(i)
        return signalled

    def handoff_apiserver(self, timeout: float = 30.0) -> None:
        """Replace the apiserver process over its durable store: SIGTERM
        the old one (its shutdown fsyncs the WAL), start the replacement
        on the SAME port + token + data dir, and wait for /healthz.  WAL
        recovery restores every object and the revision counter, so the
        children never need repointing: their HTTP clients reconnect
        per-request, and their watches — whose windows died with the old
        process — raise TooOld and relist through the normal recovery
        path.  Requires data_dir (an in-memory store cannot hand off)."""
        if not self.data_dir:
            raise RuntimeError("handoff_apiserver requires data_dir")
        if self._api is not None:
            self._api.terminate()
            try:
                self._api.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self._api.kill()
                self._api.wait()
            self._api = None
        self._start_apiserver()

    def supervisor_metrics_text(self) -> str:
        """Supervisor-side counters in exposition format — appended to
        the children's federated texts by the bench/ops tooling.  These
        are process-management tallies the children cannot see (they are
        the ones being SIGKILLed), plus a per-child idle-share line
        federated from the children's /debug/timeline rings."""
        lines = ["# TYPE scheduler_proc_drain_escalated_total counter",
                 f"scheduler_proc_drain_escalated_total "
                 f"{float(self.drain_escalations)}",
                 "# TYPE scheduler_proc_wave_device_idle_share gauge"]
        for i, doc in sorted(self.timeline_snapshots().items()):
            idle = doc.get("device_idle_share")
            if idle is not None:
                lines.append(f'scheduler_proc_wave_device_idle_share'
                             f'{{instance="{i}"}} {float(idle)}')
        return "\n".join(lines) + "\n"

    def timeline_snapshots(self) -> dict[int, dict]:
        """One /debug/timeline pull per live child: instance index ->
        the child's timeline debug doc (summary + interval rows).  A
        child with the timeline disabled answers with enabled=false and
        empty rows — included so the caller sees the full topology."""
        import json as _json
        import urllib.request
        out: dict[int, dict] = {}
        for i in sorted(self._children):
            c = self._children[i]
            if not self.alive(i) or c.metrics_port is None:
                continue
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{c.metrics_port}"
                        f"/debug/timeline", timeout=10.0) as resp:
                    out[i] = _json.loads(resp.read().decode())
            except (OSError, ValueError):  # died or torn mid-pull: skip
                continue
        return out

    def federated_timeline(self):
        """Merge every live child's interval rows into one supervisor
        Timeline (rows are wall-anchored by each child's own clock, so
        the merge is plain concatenation — same contract as the remote
        seam's worker drain) and return it.  Use .snapshot_summary() for
        the cluster-wide idle share or .to_chrome_trace() for one
        Perfetto doc with per-child process lanes."""
        from ..component_base import timeline as cb_timeline
        tl = cb_timeline.Timeline(
            ring=65536, enabled=True, proc="supervisor")
        for i, doc in sorted(self.timeline_snapshots().items()):
            rows = doc.get("interval_rows") or []
            # re-tag the lane so per-child attribution survives the merge
            tl.ingest([dict(r, proc=f"sched{i}") for r in rows])
        return tl

    def metrics_texts(self) -> list[str]:
        """One /metrics pull per live child — the raw exposition bodies
        component_base/profiling.federate_texts merges (the true
        cross-process federation path PR 8 built the parser for)."""
        import urllib.request
        out = []
        for i in sorted(self._children):
            c = self._children[i]
            if not self.alive(i) or c.metrics_port is None:
                continue
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{c.metrics_port}/metrics",
                        timeout=10.0) as resp:
                    out.append(resp.read().decode())
            except OSError:  # child died mid-pull: skip, don't fail
                continue
        return out

    def shutdown(self) -> None:
        for i in list(self._children):
            try:
                self.drain(i, timeout=10.0)
            except Exception:  # noqa: BLE001 - teardown must not raise
                pass
        if self._api is not None:
            self._api.terminate()
            try:
                self._api.wait(timeout=10.0)
            except subprocess.TimeoutExpired:
                self._api.kill()
                self._api.wait()
            self._api = None
        self._close_api_log()

    def __enter__(self) -> "ProcCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.shutdown()


class WireBindLedger:
    """Cross-process double-bind detector: tails the apiserver's pod
    watch from rv=0 (the store's full event history) and records every
    nodeName a pod key has EVER carried.  A pod bound exactly once has
    one node in its set; a pod two PROCESSES both committed would show
    two — the assertion no amount of in-process mocking can fake."""

    def __init__(self, client):
        self.nodes_seen: dict[str, set[str]] = {}
        # first wall-clock moment this LEDGER saw each pod carry a
        # nodeName — the external observation timestamp the timeline's
        # per-pod `watch` segment is stitched from
        # (component_base/timeline.stitch_watch_segments)
        self.observed_at: dict[str, float] = {}
        from ..client.clientset import PODS
        self._pods = PODS
        self._client = client
        self._watch = client.watch(PODS, since_rv=0)

    def _record(self, obj) -> None:
        md = obj.get("metadata") or {}
        key = f"{md.get('namespace')}/{md.get('name')}"
        node = (obj.get("spec") or {}).get("nodeName")
        if node:
            self.nodes_seen.setdefault(key, set()).add(node)
            self.observed_at.setdefault(key, time.time())

    def _rearm(self) -> None:
        """The streaming watch EOFs when the apiserver hands off to a
        WAL-recovered replacement.  Re-arm against the successor: rv=0
        replay when the history still reaches back that far, else LIST
        (each pod's current nodeName is still a bind record) and watch
        from the list revision — reflector.go's relist-on-TooOld,
        applied to the test oracle.  A refused connection (mid-handoff
        gap) leaves the ledger stopped; the next drain retries."""
        from ..store import kv
        try:
            self._watch = self._client.watch(self._pods, since_rv=0)
            return
        except kv.TooOldError:
            pass
        except OSError:
            return
        try:
            items, rv = self._client.list(self._pods)
            for obj in items:
                self._record(obj)
            self._watch = self._client.watch(self._pods, since_rv=rv)
        except (kv.TooOldError, OSError):
            return

    def drain(self, timeout: float = 0.05):
        if getattr(self._watch, "stopped", False):
            self._rearm()
        for ev in self._watch.next_batch(timeout=timeout):
            self._record(ev.object)
        return self.nodes_seen

    def bound_total(self) -> int:
        self.drain()
        return len(self.nodes_seen)

    def assert_no_double_binds(self) -> None:
        self.drain()
        moved = {k: v for k, v in self.nodes_seen.items() if len(v) > 1}
        assert not moved, f"pods bound to more than one node: {moved}"

    def stop(self) -> None:
        self._watch.stop()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(prog="ktpu-procrun")
    ap.add_argument("--child", action="store_true",
                    help="run as one scheduler instance (supervisor use)")
    ap.add_argument("--server", default="http://127.0.0.1:8080")
    ap.add_argument("--token", default="")
    ap.add_argument("--instance-index", type=int, default=0)
    ap.add_argument("--instance-count", type=int, default=1)
    ap.add_argument("--backend", default="none",
                    choices=["none", "null", "tpu", "sharded"],
                    help="batch backend kind; none = per-pod host path")
    ap.add_argument("--batch-size", type=int, default=1024)
    ap.add_argument("--nodes", type=int, default=256,
                    help="expected node count (backend capacity sizing)")
    ap.add_argument("--lease-duration", type=float, default=1.5)
    ap.add_argument("--renew-interval", type=float, default=0.25)
    ap.add_argument("--warm-dir", default="",
                    help="checkpoint dir: write the mirror checkpoint on "
                         "drain, warm-start from it on boot")
    ap.add_argument("--config", default="",
                    help="KubeSchedulerConfiguration file whose dynamic "
                         "stanzas apply at boot and re-apply on SIGHUP")
    args = ap.parse_args(argv)
    if not args.child:
        ap.error("supervisor mode is library-only: use ProcCluster; "
                 "--child is the process entrypoint")
    sys.exit(child_main(args))


if __name__ == "__main__":
    main()
